"""Always-on span tracer: per-height consensus timelines + device-
pipeline stage attribution.

Round 5's verdict left one open axis: the headline end-to-end number is
relay-wire-bound (4.3x device exec) and the aggregate histograms in
metrics.py cannot say where the other ~130 ms goes. This module is the
instrument for that question — monotonic-clock spans with parent/child
links over the hot paths:

  consensus.height                     one root span per height
    consensus.propose / .prevote / .precommit / .commit ...
      wal.fsync                        every write_sync
      state.apply_block                ApplyBlock wall time
        crypto.batch                   a BatchVerifier.verify call
          crypto.verify                one device verify
            crypto.pack                host byte packing (numpy)
            crypto.dispatch            kernel-launch enqueue
            crypto.device_exec         wait-until-verdicts-ready
            crypto.readback            device->host verdict copy
  p2p.send_flush / p2p.recv_msg        wire-side attribution

Design constraints (this stays ON in production):

  * Fixed-size ring buffer (collections.deque(maxlen=N), default 16k
    spans): ending a span is one tuple append; overflow evicts the
    oldest — memory is bounded no matter the load.
  * time.perf_counter_ns() start/stop; no datetime, no wall clock.
  * Task-local context via contextvars: asyncio tasks inherit the
    active span automatically. Executor threads do NOT (run_in_executor
    ignores the caller's Context), so cross-thread parenting is an
    EXPLICIT handoff: `loop.run_in_executor(None, TRACER.wrap(fn), ...)`
    captures the caller's active span and re-activates it inside the
    worker thread. This is how a crypto.verify span recorded in the
    BatchVerifier executor thread still parents under the event loop's
    consensus span.
  * Span kinds are a closed registry: every instrumented site names a
    constant registered here (tools/check_spans.py lints for ad-hoc
    string literals). An unregistered kind raises at span start — a
    typo'd kind is a programming error, not a silent new timeline row.

Export: chrome_trace() renders the ring as Chrome trace-event JSON
("X" complete events) loadable in Perfetto / chrome://tracing; served
at GET /debug/trace?seconds=N (libs/debugsrv.py), captured by
`tendermint-tpu debug trace` (cmd/debug.py), and rolled up per-kind
(p50/p95/p99) into bench.py's BENCH_*.json stage_breakdown field.
"""

from __future__ import annotations

import contextvars
import itertools
import os
import struct
import threading
import time
from collections import deque
from typing import NamedTuple

# ---------------------------------------------------------------- registry

_KINDS: set[str] = set()


def register_kind(name: str) -> str:
    """Register a span kind. Instrumented modules use the constants
    below; tests may register their own (namespaced `test.*`)."""
    _KINDS.add(name)
    return name


def registered_kinds() -> frozenset[str]:
    return frozenset(_KINDS)


# Consensus timeline (one root per height; step children follow
# consensus/cstypes.py RoundStep names via consensus_step_kind()).
CONSENSUS_HEIGHT = register_kind("consensus.height")
CONSENSUS_PROPOSE = register_kind("consensus.propose")
CONSENSUS_PREVOTE = register_kind("consensus.prevote")
CONSENSUS_PREVOTE_WAIT = register_kind("consensus.prevote_wait")
CONSENSUS_PRECOMMIT = register_kind("consensus.precommit")
CONSENSUS_PRECOMMIT_WAIT = register_kind("consensus.precommit_wait")
CONSENSUS_COMMIT = register_kind("consensus.commit")
CONSENSUS_NEW_ROUND = register_kind("consensus.new_round")
CONSENSUS_VOTE_BATCH = register_kind("consensus.vote_batch")

_STEP_KINDS = {
    "PROPOSE": CONSENSUS_PROPOSE,
    "PREVOTE": CONSENSUS_PREVOTE,
    "PREVOTE_WAIT": CONSENSUS_PREVOTE_WAIT,
    "PRECOMMIT": CONSENSUS_PRECOMMIT,
    "PRECOMMIT_WAIT": CONSENSUS_PRECOMMIT_WAIT,
    "COMMIT": CONSENSUS_COMMIT,
}


def consensus_step_kind(step_name: str) -> str:
    """RoundStep name -> registered step-span kind (NEW_HEIGHT /
    NEW_ROUND transitions fold into consensus.new_round)."""
    return _STEP_KINDS.get(step_name, CONSENSUS_NEW_ROUND)


# Device pipeline (crypto/batch.py, crypto/tpu/verify.py + expanded.py).
CRYPTO_BATCH = register_kind("crypto.batch")
CRYPTO_VERIFY = register_kind("crypto.verify")
CRYPTO_PACK = register_kind("crypto.pack")
CRYPTO_DISPATCH = register_kind("crypto.dispatch")
CRYPTO_DEVICE_EXEC = register_kind("crypto.device_exec")
CRYPTO_READBACK = register_kind("crypto.readback")
CRYPTO_HOST_VERIFY = register_kind("crypto.host_verify")

# Verify-ahead pipeline (consensus/speculation.py + crypto/tpu/
# resident.py): speculate = an ahead-of-commit verification launch,
# patch = a delta splice into the device-resident arena, reconcile =
# the commit-time serve (template match + miss fallback).
SPECULATION_SPECULATE = register_kind("speculation.speculate")
SPECULATION_PATCH = register_kind("speculation.patch")
SPECULATION_RECONCILE = register_kind("speculation.reconcile")

# State machine + durability + wire.
STATE_APPLY_BLOCK = register_kind("state.apply_block")
WAL_FSYNC = register_kind("wal.fsync")
P2P_SEND_FLUSH = register_kind("p2p.send_flush")
P2P_RECV_MSG = register_kind("p2p.recv_msg")


# ---------------------------------------------------------------- spans

_CURRENT: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "tm_tpu_trace_span", default=None
)

_ids = itertools.count(1)  # CPython: count.__next__ is GIL-atomic


class Span:
    """A live span. end() seals it into the tracer's ring buffer as a
    plain tuple; no reference is kept after that beyond the ring."""

    __slots__ = ("kind", "span_id", "parent_id", "tid", "t0", "attrs",
                 "_tracer", "_done")

    def __init__(self, tracer: "Tracer", kind: str, parent_id: int,
                 attrs: dict | None):
        self.kind = kind
        self.span_id = next(_ids)
        self.parent_id = parent_id
        self.tid = threading.get_ident()
        self.attrs = attrs
        self._tracer = tracer
        self._done = False
        self.t0 = time.perf_counter_ns()

    def set_attr(self, key: str, value) -> None:
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value

    def end(self) -> None:
        if self._done:  # idempotent: height/step spans end via two paths
            return
        self._done = True
        t1 = time.perf_counter_ns()
        tracer = self._tracer
        # Ring-overflow accounting: deque(maxlen=N) evicts silently, so
        # a truncated timeline would be indistinguishable from a complete
        # one. len() on a deque is O(1); the increment is GIL-atomic
        # enough for a monitoring counter (exactness is not load-bearing,
        # non-zero-ness is).
        if len(tracer._ring) >= tracer.capacity:
            tracer._dropped += 1
            dsink = tracer.drop_sink
            if dsink is not None:
                try:
                    dsink(1)
                except Exception:
                    pass
        tracer._ring.append((
            self.kind, self.span_id, self.parent_id, self.tid,
            self.t0, t1 - self.t0, self.attrs,
        ))
        # tracing→metrics bridge: the same close feeds the kind's
        # Prometheus histogram (libs/metrics.py span_metrics_sink) —
        # one instrumentation point, two exports. Monitoring must
        # never take down the instrumented path, hence the blanket
        # except; the sink itself is a dict lookup + bucket scan,
        # inside the tools/check_spans.py per-span budget.
        sink = tracer.metrics_sink
        if sink is not None:
            try:
                sink(self.kind, (t1 - self.t0) / 1e9)
            except Exception:
                pass


class _NoopSpan:
    """Shared do-nothing span for the disabled tracer (and a safe
    parent placeholder): keeps call sites branch-free."""

    __slots__ = ()
    kind = ""
    span_id = 0
    parent_id = 0

    def set_attr(self, key, value) -> None:
        pass

    def end(self) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class _SpanCtx:
    """Context manager: starts a span parented on the task-local
    current span, makes it current for the body, seals it on exit."""

    __slots__ = ("_tracer", "_kind", "_attrs", "_span", "_token")

    def __init__(self, tracer, kind, attrs):
        self._tracer = tracer
        self._kind = kind
        self._attrs = attrs

    def __enter__(self) -> Span:
        self._span = self._tracer.begin(self._kind, **(self._attrs or {}))
        # disabled tracer: skip the contextvar set/reset entirely
        self._token = (None if self._span is NOOP_SPAN
                       else _CURRENT.set(self._span))
        return self._span

    def __exit__(self, *exc) -> bool:
        if self._token is not None:
            _CURRENT.reset(self._token)
        self._span.end()
        return False


class _AttachCtx:
    """Context manager: make an existing span the task-local current
    span (explicit handoff) without starting or ending anything."""

    __slots__ = ("_span", "_token")

    def __init__(self, span):
        self._span = span

    def __enter__(self):
        self._token = _CURRENT.set(self._span)
        return self._span

    def __exit__(self, *exc) -> bool:
        _CURRENT.reset(self._token)
        return False


# ---------------------------------------------------------------- tracer

DEFAULT_CAPACITY = int(os.environ.get("TM_TPU_TRACE_CAPACITY", "16384"))


class Tracer:
    """Ring-buffered span recorder. Thread-safe by construction: the
    only shared mutation is deque.append / popleft-on-overflow, both
    atomic under the GIL; snapshots copy the ring."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 enabled: bool = True):
        self.capacity = capacity
        self.enabled = enabled
        self._ring: deque = deque(maxlen=capacity)
        self._dropped = 0
        # tracing→metrics bridge: fn(kind, seconds) called on every
        # span close (libs/metrics.py installs span_metrics_sink on
        # the global TRACER). None = no bridge (private test tracers).
        self.metrics_sink = None
        # eviction bridge: fn(n) on every ring overflow — feeds
        # tracing_spans_dropped_total. Same None-means-no-bridge rule.
        self.drop_sink = None

    def set_metrics_sink(self, sink) -> None:
        self.metrics_sink = sink

    def set_drop_sink(self, sink) -> None:
        self.drop_sink = sink

    @property
    def dropped(self) -> int:
        """Spans evicted from the ring since the last clear() — a
        non-zero value means snapshot() is a suffix, not the history."""
        return self._dropped

    # -- recording --

    def begin(self, kind: str, parent: Span | None = None, **attrs) -> Span:
        """Start a span. Parent defaults to the task-local current
        span; pass `parent=` to link manually-managed spans (the
        consensus height/step timeline). Returns NOOP_SPAN when
        disabled — callers never branch."""
        if not self.enabled:
            return NOOP_SPAN
        if kind not in _KINDS:
            raise ValueError(f"unregistered span kind {kind!r} "
                             "(register_kind / tools/check_spans.py)")
        if parent is None:
            parent = _CURRENT.get()
        return Span(self, kind, parent.span_id if parent else 0,
                    attrs or None)

    def span(self, kind: str, **attrs) -> _SpanCtx:
        """`with TRACER.span(KIND, k=v): ...` — the instrumented-site
        form. Nested spans parent automatically via the task context."""
        return _SpanCtx(self, kind, attrs)

    def current(self) -> Span | None:
        return _CURRENT.get()

    def attach(self, span: Span | None) -> _AttachCtx:
        """Make `span` current for a block — used to hang with-block
        children under a manually-managed span (e.g. the commit step
        span during finalize) regardless of which task runs the code."""
        return _AttachCtx(span)

    def wrap(self, fn):
        """Explicit executor handoff: capture the caller's active span
        NOW; the returned callable re-activates it in whatever thread
        runs fn. `loop.run_in_executor(None, TRACER.wrap(f), *a)`."""
        parent = _CURRENT.get()

        def _with_parent(*args, **kwargs):
            token = _CURRENT.set(parent)
            try:
                return fn(*args, **kwargs)
            finally:
                _CURRENT.reset(token)

        return _with_parent

    # -- reading --

    def clear(self) -> None:
        self._ring.clear()
        self._dropped = 0

    def __len__(self) -> int:
        return len(self._ring)

    def snapshot(self, seconds: float | None = None) -> list[tuple]:
        """Finished spans, oldest first; `seconds` keeps only spans
        that ENDED within the trailing window."""
        recs = list(self._ring)
        if seconds is None:
            return recs
        cutoff = time.perf_counter_ns() - int(seconds * 1e9)
        return [r for r in recs if r[4] + r[5] >= cutoff]

    def stage_rollup(self, seconds: float | None = None,
                     prefix: str | None = None) -> dict[str, dict]:
        """Per-kind latency rollup {kind: {count, p50_ms, p95_ms,
        p99_ms, total_ms}} over the ring (optionally windowed /
        prefix-filtered) — the BENCH stage-breakdown payload."""
        by_kind: dict[str, list[int]] = {}
        for r in self.snapshot(seconds):
            if prefix is not None and not r[0].startswith(prefix):
                continue
            by_kind.setdefault(r[0], []).append(r[5])
        out: dict[str, dict] = {}
        for kind, durs in sorted(by_kind.items()):
            durs.sort()
            n = len(durs)

            def pct(p):
                return durs[min(n - 1, int(p * n))] / 1e6

            out[kind] = {
                "count": n,
                "p50_ms": round(pct(0.50), 4),
                "p95_ms": round(pct(0.95), 4),
                "p99_ms": round(pct(0.99), 4),
                "total_ms": round(sum(durs) / 1e6, 4),
            }
        return out


# Process-global tracer — the instrument every module records into.
TRACER = Tracer()


# ---------------------------------------------------------------- origin tags
#
# Cross-node trace context. A compact binary tag rides the consensus
# wire messages that define the block lifecycle (Proposal, BlockPart,
# Vote): the sender stamps (height, round, its node label, the span id
# active at send time), the receiver rehydrates the tag into the attrs
# of its live p2p.recv_msg span. A part's recv span on node B thus
# names its send span on node A — zero new hot-path span sites, and
# peers that never set the field are untouched (the wire field is
# optional; old decoders skip it as an unknown proto field).

_ORIGIN_VERSION = 1
_ORIGIN_HDR = struct.Struct(">BQIQ")  # version, height, round, span_id
_ORIGIN_MAX_NODE = 64  # label bytes cap: tags stay wire-cheap


class OriginTag(NamedTuple):
    height: int
    round: int
    node: str
    span_id: int


def encode_origin(height: int, round_: int, node: str,
                  span_id: int = 0) -> bytes:
    """Binary origin tag: 21-byte fixed header + UTF-8 node label
    (truncated to 64 bytes). Total ≤ 85 bytes per stamped message."""
    label = node.encode("utf-8", "replace")[:_ORIGIN_MAX_NODE]
    return _ORIGIN_HDR.pack(
        _ORIGIN_VERSION, height & (2**64 - 1), round_ & (2**32 - 1),
        span_id & (2**64 - 1)) + label


def decode_origin(data: bytes | None) -> OriginTag | None:
    """Parse an origin tag; never raises. None on absent/short/
    unknown-version payloads — a garbled tag degrades to 'no tag',
    it must not take down message decode."""
    if not data or len(data) < _ORIGIN_HDR.size:
        return None
    try:
        ver, height, round_, span_id = _ORIGIN_HDR.unpack_from(data)
        if ver != _ORIGIN_VERSION:
            return None
        node = data[_ORIGIN_HDR.size:].decode("utf-8", "replace")
        return OriginTag(height, round_, node, span_id)
    except Exception:
        return None


def origin_stamp(node: str, height: int, round_: int) -> bytes:
    """Send-side: build the tag for an outgoing lifecycle message,
    capturing the task-local active span (0 if none — the node/height/
    round triple still carries the cross-node link)."""
    cur = _CURRENT.get()
    return encode_origin(height, round_, node,
                         cur.span_id if cur is not None else 0)


def rehydrate_origin(data: bytes | None) -> OriginTag | None:
    """Recv-side: decode an incoming tag and fold it into the attrs of
    the live current span (the p2p.recv_msg span wrapping reactor
    dispatch), linking this receive to the sender's send-side span."""
    tag = decode_origin(data)
    if tag is None:
        return None
    cur = _CURRENT.get()
    if cur is not None:
        cur.set_attr("origin_node", tag.node)
        cur.set_attr("origin_height", tag.height)
        cur.set_attr("origin_round", tag.round)
        if tag.span_id:
            cur.set_attr("origin_span", tag.span_id)
    return tag


# ---------------------------------------------------------------- export

_PID = os.getpid()


def chrome_trace(records: list[tuple], meta: dict | None = None) -> dict:
    """Chrome trace-event JSON (the `traceEvents` array object form)
    from snapshot() tuples: one "X" (complete) event per span, ts/dur
    in microseconds, parent links + attributes under args. Loads
    directly in Perfetto / chrome://tracing; nesting renders from
    ts/dur containment per (pid, tid) track, and args.parent_id gives
    exact cross-thread lineage. `meta` (ring capacity, drop counter,
    clock anchor...) lands under a top-level "tm_tpu" key — viewers
    ignore unknown top-level keys, collectors read it."""
    events = []
    for kind, span_id, parent_id, tid, t0, dur, attrs in records:
        args = {"span_id": span_id}
        if parent_id:
            args["parent_id"] = parent_id
        if attrs:
            args.update(attrs)
        events.append({
            "name": kind,
            "cat": kind.partition(".")[0],
            "ph": "X",
            "ts": t0 / 1e3,
            "dur": dur / 1e3,
            "pid": _PID,
            "tid": tid,
            "args": args,
        })
    out = {"traceEvents": events, "displayTimeUnit": "ms"}
    if meta is not None:
        out["tm_tpu"] = meta
    return out
