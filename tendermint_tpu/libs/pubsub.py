"""Event pub/sub with a query language (reference: libs/pubsub).

Queries follow the reference DSL (libs/pubsub/query): conditions over
string-keyed event attributes joined by AND, e.g.

    tm.event = 'NewBlock' AND tx.height > 5 AND tx.hash CONTAINS 'ab'

Events are published with a message plus an attribute multimap
(key -> list of string values); a condition matches if ANY value for
the key satisfies it.
"""

from __future__ import annotations

import asyncio
import re
from dataclasses import dataclass, field


class QueryError(ValueError):
    pass


_TOKEN = re.compile(
    r"\s*(?:(?P<op><=|>=|=|<|>)|(?P<kw>AND|CONTAINS|EXISTS)\b|"
    r"(?P<str>'[^']*')|(?P<num>-?\d+(?:\.\d+)?)|(?P<key>[\w.\-/]+))"
)


@dataclass(frozen=True)
class Condition:
    key: str
    op: str  # '=', '<', '>', '<=', '>=', 'CONTAINS', 'EXISTS'
    value: str | float | None = None

    def matches(self, attrs: dict[str, list[str]]) -> bool:
        values = attrs.get(self.key)
        if values is None:
            return False
        if self.op == "EXISTS":
            return True
        for v in values:
            if self._match_one(v):
                return True
        return False

    def _match_one(self, v: str) -> bool:
        if self.op == "CONTAINS":
            return str(self.value) in v
        if self.op == "=":
            if isinstance(self.value, float):
                try:
                    return float(v) == self.value
                except ValueError:
                    return False
            return v == self.value
        try:
            lhs = float(v)
        except ValueError:
            return False
        rhs = float(self.value)  # type: ignore[arg-type]
        return {
            "<": lhs < rhs,
            ">": lhs > rhs,
            "<=": lhs <= rhs,
            ">=": lhs >= rhs,
        }[self.op]


class Query:
    """AND-composed conditions parsed from the DSL string."""

    def __init__(self, conditions: list[Condition], source: str = ""):
        self.conditions = conditions
        self._source = source or " AND ".join(
            f"{c.key} {c.op} {c.value!r}" for c in conditions
        )

    @classmethod
    def parse(cls, s: str) -> "Query":
        tokens = []
        pos = 0
        while pos < len(s):
            m = _TOKEN.match(s, pos)
            if not m or m.end() == pos:
                if s[pos:].strip():
                    raise QueryError(f"bad query near {s[pos:]!r}")
                break
            pos = m.end()
            kind = m.lastgroup
            tokens.append((kind, m.group(kind)))
        conds = []
        i = 0
        while i < len(tokens):
            if tokens[i] == ("kw", "AND"):
                i += 1
                continue
            if tokens[i][0] != "key":
                raise QueryError(f"expected key, got {tokens[i]!r}")
            key = tokens[i][1]
            if i + 1 >= len(tokens):
                raise QueryError("dangling key")
            kind, tok = tokens[i + 1]
            if (kind, tok) == ("kw", "EXISTS"):
                conds.append(Condition(key, "EXISTS"))
                i += 2
                continue
            if kind == "kw" and tok == "CONTAINS":
                if i + 2 >= len(tokens) or tokens[i + 2][0] != "str":
                    raise QueryError("CONTAINS needs a string")
                conds.append(Condition(key, "CONTAINS", tokens[i + 2][1][1:-1]))
                i += 3
                continue
            if kind != "op":
                raise QueryError(f"expected operator after {key}")
            if i + 2 >= len(tokens):
                raise QueryError("dangling operator")
            vkind, vtok = tokens[i + 2]
            if vkind == "str":
                value: str | float = vtok[1:-1]
            elif vkind == "num":
                value = float(vtok)
            else:
                raise QueryError(f"bad value {vtok!r}")
            conds.append(Condition(key, tok, value))
            i += 3
        if not conds:
            raise QueryError("empty query")
        return cls(conds, s)

    def matches(self, attrs: dict[str, list[str]]) -> bool:
        return all(c.matches(attrs) for c in self.conditions)

    def __str__(self) -> str:
        return self._source

    def __eq__(self, other) -> bool:
        return isinstance(other, Query) and str(self) == str(other)

    def __hash__(self) -> int:
        return hash(str(self))


ALL = Query([Condition("__all__", "EXISTS")], "__all__")
ALL.matches = lambda attrs: True  # type: ignore[method-assign]


@dataclass
class Message:
    data: object
    attrs: dict[str, list[str]] = field(default_factory=dict)


class Subscription:
    def __init__(self, query: Query, buffer: int):
        self.query = query
        self.queue: asyncio.Queue[Message] = asyncio.Queue(buffer)
        self.cancelled: asyncio.Event = asyncio.Event()

    async def next(self) -> Message:
        get = asyncio.ensure_future(self.queue.get())
        cancel = asyncio.ensure_future(self.cancelled.wait())
        done, pending = await asyncio.wait(
            [get, cancel], return_when=asyncio.FIRST_COMPLETED
        )
        for p in pending:
            p.cancel()
        if get in done:
            return get.result()
        raise asyncio.CancelledError("subscription cancelled")


class PubSub:
    """In-process event bus: subscribe by query, publish with attrs.

    Unlike the reference's buffered-channel semantics, a full subscriber
    queue drops the oldest message for that subscriber (slow consumers
    never stall consensus) — the same policy the reference applies via
    unsubscribe-on-overflow, without the forced resubscribe.
    """

    def __init__(self, buffer: int = 1024):
        self._buffer = buffer
        self._subs: dict[tuple[str, str], Subscription] = {}

    def subscribe(self, subscriber: str, query: Query) -> Subscription:
        key = (subscriber, str(query))
        if key in self._subs:
            raise ValueError(f"already subscribed: {key}")
        sub = Subscription(query, self._buffer)
        self._subs[key] = sub
        return sub

    def unsubscribe(self, subscriber: str, query: Query) -> None:
        key = (subscriber, str(query))
        sub = self._subs.pop(key, None)
        if sub is None:
            raise ValueError(f"not subscribed: {key}")
        sub.cancelled.set()

    def unsubscribe_all(self, subscriber: str) -> None:
        for key in [k for k in self._subs if k[0] == subscriber]:
            self._subs.pop(key).cancelled.set()

    def publish(self, data: object, attrs: dict[str, list[str]] | None = None) -> None:
        attrs = attrs or {}
        msg = Message(data, attrs)
        for sub in self._subs.values():
            if sub.query.matches(attrs):
                while True:
                    try:
                        sub.queue.put_nowait(msg)
                        break
                    except asyncio.QueueFull:
                        try:
                            sub.queue.get_nowait()
                        except asyncio.QueueEmpty:
                            break

    @property
    def num_subscribers(self) -> int:
        return len(self._subs)
