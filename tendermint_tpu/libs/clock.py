"""Process-wide time source seam (sim/clock.py virtual time plugs in
here).

Production code that consults the wall clock for CONTROL FLOW —
consensus round start times, breaker cooldowns, token-bucket refill,
trust-metric interval ticks, flow-rate EWMA windows, overload shed
windows — reads it through this module instead of `time` directly.
By default every call is a thin shim over the stdlib (one module
global load + an is-None check on the hot path). When a simulation
installs a virtual clock (tendermint_tpu/sim), ALL of those call
sites advance on simulated time together, coherently with the sim
event loop's own `loop.time()`: a scenario's "30 seconds of
partition" costs milliseconds of wall clock and is deterministic
under its seed.

Deliberately NOT routed through here: pure-measurement reads
(`perf_counter` for metrics/span durations) — they never steer
control flow, and wall-clock durations are exactly what an operator
wants on a dashboard even inside a simulation.
"""

from __future__ import annotations

import time as _time

# The installed source must provide monotonic() -> float seconds and
# time_ns() -> int nanoseconds since the unix epoch, mutually
# coherent (time_ns advances iff monotonic does).
_source = None


def install(source) -> None:
    """Install a virtual time source (sim use; tests must uninstall)."""
    global _source
    _source = source


def uninstall() -> None:
    global _source
    _source = None


def installed():
    """The active virtual source, or None under real time."""
    return _source


def monotonic() -> float:
    s = _source
    return _time.monotonic() if s is None else s.monotonic()


def time_ns() -> int:
    s = _source
    return _time.time_ns() if s is None else s.time_ns()
