"""Address + retry helpers shared by node, CLI drivers, and app
creators."""

from __future__ import annotations

import random


def split_laddr(laddr: str,
                default_host: str = "0.0.0.0") -> tuple[str, int]:
    """'tcp://host:port' or 'host:port' -> (host, port)."""
    addr = laddr[len("tcp://"):] if laddr.startswith("tcp://") else laddr
    host, _, port = addr.rpartition(":")
    return host or default_host, int(port)


def jittered_backoff(attempt: int, base: float, cap: float) -> float:
    """THE retry-delay policy, one copy for every backoff site (p2p
    persistent-peer reconnect, ABCI client re-dial, statesync chunk
    re-request, device-breaker cooldown): capped exponential from
    `base` with ±20 % uniform jitter so a fleet of retriers never
    thunders in lockstep. `attempt` is 0-based."""
    return min(base * 2 ** attempt, cap) * (0.8 + 0.4 * random.random())
