"""Address helpers shared by node, CLI drivers, and app creators."""

from __future__ import annotations


def split_laddr(laddr: str,
                default_host: str = "0.0.0.0") -> tuple[str, int]:
    """'tcp://host:port' or 'host:port' -> (host, port)."""
    addr = laddr[len("tcp://"):] if laddr.startswith("tcp://") else laddr
    host, _, port = addr.rpartition(":")
    return host or default_host, int(port)
