"""Failpoint crash injection (reference: libs/fail/fail.go).

Set FAIL_TEST_INDEX=<n>: the n-th fail() call-site reached in this
process exits hard (os._exit, no cleanup — simulating a crash). Used by
crash-recovery tests around the WAL and ApplyBlock persistence steps.
"""

from __future__ import annotations

import os

_counter = -1


def fail() -> None:
    global _counter
    env = os.environ.get("FAIL_TEST_INDEX")
    if env is None:
        return
    _counter += 1
    if _counter == int(env):
        os._exit(1)


def reset() -> None:
    global _counter
    _counter = -1
