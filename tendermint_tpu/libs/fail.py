"""Legacy failpoint shim (reference: libs/fail/fail.go).

The crash-injection machinery lives in libs/failpoints.py now: the six
original fail() persistence-boundary call sites are NAMED points
(consensus.commit.* / state.apply.*) hit through the registry, which
still honors FAIL_TEST_INDEX with the original ordinal semantics —
the n-th legacy site reached in the process exits hard (os._exit, no
cleanup). This module keeps the old import surface working.

FAIL_TEST_INDEX is parsed once at first use; a malformed value is
logged and ignored instead of raising from inside consensus.
"""

from __future__ import annotations

from . import failpoints


def fail() -> None:
    failpoints.legacy_fail()


def reset() -> None:
    failpoints.reset()
