"""Prometheus-style metrics (reference: libs + per-module metrics.go,
docs/nodes/metrics.md:21-52).

Counters, gauges and histograms with optional labels, collected in a
process-global registry and rendered in the Prometheus text exposition
format. Served on the RPC listener at GET /metrics and (when
`instrumentation.prometheus` is on) on a dedicated listener, mirroring
the reference's MetricsProvider wiring (node/node.go:110-125).

Implementation is deliberately tiny and allocation-light: consensus
hot paths (vote batches, device launches) record into plain floats
under no lock — the event-loop/worker structure makes races harmless
for monitoring data, same stance as Prometheus client libs' relaxed
atomicity on Python. The one consistency guarantee render() DOES make:
a histogram's cumulative buckets, `_count` and `+Inf` are derived from
a single snapshot of the bucket array, so concurrent observes (the
BatchVerifier executor threads) can never produce exposition output
where `+Inf` != `_count` or the cumulative sequence decreases. `_sum`
may lag the buckets by in-flight observes — relaxed, like counters.

The tracing→metrics bridge at the bottom of this module makes every
registered span kind (libs/tracing.py) populate a histogram on span
close: one instrumentation point, two exports. The device-pipeline
kinds (crypto.pack/dispatch/device_exec/readback) feed the dedicated
`tpu_*_seconds` histograms; every other kind feeds
`tracing_span_seconds{kind=...}`.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field, fields as dc_fields

from . import tracing as _tracing


def _escape_label_value(v: str) -> str:
    """Exposition-format label-value escaping: backslash, double-quote
    and newline emitted raw produce unparseable output for values like
    peer addresses or chain ids (text format spec, label_value)."""
    return (str(v).replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(h: str) -> str:
    """HELP lines escape backslash and newline (text format spec)."""
    return h.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(labels: dict[str, str] | None) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class Metric:
    def __init__(self, name: str, help_: str, namespace: str = ""):
        self.name = f"{namespace}_{name}" if namespace else name
        self.namespace = namespace
        self.help = help_

    def render(self) -> list[str]:  # pragma: no cover - abstract
        raise NotImplementedError


class Counter(Metric):
    kind = "counter"

    def __init__(self, name: str, help_: str, namespace: str = ""):
        super().__init__(name, help_, namespace)
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        return self._values.get(tuple(sorted(labels.items())), 0.0)

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help)}",
               f"# TYPE {self.name} {self.kind}"]
        for key, v in sorted(self._values.items()):
            out.append(f"{self.name}{_fmt_labels(dict(key))} {_fmt_value(v)}")
        if not self._values:
            out.append(f"{self.name} 0")
        return out


class Gauge(Counter):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        self._values[tuple(sorted(labels.items()))] = float(value)

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)


_DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class _Series:
    """One labelset's state: a bucket-count array and a running sum."""

    __slots__ = ("counts", "sum")

    def __init__(self, nbuckets: int):
        self.counts = [0] * (nbuckets + 1)
        self.sum = 0.0


class _BoundHistogram:
    """A histogram pre-resolved to one labelset: observe() is a bucket
    scan + two plain increments, no label handling per call — the
    handle the tracing bridge caches per span kind."""

    __slots__ = ("_buckets", "_series")

    def __init__(self, buckets: tuple, series: _Series):
        self._buckets = buckets
        self._series = series

    def observe(self, value: float) -> None:
        s = self._series
        s.sum += value
        for i, b in enumerate(self._buckets):
            if value <= b:
                s.counts[i] += 1
                return
        s.counts[-1] += 1


class Histogram(Metric):
    """Histogram with optional labels: `observe(v)` records into the
    unlabelled series, `observe(v, ch="0x20")` into a labelled one,
    `labels(ch="0x20")` returns a bound handle for hot paths."""

    kind = "histogram"

    def __init__(self, name: str, help_: str, namespace: str = "",
                 buckets: tuple = _DEFAULT_BUCKETS):
        super().__init__(name, help_, namespace)
        self.buckets = tuple(sorted(buckets))
        self._series: dict[tuple, _Series] = {}
        self._series_lock = threading.Lock()

    def _series_for(self, key: tuple) -> _Series:
        s = self._series.get(key)
        if s is None:
            # creation is the only guarded op: a first-observe race
            # from two threads must not drop a whole series
            with self._series_lock:
                s = self._series.setdefault(
                    key, _Series(len(self.buckets)))
        return s

    def labels(self, **labels) -> _BoundHistogram:
        key = tuple(sorted(labels.items()))
        return _BoundHistogram(self.buckets, self._series_for(key))

    def observe(self, value: float, **labels) -> None:
        key = tuple(sorted(labels.items())) if labels else ()
        s = self._series_for(key)
        s.sum += value
        for i, b in enumerate(self.buckets):
            if value <= b:
                s.counts[i] += 1
                return
        s.counts[-1] += 1

    @property
    def count(self) -> int:
        return sum(sum(s.counts) for s in self._series.values())

    @property
    def sum(self) -> float:
        return sum(s.sum for s in self._series.values())

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help)}",
               f"# TYPE {self.name} histogram"]
        series = sorted(self._series.items()) or [((), _Series(
            len(self.buckets)))]
        for key, s in series:
            # ONE snapshot of the bucket array per series: cumulative
            # buckets, +Inf and _count all derive from it, so a
            # concurrent observe (executor threads) can never render
            # +Inf != _count or a non-monotone cumulative sequence.
            counts = list(s.counts)
            lbl = dict(key)
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += counts[i]
                out.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels({**lbl, 'le': _fmt_value(b)})} {cum}")
            cum += counts[-1]
            out.append(
                f"{self.name}_bucket"
                f"{_fmt_labels({**lbl, 'le': '+Inf'})} {cum}")
            out.append(f"{self.name}_sum{_fmt_labels(lbl)} "
                       f"{_fmt_value(s.sum)}")
            out.append(f"{self.name}_count{_fmt_labels(lbl)} {cum}")
        return out

    class _Timer:
        def __init__(self, observe):
            self._observe = observe

        def __enter__(self):
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self._observe(time.perf_counter() - self._t0)
            return False

    def time(self, **labels) -> "Histogram._Timer":
        if labels:
            return self._Timer(self.labels(**labels).observe)
        return self._Timer(self.observe)


class Registry:
    def __init__(self):
        self._metrics: list[Metric] = []
        self._lock = threading.Lock()

    def register(self, m: Metric) -> Metric:
        with self._lock:
            self._metrics.append(m)
        return m

    def counter(self, name, help_, namespace="") -> Counter:
        return self.register(Counter(name, help_, namespace))

    def gauge(self, name, help_, namespace="") -> Gauge:
        return self.register(Gauge(name, help_, namespace))

    def histogram(self, name, help_, namespace="",
                  buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self.register(Histogram(name, help_, namespace, buckets))

    def render_text(self) -> str:
        with self._lock:
            metrics = list(self._metrics)
        lines = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


# Process-global registry — the MetricsProvider analogue.
DEFAULT = Registry()


@dataclass
class ConsensusMetrics:
    """reference: consensus/metrics.go."""
    height: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "height", "Height of the chain.", "consensus"))
    rounds: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "rounds", "Round of the chain.", "consensus"))
    validators: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "validators", "Number of validators.", "consensus"))
    validators_power: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "validators_power", "Total voting power of validators.", "consensus"))
    missing_validators: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "missing_validators", "Validators absent from the last commit.",
        "consensus"))
    missing_validators_power: Gauge = field(
        default_factory=lambda: DEFAULT.gauge(
            "missing_validators_power",
            "Voting power of validators absent from the last commit.",
            "consensus"))
    byzantine_validators: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "byzantine_validators", "Validators that equivocated.", "consensus"))
    byzantine_validators_power: Gauge = field(
        default_factory=lambda: DEFAULT.gauge(
            "byzantine_validators_power",
            "Voting power of validators that equivocated.", "consensus"))
    validator_power: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "validator_power", "This node's voting power (0 if not a "
        "validator).", "consensus"))
    validator_last_signed_height: Gauge = field(
        default_factory=lambda: DEFAULT.gauge(
            "validator_last_signed_height",
            "Last height this node's precommit made a commit.",
            "consensus"))
    validator_missed_blocks: Counter = field(
        default_factory=lambda: DEFAULT.counter(
            "validator_missed_blocks",
            "Commits missing this node's precommit.", "consensus"))
    fast_syncing: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "fast_syncing", "1 while fast sync is running.", "consensus"))
    state_syncing: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "state_syncing", "1 while state sync is running.", "consensus"))
    num_txs: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "num_txs", "Transactions in the latest block.", "consensus"))
    block_size_bytes: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "block_size_bytes", "Size of the latest block.", "consensus"))
    total_txs: Counter = field(default_factory=lambda: DEFAULT.counter(
        "total_txs", "Total transactions committed.", "consensus"))
    block_interval_seconds: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "block_interval_seconds", "Time between blocks.", "consensus",
            buckets=(0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60)))
    fast_sync_blocks: Counter = field(default_factory=lambda: DEFAULT.counter(
        "fast_sync_blocks", "Blocks applied via fast sync.", "consensus"))
    block_parts: Counter = field(default_factory=lambda: DEFAULT.counter(
        "block_parts", "Block parts received and added.", "consensus"))
    # --- TPU batch-verify observability (new capability; no reference
    # equivalent): these are the numbers that justify _DEVICE_THRESHOLD
    # and the micro-batch window empirically.
    vote_batch_size: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "vote_batch_size", "Votes per micro-batch.", "consensus",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)))
    vote_batch_wait_seconds: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "vote_batch_wait_seconds",
            "Window wait before a vote micro-batch verified.", "consensus"))


@dataclass
class CryptoMetrics:
    """Batch-verifier instrumentation (new; the SURVEY §6 speedup
    denominators come straight from these)."""
    batch_lanes: Counter = field(default_factory=lambda: DEFAULT.counter(
        "batch_lanes_total", "Signature lanes verified, by backend.",
        "crypto"))
    batch_seconds: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "batch_verify_seconds", "Wall time per verify() call.",
            "crypto"))
    device_launches: Counter = field(default_factory=lambda: DEFAULT.counter(
        "device_launches_total", "Device kernel launches.", "crypto"))
    invalid_sigs: Counter = field(default_factory=lambda: DEFAULT.counter(
        "invalid_signatures_total", "Lanes that failed verification.",
        "crypto"))
    device_failures: Counter = field(default_factory=lambda: DEFAULT.counter(
        "device_failures_total",
        "Device batch launches that raised; host degradation engaged.",
        "crypto"))
    breaker_state: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "breaker_state",
        "Device circuit-breaker state by backend "
        "(0 closed, 1 open, 2 half-open).", "crypto"))
    breaker_opens: Counter = field(default_factory=lambda: DEFAULT.counter(
        "breaker_opens_total",
        "Circuit-breaker closed/half-open -> open transitions, "
        "by backend.", "crypto"))
    breaker_probes: Counter = field(default_factory=lambda: DEFAULT.counter(
        "breaker_probes_total",
        "Half-open synthetic probe batches, by backend and result.",
        "crypto"))


@dataclass
class P2PMetrics:
    """reference: p2p/metrics.go."""
    peers: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "peers", "Connected peers.", "p2p"))
    peer_receive_bytes: Counter = field(
        default_factory=lambda: DEFAULT.counter(
            "peer_receive_bytes_total", "Bytes received, by channel.",
            "p2p"))
    peer_send_bytes: Counter = field(default_factory=lambda: DEFAULT.counter(
        "peer_send_bytes_total", "Bytes sent, by channel.", "p2p"))
    pending_send_bytes: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "pending_send_bytes", "Pending bytes across peers.", "p2p"))
    message_receive: Counter = field(default_factory=lambda: DEFAULT.counter(
        "message_receive_total", "Complete messages received, by channel.",
        "p2p"))
    message_send: Counter = field(default_factory=lambda: DEFAULT.counter(
        "message_send_total", "Complete messages sent, by channel.", "p2p"))
    num_txs: Counter = field(default_factory=lambda: DEFAULT.counter(
        "num_txs", "Transactions received from peers.", "p2p"))
    reconnect_exhausted: Counter = field(
        default_factory=lambda: DEFAULT.counter(
            "reconnect_exhausted_total",
            "Persistent peers abandoned after exhausting reconnect "
            "attempts.", "p2p"))
    send_drops: Counter = field(default_factory=lambda: DEFAULT.counter(
        "send_drops_total",
        "Messages dropped on full send queues (try_send/broadcast), "
        "by channel.", "p2p"))
    slow_peer_events: Counter = field(
        default_factory=lambda: DEFAULT.counter(
            "slow_peer_events_total",
            "Slow-peer escalation transitions "
            "(skip/demote/disconnect/recover).", "p2p"))


@dataclass
class MempoolMetrics:
    """reference: mempool/metrics.go."""
    size: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "size", "Transactions in the mempool.", "mempool"))
    tx_bytes: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "tx_bytes", "Total bytes of transactions in the mempool.",
        "mempool"))
    tx_size_bytes: Histogram = field(default_factory=lambda: DEFAULT.histogram(
        "tx_size_bytes", "Transaction sizes.", "mempool",
        buckets=(32, 128, 512, 2048, 8192, 32768, 131072)))
    failed_txs: Counter = field(default_factory=lambda: DEFAULT.counter(
        "failed_txs", "CheckTx rejections.", "mempool"))
    recheck_times: Counter = field(default_factory=lambda: DEFAULT.counter(
        "recheck_times", "Transactions rechecked after commit.", "mempool"))


@dataclass
class AdmissionMetrics:
    """Device-offloaded tx admission plane (mempool/admission.py):
    the micro-batch collector in front of CheckTx. Occupancy and lane
    histograms show whether floods actually coalesce into wide device
    launches; the shed counter (by reason) is the evidence that junk
    dies at the device, not in the app."""
    batch_lanes: Histogram = field(default_factory=lambda: DEFAULT.histogram(
        "batch_lanes",
        "Txs per admission pre-verify flush (device or host).",
        "admission",
        buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512)))
    batch_occupancy: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "batch_occupancy_ratio",
            "Flush size / configured admission batch size.", "admission",
            buckets=(0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0)))
    verify_seconds: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "verify_seconds",
            "Wall time of one admission batch-verify launch.",
            "admission"))
    admitted: Counter = field(default_factory=lambda: DEFAULT.counter(
        "admitted_total",
        "Txs past signature pre-verification, by signed=yes|no.",
        "admission"))
    sheds: Counter = field(default_factory=lambda: DEFAULT.counter(
        "shed_total",
        "Txs shed at admission before any ABCI round trip, by reason "
        "(bad_signature/malformed/unsigned/queue_full).", "admission"))
    launches: Counter = field(default_factory=lambda: DEFAULT.counter(
        "verify_launches_total",
        "Admission batch-verify launches, by backend "
        "(device/host/host_recheck).", "admission"))


@dataclass
class LightMetrics:
    """Light-client serving plane (light/serving.py): the shared
    verification plane between the proxy RPC surface and the light
    client. Lanes-per-launch and the coalesce/cache counters are the
    evidence that N concurrent client requests collapse into few wide
    device launches; the shed counter is the evidence a request flood
    dies at the plane, not in the event loop."""
    batch_lanes: Histogram = field(default_factory=lambda: DEFAULT.histogram(
        "batch_lanes",
        "Signature lanes per coalesced light-verify launch.", "light",
        buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)))
    verify_seconds: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "verify_seconds",
            "Wall time of one coalesced light-verify launch.", "light"))
    verify_launches: Counter = field(default_factory=lambda: DEFAULT.counter(
        "verify_launches_total",
        "Light-plane batch-verify launches, by backend "
        "(device/host/host_recheck).", "light"))
    cache_hits: Counter = field(default_factory=lambda: DEFAULT.counter(
        "cache_hits_total",
        "Requests served from the verified-header cache.", "light"))
    cache_misses: Counter = field(default_factory=lambda: DEFAULT.counter(
        "cache_misses_total",
        "Requests that missed the verified-header cache.", "light"))
    requests_coalesced: Counter = field(
        default_factory=lambda: DEFAULT.counter(
            "requests_coalesced_total",
            "Requests that joined an in-flight verification for the "
            "same height instead of starting their own.", "light"))
    shed: Counter = field(default_factory=lambda: DEFAULT.counter(
        "shed_total",
        "Requests shed at the serving plane, by reason (queue_full).",
        "light"))


@dataclass
class SpeculationMetrics:
    """Verify-ahead pipeline (consensus/speculation.py +
    crypto/tpu/resident.py): commit verification launched BEFORE the
    commit is needed, served at commit time from a byte-exact template
    match. The hit counter is the evidence the commit-time verify
    vanished from the critical path; overlap_seconds is how far ahead
    the launch completed; arena/reupload bytes quantify what device
    residency + donated buffers save per launch."""
    hits: Counter = field(default_factory=lambda: DEFAULT.counter(
        "hits_total",
        "Commits whose verdicts were fully served from a completed "
        "speculative launch (zero verification launches on the "
        "post-commit critical path).", "speculation"))
    misses: Counter = field(default_factory=lambda: DEFAULT.counter(
        "misses_total",
        "Speculation misses, by reason (no_plan once per unserved "
        "commit; unpatched/mismatch/equivocation/not_launched per "
        "fallback lane).", "speculation"))
    patched_lanes: Counter = field(default_factory=lambda: DEFAULT.counter(
        "patched_lanes_total",
        "Precommit lanes patched into the speculative batch as votes "
        "arrived.", "speculation"))
    launches: Counter = field(default_factory=lambda: DEFAULT.counter(
        "launches_total",
        "Speculative verification launches, by backend "
        "(device/host/host_recheck).", "speculation"))
    overlap_seconds: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "overlap_seconds",
            "Time between a speculative launch completing and its "
            "verdicts being served at commit time.", "speculation"))
    arena_bytes: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "arena_bytes",
        "Bytes of persistent device-resident verify buffers "
        "(crypto/tpu/resident.py ResidentArena).", "speculation"))
    reupload_bytes: Counter = field(default_factory=lambda: DEFAULT.counter(
        "resident_reupload_bytes_total",
        "Host-to-device bytes actually shipped by arena delta splices "
        "and per-launch templates (vs re-transferring every lane).",
        "speculation"))


@dataclass
class BlockchainMetrics:
    """Fast-sync pool instrumentation (reference has no blocksync
    metrics in v0.34; names follow the pool's own vocabulary)."""
    pool_height: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "pool_height", "Next height the fast-sync pool will fetch.",
        "blockchain"))
    pending_requests: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "pending_requests", "In-flight block requests.", "blockchain"))
    num_peers: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "num_peers", "Peers the fast-sync pool can fetch from.",
        "blockchain"))
    blocks_synced: Counter = field(default_factory=lambda: DEFAULT.counter(
        "blocks_synced_total", "Blocks verified and applied by fast sync.",
        "blockchain"))
    block_bytes_received: Counter = field(
        default_factory=lambda: DEFAULT.counter(
            "block_bytes_received_total",
            "Block-response bytes received from peers.", "blockchain"))


@dataclass
class StateSyncMetrics:
    """Snapshot-restore instrumentation (reference: statesync/)."""
    syncing: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "syncing", "1 while state sync is running.", "statesync"))
    snapshots_discovered: Counter = field(
        default_factory=lambda: DEFAULT.counter(
            "snapshots_discovered_total",
            "Snapshot advertisements received from peers.", "statesync"))
    chunks_received: Counter = field(default_factory=lambda: DEFAULT.counter(
        "chunks_received_total", "Snapshot chunks received.", "statesync"))
    chunks_served: Counter = field(default_factory=lambda: DEFAULT.counter(
        "chunks_served_total", "Snapshot chunks served to peers.",
        "statesync"))
    chunk_retries: Counter = field(default_factory=lambda: DEFAULT.counter(
        "chunk_retries_total",
        "Snapshot chunk fetches re-requested after a miss/timeout.",
        "statesync"))
    chunks_refetched: Counter = field(
        default_factory=lambda: DEFAULT.counter(
            "chunks_refetched_total",
            "Snapshot chunks discarded and re-fetched, by reason "
            "(poisoned restore attempt, app refetch/retry verdicts).",
            "statesync"))
    peers_quarantined: Counter = field(
        default_factory=lambda: DEFAULT.counter(
            "peers_quarantined_total",
            "Snapshot peers quarantined for serving provably bad "
            "chunks or app-rejected senders.", "statesync"))
    restore_attempts: Counter = field(
        default_factory=lambda: DEFAULT.counter(
            "restore_attempts_total",
            "Snapshot restore attempts started (first try plus every "
            "re-fetch with a rotated peer mix).", "statesync"))


@dataclass
class EvidenceMetrics:
    """reference: evidence/metrics.go (pool size) + admission counters."""
    pool_size: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "pool_size", "Pending evidence in the pool.", "evidence"))
    pool_bytes: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "pool_bytes", "Bytes of pending evidence in the pool.", "evidence"))
    verified: Counter = field(default_factory=lambda: DEFAULT.counter(
        "verified_total", "Evidence verified and admitted to the pool.",
        "evidence"))
    committed: Counter = field(default_factory=lambda: DEFAULT.counter(
        "committed_total", "Evidence committed in blocks.", "evidence"))


@dataclass
class StateMetrics:
    """reference: state/metrics.go."""
    block_processing_seconds: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "block_processing_seconds", "ApplyBlock wall time.", "state"))
    commit_verify_seconds: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "commit_verify_seconds",
            "LastCommit signature-batch wall time.", "state"))
    validator_set_updates: Counter = field(
        default_factory=lambda: DEFAULT.counter(
            "validator_set_updates_total",
            "Validator updates applied from EndBlock.", "state"))
    consensus_param_updates: Counter = field(
        default_factory=lambda: DEFAULT.counter(
            "consensus_param_updates_total",
            "Consensus-parameter updates applied from EndBlock.", "state"))


@dataclass
class ABCIMetrics:
    """Per-method ABCI connection latency (reference: the per-method
    `abci_connection_method_timing_seconds` added in later lines)."""
    method_seconds: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "connection_method_seconds",
            "ABCI call latency, by connection and method.", "abci"))
    client_reconnects: Counter = field(
        default_factory=lambda: DEFAULT.counter(
            "client_reconnects_total",
            "ABCI client transport reconnect attempts, by result.",
            "abci"))


@dataclass
class TPUMetrics:
    """Device verify-pipeline telemetry (new capability; no reference
    equivalent). The four stage histograms are fed by the
    tracing→metrics bridge from existing span closes — no extra
    instrumentation sites in the hot path."""
    verify_queue_depth: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "verify_queue_depth",
        "Votes waiting in the micro-batch verify queue.", "tpu"))
    batch_occupancy: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "batch_occupancy_ratio",
            "Real lanes / padded bucket size per device batch.", "tpu",
            buckets=(0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8,
                     0.9, 1.0)))
    pack_seconds: Histogram = field(default_factory=lambda: DEFAULT.histogram(
        "pack_seconds", "Host byte-packing time per launch "
        "(bridge-fed from crypto.pack spans).", "tpu"))
    dispatch_seconds: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "dispatch_seconds", "Kernel-launch enqueue time "
            "(bridge-fed from crypto.dispatch spans).", "tpu"))
    device_exec_seconds: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "device_exec_seconds", "Wait-until-verdicts-ready time "
            "(bridge-fed from crypto.device_exec spans).", "tpu"))
    readback_seconds: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "readback_seconds", "Device-to-host verdict copy time "
            "(bridge-fed from crypto.readback spans).", "tpu"))
    host_fallbacks: Counter = field(default_factory=lambda: DEFAULT.counter(
        "host_fallbacks_total",
        "Batches that wanted the device but verified on host.", "tpu"))
    batch_splits: Counter = field(default_factory=lambda: DEFAULT.counter(
        "batch_splits_total",
        "Verifies split into multiple launches (batch > max bucket).",
        "tpu"))
    jit_compiles: Counter = field(default_factory=lambda: DEFAULT.counter(
        "jit_compiles_total",
        "First launches at a new kernel shape (each triggers an XLA "
        "trace+compile), by kernel.", "tpu"))
    expanded_cache: Counter = field(default_factory=lambda: DEFAULT.counter(
        "expanded_cache_events_total",
        "Expanded-valset table cache hits/misses.", "tpu"))
    expanded_build_seconds: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "expanded_build_seconds",
            "Wall time building expanded comb tables for a valset.", "tpu",
            buckets=(0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120)))
    mesh_devices: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "mesh_devices",
        "Devices in the ('dp',) verify mesh (1 = single-device).",
        "tpu"))
    shard_lanes: Counter = field(default_factory=lambda: DEFAULT.counter(
        "shard_lanes_total",
        "Signature lanes dispatched to each mesh device by sharded "
        "verify launches, by device.", "tpu"))
    table_shard_bytes: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "table_shard_bytes",
        "Per-device bytes of the newest key-range-sharded expanded "
        "comb table (0 until a sharded build runs).", "tpu"))
    effective_backend: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "effective_backend",
        "One-hot effective verify backend classified from the launch "
        "ledger by the silicon watchdog, by backend state.", "tpu"))
    launch_ledger_records: Counter = field(
        default_factory=lambda: DEFAULT.counter(
            "launch_ledger_records_total",
            "Device launch-ledger records appended, by workload and "
            "backend.", "tpu"))
    launch_ledger_evictions: Counter = field(
        default_factory=lambda: DEFAULT.counter(
            "launch_ledger_evictions_total",
            "Launch-ledger records evicted from the bounded ring.",
            "tpu"))
    hbm_resident_bytes: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "hbm_resident_bytes",
        "Device-resident bytes registered with the HBM accounting "
        "registry, by device and kind.", "tpu"))
    device_breaker_state: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "device_breaker_state",
        "Per-mesh-device circuit-breaker state (0 closed, 1 open, "
        "2 half-open), by device.", "tpu"))
    mesh_evictions: Counter = field(default_factory=lambda: DEFAULT.counter(
        "mesh_evictions_total",
        "Mesh devices evicted from the verify fabric (per-device "
        "breaker opened), by device and reason.", "tpu"))
    reshard_seconds: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "reshard_seconds",
            "Wall time of a live fabric reshard (rebuilding key-range "
            "shards / resident arena over the surviving device set).",
            "tpu",
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30)))
    mesh_active_devices: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "mesh_active_devices",
        "Devices currently serving the verify mesh (mesh size minus "
        "evicted devices; 0 until a mesh forms).", "tpu"))


@dataclass
class FailpointMetrics:
    """Chaos-injection blast radius (libs/failpoints.py): how often
    each armed point was evaluated and how often it actually fired —
    on the same scrape as the degradation it causes."""
    hits: Counter = field(default_factory=lambda: DEFAULT.counter(
        "hits_total",
        "Armed failpoint evaluations, by point.", "failpoint"))
    fires: Counter = field(default_factory=lambda: DEFAULT.counter(
        "fires_total",
        "Failpoint actions actually injected, by point and action.",
        "failpoint"))


@dataclass
class RecoveryMetrics:
    """Startup reconciliation (consensus/replay.py): every legal
    cross-store skew a crash can leave is enumerated and healed on
    boot, and each heal is counted here — a fleet whose repair
    counters climb without chaos injections has a disk/crash problem
    worth paging on."""
    repairs: Counter = field(default_factory=lambda: DEFAULT.counter(
        "repairs_total",
        "Cross-store skews healed by the startup reconciler, by "
        "repair kind.", "recovery"))
    blocks_replayed: Counter = field(
        default_factory=lambda: DEFAULT.counter(
            "blocks_replayed_total",
            "Blocks replayed into the app or re-applied to state "
            "during startup reconciliation.", "recovery"))
    quarantined_files: Gauge = field(
        default_factory=lambda: DEFAULT.gauge(
            "quarantined_files",
            "Corruption-evidence files (*.corrupt.NNN) present in the "
            "data/WAL dirs at the last startup scan.", "recovery"))


@dataclass
class RPCMetrics:
    """JSON-RPC server overload surface (this framework's addition):
    the 429-style limiter and the bounded websocket event queue."""
    ws_events_dropped: Counter = field(
        default_factory=lambda: DEFAULT.counter(
            "ws_events_dropped_total",
            "Websocket events dropped (drop-oldest) from the bounded "
            "client notification queue.", "rpc"))
    requests_rejected: Counter = field(
        default_factory=lambda: DEFAULT.counter(
            "requests_rejected_total",
            "JSON-RPC requests rejected by the overload limiter "
            "(429-style), by reason.", "rpc"))
    requests_in_flight: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "requests_in_flight",
        "JSON-RPC requests currently being handled.", "rpc"))


@dataclass
class OverloadMetrics:
    """The overload controller's aggregate view (libs/overload.py):
    one level gauge plus per-tracked-queue depth/capacity/shed — the
    numbers the liveness-under-overload e2e asserts on."""
    level: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "level",
        "Aggregate overload level (0 ok, 1 pressured, 2 shedding).",
        "overload"))
    queue_depth: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "queue_depth",
        "Current depth of each tracked bounded queue.", "overload"))
    queue_capacity: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "queue_capacity",
        "Configured bound of each tracked queue.", "overload"))
    shed: Counter = field(default_factory=lambda: DEFAULT.counter(
        "shed_total",
        "Items dropped by shedding policy, by tracked queue.",
        "overload"))


@dataclass
class TracingMetrics:
    """The generic half of the tracing→metrics bridge: span kinds with
    no dedicated histogram land here, labelled by kind."""
    span_seconds: Histogram = field(default_factory=lambda: DEFAULT.histogram(
        "span_seconds", "Span duration by registered kind "
        "(bridge-fed from every span close).", "tracing"))
    spans_dropped: Counter = field(default_factory=lambda: DEFAULT.counter(
        "spans_dropped_total",
        "Spans evicted from the trace ring buffer (overflow) — "
        "non-zero means /debug/trace is a suffix of the timeline, "
        "not the whole of it.", "tracing"))


_SINGLETONS: dict[str, object] = {}
_SINGLETONS_LOCK = threading.Lock()


def _singleton(key: str, cls):
    # NOT setdefault(key, cls()): constructing the dataclass registers
    # its metrics into DEFAULT, so the constructor must only ever run
    # once per key — and under a lock, because these accessors are
    # called from executor threads (BatchVerifier offload) as well as
    # the event loop; a first-call race would double-register a whole
    # metric family and corrupt the exposition output.
    with _SINGLETONS_LOCK:
        if key not in _SINGLETONS:
            _SINGLETONS[key] = cls()
        return _SINGLETONS[key]


def consensus_metrics() -> ConsensusMetrics:
    return _singleton("consensus", ConsensusMetrics)


def crypto_metrics() -> CryptoMetrics:
    return _singleton("crypto", CryptoMetrics)


def p2p_metrics() -> P2PMetrics:
    return _singleton("p2p", P2PMetrics)


def mempool_metrics() -> MempoolMetrics:
    return _singleton("mempool", MempoolMetrics)


def admission_metrics() -> AdmissionMetrics:
    return _singleton("admission", AdmissionMetrics)


def light_metrics() -> LightMetrics:
    return _singleton("light", LightMetrics)


def speculation_metrics() -> SpeculationMetrics:
    return _singleton("speculation", SpeculationMetrics)


def blockchain_metrics() -> BlockchainMetrics:
    return _singleton("blockchain", BlockchainMetrics)


def statesync_metrics() -> StateSyncMetrics:
    return _singleton("statesync", StateSyncMetrics)


def evidence_metrics() -> EvidenceMetrics:
    return _singleton("evidence", EvidenceMetrics)


def state_metrics() -> StateMetrics:
    return _singleton("state", StateMetrics)


def abci_metrics() -> ABCIMetrics:
    return _singleton("abci", ABCIMetrics)


def tpu_metrics() -> TPUMetrics:
    return _singleton("tpu", TPUMetrics)


def tracing_metrics() -> TracingMetrics:
    return _singleton("tracing", TracingMetrics)


def failpoint_metrics() -> FailpointMetrics:
    return _singleton("failpoint", FailpointMetrics)


def rpc_metrics() -> RPCMetrics:
    return _singleton("rpc", RPCMetrics)


def overload_metrics() -> OverloadMetrics:
    return _singleton("overload", OverloadMetrics)


def recovery_metrics() -> RecoveryMetrics:
    return _singleton("recovery", RecoveryMetrics)


# ------------------------------------------------- MetricsProvider wiring

@dataclass
class NodeMetrics:
    """The full per-module bundle one node records into — what the
    reference's MetricsProvider returns per subsystem
    (node/node.go:110-125), collapsed into one object because our
    modules share process-global singletons."""

    consensus: ConsensusMetrics
    crypto: CryptoMetrics
    p2p: P2PMetrics
    mempool: MempoolMetrics
    admission: AdmissionMetrics
    light: LightMetrics
    speculation: SpeculationMetrics
    blockchain: BlockchainMetrics
    statesync: StateSyncMetrics
    evidence: EvidenceMetrics
    state: StateMetrics
    abci: ABCIMetrics
    tpu: TPUMetrics
    tracing: TracingMetrics
    failpoint: FailpointMetrics
    rpc: RPCMetrics
    overload: OverloadMetrics
    recovery: RecoveryMetrics


def node_metrics() -> NodeMetrics:
    """Materialize every per-module metric family (idempotent). A
    scrape of a freshly-started node must show the full catalog, not
    just the families something has already recorded into."""
    return NodeMetrics(
        consensus=consensus_metrics(), crypto=crypto_metrics(),
        p2p=p2p_metrics(), mempool=mempool_metrics(),
        admission=admission_metrics(), light=light_metrics(),
        speculation=speculation_metrics(),
        blockchain=blockchain_metrics(), statesync=statesync_metrics(),
        evidence=evidence_metrics(), state=state_metrics(),
        abci=abci_metrics(), tpu=tpu_metrics(),
        tracing=tracing_metrics(), failpoint=failpoint_metrics(),
        rpc=rpc_metrics(), overload=overload_metrics(),
        recovery=recovery_metrics(),
    )


def metrics_provider(instrumentation):
    """reference: node/node.go:110-125 DefaultMetricsProvider — with
    `instrumentation.prometheus` on, the node eagerly constructs every
    subsystem's metric family at build time (so the first scrape is
    complete); off, modules keep lazily materializing only what they
    record into, the Nop analogue."""
    def provider(chain_id: str) -> NodeMetrics | None:
        if instrumentation.prometheus:
            return node_metrics()
        return None

    return provider


def all_module_metrics() -> dict[str, Metric]:
    """{metric_name: Metric} over every dataclass field of the full
    bundle — the declared catalog tools/check_metrics.py lints
    against."""
    out: dict[str, Metric] = {}
    nm = node_metrics()
    for module_field in dc_fields(nm):
        bundle = getattr(nm, module_field.name)
        for f in dc_fields(bundle):
            m = getattr(bundle, f.name)
            out[m.name] = m
    return out


# ------------------------------------------------ snapshot / delta (bench)

def snapshot(registry: Registry | None = None) -> dict:
    """Point-in-time copy of every metric's values, keyed by
    `name{labels}`. Counters/gauges map to floats; histograms to
    {"buckets": (...), "counts": [...], "sum": s}. Input to delta()."""
    reg = registry or DEFAULT
    with reg._lock:
        metrics = list(reg._metrics)
    out: dict = {}
    for m in metrics:
        if isinstance(m, Histogram):
            for key, s in list(m._series.items()):
                out[m.name + _fmt_labels(dict(key))] = {
                    "buckets": m.buckets,
                    "counts": list(s.counts),
                    "sum": s.sum,
                }
        else:
            for key, v in list(m._values.items()):
                out[m.name + _fmt_labels(dict(key))] = v
    return out


def _bucket_quantile(buckets, counts, q: float):
    """Prometheus-style histogram_quantile over one bucket-count
    vector: linear interpolation inside the bucket; the overflow
    bucket clamps to the largest finite bound."""
    total = sum(counts)
    if total == 0:
        return None
    rank = q * total
    cum = 0.0
    lo = 0.0
    for i, b in enumerate(buckets):
        prev = cum
        cum += counts[i]
        if cum >= rank:
            frac = (rank - prev) / counts[i] if counts[i] else 0.0
            return lo + (b - lo) * frac
        lo = b
    return buckets[-1]


def delta(before: dict, after: dict) -> dict:
    """What changed between two snapshot()s: counter/gauge increments
    (nonzero only) and, per histogram series with new observations,
    the count/sum delta plus p50/p95/p99 estimated from the bucket
    deltas — the BENCH `metrics_delta` payload."""
    out: dict = {}
    for key, val in after.items():
        prev = before.get(key)
        if isinstance(val, dict):
            pcounts = prev["counts"] if isinstance(prev, dict) \
                else [0] * len(val["counts"])
            dcounts = [a - b for a, b in zip(val["counts"], pcounts)]
            n = sum(dcounts)
            if n <= 0:
                continue
            psum = prev["sum"] if isinstance(prev, dict) else 0.0
            finite = val["buckets"]
            out[key] = {
                "count": n,
                "sum": round(val["sum"] - psum, 6),
                "p50": _bucket_quantile(finite, dcounts, 0.50),
                "p95": _bucket_quantile(finite, dcounts, 0.95),
                "p99": _bucket_quantile(finite, dcounts, 0.99),
            }
        else:
            d = val - (prev if isinstance(prev, float) else 0.0)
            if d != 0:
                out[key] = round(d, 6)
    return out


# ------------------------------------------------ tracing→metrics bridge

# Span kinds with a dedicated histogram; resolved lazily so importing
# this module does not force-construct the tpu family.
_BRIDGE_DEDICATED = {
    _tracing.CRYPTO_PACK: lambda: tpu_metrics().pack_seconds,
    _tracing.CRYPTO_DISPATCH: lambda: tpu_metrics().dispatch_seconds,
    _tracing.CRYPTO_DEVICE_EXEC: lambda: tpu_metrics().device_exec_seconds,
    _tracing.CRYPTO_READBACK: lambda: tpu_metrics().readback_seconds,
}
_BRIDGE_CACHE: dict[str, object] = {}


def span_metrics_sink(kind: str, seconds: float) -> None:
    """Installed into the global TRACER: every span close observes one
    histogram — the dedicated tpu stage histogram for the device
    pipeline kinds, tracing_span_seconds{kind=...} for the rest. The
    per-close cost is one dict lookup + one bucket scan (the bound
    handle is cached per kind), inside the tools/check_spans.py
    per-span overhead budget."""
    ob = _BRIDGE_CACHE.get(kind)
    if ob is None:
        mk = _BRIDGE_DEDICATED.get(kind)
        if mk is not None:
            h = mk()
            ob = _BoundHistogram(h.buckets, h._series_for(()))
        else:
            ob = tracing_metrics().span_seconds.labels(kind=kind)
        _BRIDGE_CACHE[kind] = ob
    ob.observe(seconds)


def span_drop_sink(n: int) -> None:
    """Installed into the global TRACER: counts ring-buffer evictions
    so a truncated trace export is detectable from /metrics alone."""
    tracing_metrics().spans_dropped.inc(n)


# One instrumentation point, two exports: the ring buffer keeps the
# per-event timeline, the sink keeps the aggregate histograms.
_tracing.TRACER.set_metrics_sink(span_metrics_sink)
_tracing.TRACER.set_drop_sink(span_drop_sink)
