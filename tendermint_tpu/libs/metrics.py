"""Prometheus-style metrics (reference: libs + per-module metrics.go,
docs/nodes/metrics.md:21-52).

Counters, gauges and histograms with optional labels, collected in a
process-global registry and rendered in the Prometheus text exposition
format. Served on the RPC listener at GET /metrics and (when
`instrumentation.prometheus` is on) on a dedicated listener, mirroring
the reference's MetricsProvider wiring (node/node.go:110-125).

Implementation is deliberately tiny and allocation-light: consensus
hot paths (vote batches, device launches) record into plain floats
under no lock — the event-loop/worker structure makes races harmless
for monitoring data, same stance as Prometheus client libs' relaxed
atomicity on Python.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


def _fmt_labels(labels: dict[str, str] | None) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{v}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class Metric:
    def __init__(self, name: str, help_: str, namespace: str = ""):
        self.name = f"{namespace}_{name}" if namespace else name
        self.help = help_

    def render(self) -> list[str]:  # pragma: no cover - abstract
        raise NotImplementedError


class Counter(Metric):
    kind = "counter"

    def __init__(self, name: str, help_: str, namespace: str = ""):
        super().__init__(name, help_, namespace)
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        return self._values.get(tuple(sorted(labels.items())), 0.0)

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} {self.kind}"]
        for key, v in sorted(self._values.items()):
            out.append(f"{self.name}{_fmt_labels(dict(key))} {_fmt_value(v)}")
        if not self._values:
            out.append(f"{self.name} 0")
        return out


class Gauge(Counter):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        self._values[tuple(sorted(labels.items()))] = float(value)

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)


_DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name: str, help_: str, namespace: str = "",
                 buckets: tuple = _DEFAULT_BUCKETS):
        super().__init__(name, help_, namespace)
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._n = 0

    def observe(self, value: float) -> None:
        self._sum += value
        self._n += 1
        for i, b in enumerate(self.buckets):
            if value <= b:
                self._counts[i] += 1
                return
        self._counts[-1] += 1

    @property
    def count(self) -> int:
        return self._n

    @property
    def sum(self) -> float:
        return self._sum

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        cum = 0
        for i, b in enumerate(self.buckets):
            cum += self._counts[i]
            out.append(f'{self.name}_bucket{{le="{_fmt_value(b)}"}} {cum}')
        cum += self._counts[-1]
        out.append(f'{self.name}_bucket{{le="+Inf"}} {cum}')
        out.append(f"{self.name}_sum {_fmt_value(self._sum)}")
        out.append(f"{self.name}_count {self._n}")
        return out

    class _Timer:
        def __init__(self, h: "Histogram"):
            self._h = h

        def __enter__(self):
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self._h.observe(time.perf_counter() - self._t0)
            return False

    def time(self) -> "_Timer":
        return self._Timer(self)


class Registry:
    def __init__(self):
        self._metrics: list[Metric] = []
        self._lock = threading.Lock()

    def register(self, m: Metric) -> Metric:
        with self._lock:
            self._metrics.append(m)
        return m

    def counter(self, name, help_, namespace="") -> Counter:
        return self.register(Counter(name, help_, namespace))

    def gauge(self, name, help_, namespace="") -> Gauge:
        return self.register(Gauge(name, help_, namespace))

    def histogram(self, name, help_, namespace="",
                  buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self.register(Histogram(name, help_, namespace, buckets))

    def render_text(self) -> str:
        with self._lock:
            metrics = list(self._metrics)
        lines = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


# Process-global registry — the MetricsProvider analogue.
DEFAULT = Registry()


@dataclass
class ConsensusMetrics:
    """reference: consensus/metrics.go."""
    height: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "height", "Height of the chain.", "consensus"))
    rounds: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "rounds", "Round of the chain.", "consensus"))
    validators: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "validators", "Number of validators.", "consensus"))
    validators_power: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "validators_power", "Total voting power of validators.", "consensus"))
    missing_validators: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "missing_validators", "Validators absent from the last commit.",
        "consensus"))
    missing_validators_power: Gauge = field(
        default_factory=lambda: DEFAULT.gauge(
            "missing_validators_power",
            "Voting power of validators absent from the last commit.",
            "consensus"))
    byzantine_validators: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "byzantine_validators", "Validators that equivocated.", "consensus"))
    byzantine_validators_power: Gauge = field(
        default_factory=lambda: DEFAULT.gauge(
            "byzantine_validators_power",
            "Voting power of validators that equivocated.", "consensus"))
    validator_power: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "validator_power", "This node's voting power (0 if not a "
        "validator).", "consensus"))
    validator_last_signed_height: Gauge = field(
        default_factory=lambda: DEFAULT.gauge(
            "validator_last_signed_height",
            "Last height this node's precommit made a commit.",
            "consensus"))
    validator_missed_blocks: Counter = field(
        default_factory=lambda: DEFAULT.counter(
            "validator_missed_blocks",
            "Commits missing this node's precommit.", "consensus"))
    fast_syncing: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "fast_syncing", "1 while fast sync is running.", "consensus"))
    state_syncing: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "state_syncing", "1 while state sync is running.", "consensus"))
    num_txs: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "num_txs", "Transactions in the latest block.", "consensus"))
    block_size_bytes: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "block_size_bytes", "Size of the latest block.", "consensus"))
    total_txs: Counter = field(default_factory=lambda: DEFAULT.counter(
        "total_txs", "Total transactions committed.", "consensus"))
    block_interval_seconds: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "block_interval_seconds", "Time between blocks.", "consensus",
            buckets=(0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60)))
    fast_sync_blocks: Counter = field(default_factory=lambda: DEFAULT.counter(
        "fast_sync_blocks", "Blocks applied via fast sync.", "consensus"))
    # --- TPU batch-verify observability (new capability; no reference
    # equivalent): these are the numbers that justify _DEVICE_THRESHOLD
    # and the micro-batch window empirically.
    vote_batch_size: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "vote_batch_size", "Votes per micro-batch.", "consensus",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)))
    vote_batch_wait_seconds: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "vote_batch_wait_seconds",
            "Window wait before a vote micro-batch verified.", "consensus"))


@dataclass
class CryptoMetrics:
    """Batch-verifier instrumentation (new; the SURVEY §6 speedup
    denominators come straight from these)."""
    batch_lanes: Counter = field(default_factory=lambda: DEFAULT.counter(
        "batch_lanes_total", "Signature lanes verified, by backend.",
        "crypto"))
    batch_seconds: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "batch_verify_seconds", "Wall time per verify() call.",
            "crypto"))
    device_launches: Counter = field(default_factory=lambda: DEFAULT.counter(
        "device_launches_total", "Device kernel launches.", "crypto"))
    invalid_sigs: Counter = field(default_factory=lambda: DEFAULT.counter(
        "invalid_signatures_total", "Lanes that failed verification.",
        "crypto"))
    device_failures: Counter = field(default_factory=lambda: DEFAULT.counter(
        "device_failures_total",
        "Device batch launches that raised; host degradation engaged.",
        "crypto"))


@dataclass
class P2PMetrics:
    """reference: p2p/metrics.go."""
    peers: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "peers", "Connected peers.", "p2p"))
    peer_receive_bytes: Counter = field(
        default_factory=lambda: DEFAULT.counter(
            "peer_receive_bytes_total", "Bytes received, by channel.",
            "p2p"))
    peer_send_bytes: Counter = field(default_factory=lambda: DEFAULT.counter(
        "peer_send_bytes_total", "Bytes sent, by channel.", "p2p"))
    pending_send_bytes: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "pending_send_bytes", "Pending bytes across peers.", "p2p"))


@dataclass
class MempoolMetrics:
    """reference: mempool/metrics.go."""
    size: Gauge = field(default_factory=lambda: DEFAULT.gauge(
        "size", "Transactions in the mempool.", "mempool"))
    tx_size_bytes: Histogram = field(default_factory=lambda: DEFAULT.histogram(
        "tx_size_bytes", "Transaction sizes.", "mempool",
        buckets=(32, 128, 512, 2048, 8192, 32768, 131072)))
    failed_txs: Counter = field(default_factory=lambda: DEFAULT.counter(
        "failed_txs", "CheckTx rejections.", "mempool"))
    recheck_times: Counter = field(default_factory=lambda: DEFAULT.counter(
        "recheck_times", "Transactions rechecked after commit.", "mempool"))


@dataclass
class StateMetrics:
    """reference: state/metrics.go."""
    block_processing_seconds: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "block_processing_seconds", "ApplyBlock wall time.", "state"))
    commit_verify_seconds: Histogram = field(
        default_factory=lambda: DEFAULT.histogram(
            "commit_verify_seconds",
            "LastCommit signature-batch wall time.", "state"))


_SINGLETONS: dict[str, object] = {}
_SINGLETONS_LOCK = threading.Lock()


def _singleton(key: str, cls):
    # NOT setdefault(key, cls()): constructing the dataclass registers
    # its metrics into DEFAULT, so the constructor must only ever run
    # once per key — and under a lock, because these accessors are
    # called from executor threads (BatchVerifier offload) as well as
    # the event loop; a first-call race would double-register a whole
    # metric family and corrupt the exposition output.
    with _SINGLETONS_LOCK:
        if key not in _SINGLETONS:
            _SINGLETONS[key] = cls()
        return _SINGLETONS[key]


def consensus_metrics() -> ConsensusMetrics:
    return _singleton("consensus", ConsensusMetrics)


def crypto_metrics() -> CryptoMetrics:
    return _singleton("crypto", CryptoMetrics)


def p2p_metrics() -> P2PMetrics:
    return _singleton("p2p", P2PMetrics)


def mempool_metrics() -> MempoolMetrics:
    return _singleton("mempool", MempoolMetrics)


def state_metrics() -> StateMetrics:
    return _singleton("state", StateMetrics)
