"""Support libraries (reference capability: libs/ — service lifecycle,
logging, pubsub with query DSL, bit arrays, rate limiting, failpoints)."""
