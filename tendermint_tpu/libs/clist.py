"""Concurrent linked list with waitable iteration (reference:
libs/clist/clist.go).

The reference's CList lets N reader goroutines walk a list while a
writer appends/removes: each element keeps next/prev pointers plus
"next-ready" wait channels, and removal tombstones the element so a
parked iterator can skip over it. Consumers: the mempool's per-peer
broadcast routines and the evidence pool's gossip routine.

Here the same contract is asyncio-native: ``front_wait``/``next_wait``
park on an asyncio.Event that the writer sets on push_back. Removal
marks the element and detaches it, but a parked iterator holding the
element can still follow its (frozen) next pointer forward.
"""

from __future__ import annotations

import asyncio
from typing import Any


class CElement:
    __slots__ = ("value", "_next", "_prev", "removed", "_next_ev")

    def __init__(self, value: Any):
        self.value = value
        self._next: CElement | None = None
        self._prev: CElement | None = None
        self.removed = False
        self._next_ev = asyncio.Event()

    def next(self) -> "CElement | None":
        return self._next

    def prev(self) -> "CElement | None":
        return self._prev

    async def next_wait(self) -> "CElement | None":
        """Wait until this element has a successor or is removed.
        Returns the successor (None if this element was removed while
        parked — caller restarts from front)."""
        while self._next is None and not self.removed:
            self._next_ev.clear()
            await self._next_ev.wait()
        return self._next


class CList:
    def __init__(self):
        self._head: CElement | None = None
        self._tail: CElement | None = None
        self._len = 0
        self._front_ev = asyncio.Event()

    def __len__(self) -> int:
        return self._len

    def front(self) -> CElement | None:
        return self._head

    def back(self) -> CElement | None:
        return self._tail

    async def front_wait(self) -> CElement:
        while self._head is None:
            self._front_ev.clear()
            await self._front_ev.wait()
        return self._head

    def push_back(self, value: Any) -> CElement:
        e = CElement(value)
        if self._tail is None:
            self._head = self._tail = e
            self._front_ev.set()
        else:
            e._prev = self._tail
            self._tail._next = e
            self._tail._next_ev.set()
            self._tail = e
        self._len += 1
        return e

    def remove(self, e: CElement) -> Any:
        if e.removed:
            return e.value
        e.removed = True
        if e._prev is not None:
            e._prev._next = e._next
        else:
            self._head = e._next
        if e._next is not None:
            e._next._prev = e._prev
        else:
            self._tail = e._prev
        self._len -= 1
        # wake iterators parked on this element so they can re-anchor;
        # e._next stays frozen so a holder can walk forward.
        e._next_ev.set()
        if self._head is None:
            self._front_ev.clear()
        return e.value

    def __iter__(self):
        e = self._head
        while e is not None:
            if not e.removed:
                yield e.value
            e = e._next
