"""RFC3339 <-> unix-ns conversion (reference tmjson encodes times as
RFC3339 strings with nanosecond fractions; this repo's native types
carry ns ints)."""

from __future__ import annotations

import re
from datetime import datetime, timedelta, timezone

NS = 1_000_000_000
_EPOCH = datetime(1970, 1, 1, tzinfo=timezone.utc)
_RX = re.compile(
    r"^(\d{4}-\d{2}-\d{2}[Tt ]\d{2}:\d{2}:\d{2})"   # date-time
    r"(\.\d+)?"                                       # optional fraction
    r"(?:[Zz]|([+-]\d{2}:\d{2}))$"                    # Z or UTC offset
)


def rfc3339_to_ns(s: str) -> int:
    """'2020-10-21T08:44:52.160326989Z' (up to ns fraction, Z or a
    numeric UTC offset — Go emits offsets for non-UTC locations) ->
    unix ns. The Go zero time ('0001-01-01T00:00:00Z') and any
    pre-1970 date yield a negative ns count."""
    m = _RX.match(s.strip())
    if m is None:
        raise ValueError(f"not an RFC3339 timestamp: {s!r}")
    base, frac, off = m.groups()
    dt = datetime.fromisoformat(base.replace("t", "T") + (off or "+00:00"))
    ns = round((dt - _EPOCH).total_seconds()) * NS
    if frac:
        ns += int(frac[1:].ljust(9, "0")[:9])
    return ns


def ns_to_rfc3339(ns: int) -> str:
    dt = _EPOCH + timedelta(seconds=ns // NS)
    frac = ns % NS
    # manual formatting: strftime("%Y") does not zero-pad years < 1000
    # (the Go zero time would render as invalid '1-01-01T...')
    out = (f"{dt.year:04d}-{dt.month:02d}-{dt.day:02d}T"
           f"{dt.hour:02d}:{dt.minute:02d}:{dt.second:02d}")
    if frac:
        out += f".{frac:09d}".rstrip("0")
    return out + "Z"
