"""Async service lifecycle (reference: libs/service/service.go).

The reference's BaseService gives every component uniform
Start/Stop/Reset semantics with idempotence guarantees. Here services
are asyncio-native: on_start may spawn tasks via ``spawn`` which are
cancelled and awaited on stop.
"""

from __future__ import annotations

import asyncio
import logging


class ServiceError(Exception):
    pass


class AlreadyStarted(ServiceError):
    pass


class NotStarted(ServiceError):
    pass


class Service:
    """Base class with idempotent start/stop and task supervision."""

    def __init__(self, name: str | None = None, logger: logging.Logger | None = None):
        self.name = name or type(self).__name__
        self.logger = logger or logging.getLogger(self.name)
        self._started = False
        self._stopped = False
        self._tasks: list[asyncio.Task] = []

    @property
    def is_running(self) -> bool:
        return self._started and not self._stopped

    async def start(self) -> None:
        if self._started:
            raise AlreadyStarted(f"{self.name} already started")
        self._started = True
        self._stopped = False
        self.logger.debug("starting %s", self.name)
        await self.on_start()

    async def stop(self) -> None:
        if not self._started:
            raise NotStarted(f"{self.name} not started")
        if self._stopped:
            return
        self._stopped = True
        self.logger.debug("stopping %s", self.name)
        await self.on_stop()
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks.clear()

    async def restart(self) -> None:
        if self._started and not self._stopped:
            await self.stop()
        self._started = False
        await self.start()

    def spawn(self, coro, name: str | None = None) -> asyncio.Task:
        """Run a coroutine under this service's supervision."""
        task = asyncio.get_running_loop().create_task(coro, name=name)
        self._tasks.append(task)
        task.add_done_callback(self._on_task_done)
        return task

    def _on_task_done(self, task: asyncio.Task) -> None:
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None:
            self.logger.error("task %s crashed: %r", task.get_name(), exc)
            self.on_task_crash(task, exc)

    def on_task_crash(self, task: asyncio.Task, exc: BaseException) -> None:
        """Override for crash policy (default: log only)."""

    async def on_start(self) -> None:  # pragma: no cover - interface
        pass

    async def on_stop(self) -> None:  # pragma: no cover - interface
        pass

    async def wait(self) -> None:
        """Block until all supervised tasks finish."""
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
