"""Bit arrays for vote bookkeeping (reference: libs/bits/bit_array.go)."""

from __future__ import annotations

import secrets


class BitArray:
    __slots__ = ("size", "_bits")

    def __init__(self, size: int):
        if size < 0:
            raise ValueError("negative size")
        self.size = size
        self._bits = 0

    def get(self, i: int) -> bool:
        if not 0 <= i < self.size:
            return False
        return bool((self._bits >> i) & 1)

    def set(self, i: int, v: bool) -> bool:
        if not 0 <= i < self.size:
            return False
        if v:
            self._bits |= 1 << i
        else:
            self._bits &= ~(1 << i)
        return True

    def is_empty(self) -> bool:
        return self._bits == 0

    def is_full(self) -> bool:
        return self.size > 0 and self._bits == (1 << self.size) - 1

    def count(self) -> int:
        return bin(self._bits).count("1")

    def copy(self) -> "BitArray":
        b = BitArray(self.size)
        b._bits = self._bits
        return b

    def or_(self, other: "BitArray") -> "BitArray":
        b = BitArray(max(self.size, other.size))
        b._bits = self._bits | other._bits
        return b

    def and_(self, other: "BitArray") -> "BitArray":
        b = BitArray(min(self.size, other.size))
        b._bits = self._bits & other._bits & ((1 << b.size) - 1)
        return b

    def not_(self) -> "BitArray":
        b = BitArray(self.size)
        b._bits = ~self._bits & ((1 << self.size) - 1)
        return b

    def sub(self, other: "BitArray") -> "BitArray":
        """Bits set in self but not in other."""
        b = BitArray(self.size)
        mask = other._bits & ((1 << self.size) - 1)
        b._bits = self._bits & ~mask
        return b

    def pick_random(self) -> tuple[int, bool]:
        """A uniformly random set bit's index (for gossip selection)."""
        idxs = [i for i in range(self.size) if self.get(i)]
        if not idxs:
            return 0, False
        return idxs[secrets.randbelow(len(idxs))], True

    def to_bytes(self) -> bytes:
        nbytes = (self.size + 7) // 8
        return self._bits.to_bytes(nbytes, "little")

    @classmethod
    def from_bytes(cls, size: int, data: bytes) -> "BitArray":
        b = cls(size)
        b._bits = int.from_bytes(data, "little") & ((1 << size) - 1) if size else 0
        return b

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, BitArray)
            and self.size == other.size
            and self._bits == other._bits
        )

    def __repr__(self) -> str:
        return "BitArray{%s}" % "".join("x" if self.get(i) else "_" for i in range(self.size))
