"""Flow-rate measurement and limiting (reference: libs/flowrate).

An EWMA byte-rate monitor used by p2p connections to cap send/recv
rates. ``limit`` returns how many bytes may be transferred now to stay
under the target rate; the caller sleeps when 0.
"""

from __future__ import annotations

from . import clock


class Monitor:
    def __init__(self, sample_period: float = 0.1, window: float = 1.0):
        self._period = sample_period
        self._alpha = sample_period / window
        self._rate = 0.0
        self._sample_bytes = 0
        self._sample_start = clock.monotonic()
        self.total = 0
        self.start_time = self._sample_start
        self._tokens = 0.0
        self._token_time: float | None = None

    def update(self, n: int) -> None:
        self.total += n
        self._sample_bytes += n
        now = clock.monotonic()
        elapsed = now - self._sample_start
        if elapsed >= self._period:
            inst = self._sample_bytes / elapsed
            self._rate += self._alpha * (inst - self._rate)
            self._sample_bytes = 0
            self._sample_start = now

    @property
    def rate(self) -> float:
        return self._rate

    def limit(self, want: int, rate_limit: int) -> int:
        """Bytes allowed now under a token bucket with ~1 s of burst.

        Idle time earns tokens only up to the burst cap, so a
        long-idle connection cannot blast unbounded backlog (the
        lifetime-average formulation would allow exactly that).
        """
        if rate_limit <= 0:
            return want
        self._refill(rate_limit)
        allowed = min(want, int(self._tokens))
        self._tokens -= allowed
        return allowed

    def _refill(self, rate_limit: int) -> None:
        now = clock.monotonic()
        if self._token_time is None:
            self._tokens = float(rate_limit)  # full initial burst
        else:
            self._tokens = min(
                float(rate_limit),
                self._tokens + rate_limit * (now - self._token_time),
            )
        self._token_time = now

    def sleep_time(self, rate_limit: int) -> float:
        """How long until at least one byte of budget frees up."""
        if rate_limit <= 0:
            return 0.0
        self._refill(rate_limit)
        if self._tokens >= 1:
            return 0.0
        return (1 - self._tokens) / rate_limit
