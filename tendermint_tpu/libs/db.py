"""Key-value database abstraction (the reference delegates to tm-db;
store/store.go:33 and state/store.go assume get/set/batch/iterate).

MemDB: sorted in-memory map. FileDB: crash-safe append-only record log
with an in-memory index — every set/delete appends a crc-framed record;
atomic batches append one multi-record entry; compaction rewrites the
live set. Durability here is belt-and-braces: consensus-critical
recovery rides the WAL (consensus/wal.py), matching the reference's
trust split between tm-db and the WAL."""

from __future__ import annotations

import bisect
import logging
import os
import struct
import zlib

logger = logging.getLogger("libs.db")


class DB:
    def get(self, key: bytes) -> bytes | None:
        raise NotImplementedError

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def set(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: bytes) -> None:
        raise NotImplementedError

    def write_batch(self, ops: list[tuple[bytes, bytes | None]]) -> None:
        """Atomically apply [(key, value-or-None-to-delete)]."""
        raise NotImplementedError

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        """Yield (key, value) with start <= key < end, key-ascending."""
        raise NotImplementedError

    def iterate_prefix(self, prefix: bytes):
        end = _prefix_end(prefix)
        return self.iterate(prefix, end)

    def close(self) -> None:
        pass


def _prefix_end(prefix: bytes) -> bytes | None:
    p = bytearray(prefix)
    for i in reversed(range(len(p))):
        if p[i] != 0xFF:
            p[i] += 1
            return bytes(p[: i + 1])
    return None  # all 0xff: no upper bound


class MemDB(DB):
    def __init__(self):
        self._m: dict[bytes, bytes] = {}
        self._keys: list[bytes] = []  # sorted view, rebuilt lazily
        self._dirty = False

    def get(self, key: bytes) -> bytes | None:
        return self._m.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        if key not in self._m:
            self._dirty = True
        self._m[key] = value

    def delete(self, key: bytes) -> None:
        if self._m.pop(key, None) is not None:
            self._dirty = True

    def write_batch(self, ops) -> None:
        for k, v in ops:
            if v is None:
                self.delete(k)
            else:
                self.set(k, v)

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        if self._dirty:
            self._keys = sorted(self._m)
            self._dirty = False
        i = bisect.bisect_left(self._keys, start)
        while i < len(self._keys):
            k = self._keys[i]
            if end is not None and k >= end:
                return
            if k in self._m:  # may have been deleted since sort
                yield k, self._m[k]
            i += 1


# FileDB record: u32 crc | u32 len | payload; payload = batch of
# (u8 op, u32 klen, key, [u32 vlen, value]) entries. op 0=set 1=del.
_HDR = struct.Struct("<II")


class SqliteDB(DB):
    """Ordered persistent KV store on sqlite — the tm-db/goleveldb
    analogue (reference state/store.go:223, store/store.go:248 assume
    ordered iteration + range deletes for pruning). Unlike FileDB the
    live set is NOT memory-resident and persistence is not an
    O(whole-DB) rewrite: restart cost and RSS are O(working set),
    chain length is bounded by disk, and pruning deletes ranges in
    place. sqlite WAL mode + synchronous=FULL gives the same
    fsync-per-write durability contract FileDB had."""

    _CHUNK = 512  # iteration page size
    SYNCHRONOUS = ("OFF", "NORMAL", "FULL")

    def __init__(self, path: str, synchronous: str = "FULL"):
        import sqlite3

        self.path = path
        synchronous = synchronous.upper()
        if synchronous not in self.SYNCHRONOUS:
            raise ValueError(
                f"db synchronous must be one of {self.SYNCHRONOUS}, "
                f"not {synchronous!r}")
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # autocommit mode; batches use explicit BEGIN IMMEDIATE.
        # check_same_thread off: the node is asyncio-single-threaded
        # but debug/tooling paths may touch a store from a worker
        # thread; sqlite itself is serialized-mode here.
        self._c = sqlite3.connect(path, isolation_level=None,
                                  check_same_thread=False)
        self._c.execute("PRAGMA journal_mode=WAL")
        # FULL (default) fsyncs the sqlite WAL on every commit — the
        # per-height durability the commit pipeline assumes. NORMAL/OFF
        # are opt-in (config base.db_synchronous) for replayable
        # non-validator workloads; a crash can then lose the tail.
        self._c.execute(f"PRAGMA synchronous={synchronous}")
        self._c.execute(
            "CREATE TABLE IF NOT EXISTS kv ("
            "k BLOB PRIMARY KEY, v BLOB NOT NULL) WITHOUT ROWID")

    def get(self, key: bytes) -> bytes | None:
        row = self._c.execute(
            "SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
        return None if row is None else bytes(row[0])

    def set(self, key: bytes, value: bytes) -> None:
        from . import failpoints

        failpoints.hit("db.set")
        self._c.execute(
            "INSERT INTO kv (k, v) VALUES (?, ?) "
            "ON CONFLICT(k) DO UPDATE SET v = excluded.v", (key, value))

    def delete(self, key: bytes) -> None:
        self._c.execute("DELETE FROM kv WHERE k = ?", (key,))

    def write_batch(self, ops) -> None:
        from . import failpoints

        failpoints.hit("db.set")
        self._c.execute("BEGIN IMMEDIATE")
        try:
            for k, v in ops:
                if v is None:
                    self._c.execute("DELETE FROM kv WHERE k = ?", (k,))
                else:
                    self._c.execute(
                        "INSERT INTO kv (k, v) VALUES (?, ?) "
                        "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                        (k, v))
            # COMMIT inside the guard: if it fails (disk full, BUSY)
            # the transaction must still be rolled back, or every
            # later BEGIN dies with "transaction within a transaction"
            self._c.execute("COMMIT")
        except BaseException:
            try:
                self._c.execute("ROLLBACK")
            except Exception:
                pass  # some COMMIT failures already ended the txn
            raise

    def iterate(self, start: bytes = b"", end: bytes | None = None):
        # Stateless pagination (fresh statement per page, resuming
        # just past the last yielded key): callers may write between
        # yields — e.g. gather-then-prune loops — without invalidating
        # the scan.
        cur = start
        while True:
            if end is None:
                rows = self._c.execute(
                    "SELECT k, v FROM kv WHERE k >= ? ORDER BY k "
                    "LIMIT ?", (cur, self._CHUNK)).fetchall()
            else:
                rows = self._c.execute(
                    "SELECT k, v FROM kv WHERE k >= ? AND k < ? "
                    "ORDER BY k LIMIT ?",
                    (cur, end, self._CHUNK)).fetchall()
            for k, v in rows:
                yield bytes(k), bytes(v)
            if len(rows) < self._CHUNK:
                return
            cur = bytes(rows[-1][0]) + b"\x00"  # k > last

    def close(self) -> None:
        self._c.close()


class FileDB(MemDB):
    """Log-structured persistent DB. The whole live set is mirrored in
    memory (fine at this scale; the reference's goleveldb caches
    comparably for its working set)."""

    COMPACT_RATIO = 4  # compact when log bytes > ratio * live bytes

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self._live_bytes = 0
        self._log_bytes = 0
        if os.path.exists(path):
            self._replay()
        self._f = open(path, "ab")

    def _replay(self) -> None:
        with open(self.path, "rb") as f:
            data = f.read()
        pos = 0
        while pos + _HDR.size <= len(data):
            crc, ln = _HDR.unpack_from(data, pos)
            body = data[pos + _HDR.size : pos + _HDR.size + ln]
            if len(body) < ln or zlib.crc32(body) != crc:
                break  # torn tail from a crash: drop it
            self._apply_payload(body)
            pos += _HDR.size + ln
        if pos < len(data):
            # Torn tail from a crash (or a bad disk): QUARANTINE the
            # bytes to <db>.corrupt.NNN before truncating, like the
            # consensus WAL's repair() — a truncate that cut more than
            # a crash tail must leave the evidence for post-mortem,
            # never silently destroy it.
            tail = data[pos:]
            qpath = self._quarantine_path()
            with open(qpath, "wb") as qf:
                qf.write(tail)
                qf.flush()
                os.fsync(qf.fileno())
            with open(self.path, "r+b") as f:
                f.truncate(pos)
            logger.warning(
                "FileDB replay: quarantined %d torn tail bytes of %s "
                "to %s", len(tail), self.path, qpath)
        self._log_bytes = pos
        self._live_bytes = sum(len(k) + len(v) for k, v in self._m.items())

    QUARANTINE_SLOTS = 8

    def _quarantine_path(self) -> str:
        """First free `<path>.corrupt.NNN` slot, capped: a crash-
        looping node (chaos kill perturbations) must not accumulate
        quarantine files without bound. The earliest slots — the first
        evidence, usually the interesting one — are preserved; once
        all slots exist, the NEWEST slot is reused."""
        for n in range(self.QUARANTINE_SLOTS):
            p = f"{self.path}.corrupt.{n:03d}"
            if not os.path.exists(p):
                return p
        return f"{self.path}.corrupt.{self.QUARANTINE_SLOTS - 1:03d}"

    def _apply_payload(self, body: bytes) -> None:
        pos = 0
        while pos < len(body):
            op = body[pos]
            klen = struct.unpack_from("<I", body, pos + 1)[0]
            key = body[pos + 5 : pos + 5 + klen]
            pos += 5 + klen
            if op == 0:
                vlen = struct.unpack_from("<I", body, pos)[0]
                val = body[pos + 4 : pos + 4 + vlen]
                pos += 4 + vlen
                super().set(key, val)
            else:
                super().delete(key)

    def _append(self, payload: bytes) -> None:
        """Write + fsync ONE crc-framed record. Called BEFORE the ops
        are applied to the in-memory mirror: an append that raises
        (injected db.set error, disk full) must leave memory and disk
        agreeing — the old ordering mutated memory first, and a failed
        append then left the process serving state the log never saw
        (divergence that silently "healed" wrong on restart)."""
        from . import failpoints

        failpoints.hit("db.set")
        rec = _HDR.pack(zlib.crc32(payload), len(payload)) + payload
        self._f.write(rec)
        self._f.flush()
        os.fsync(self._f.fileno())
        self._log_bytes += len(rec)

    def _maybe_compact(self) -> None:
        # separate from _append: compaction rewrites the log from the
        # in-memory mirror, so it must only ever run AFTER the ops of
        # the record just appended have been applied to memory —
        # compacting in between would drop them from the rewritten log.
        if (
            self._log_bytes > 1 << 20
            and self._log_bytes > self.COMPACT_RATIO * max(self._live_bytes, 1)
        ):
            self.compact()

    @staticmethod
    def _enc_set(key: bytes, value: bytes) -> bytes:
        return b"\x00" + struct.pack("<I", len(key)) + key + struct.pack(
            "<I", len(value)
        ) + value

    @staticmethod
    def _enc_del(key: bytes) -> bytes:
        return b"\x01" + struct.pack("<I", len(key)) + key

    def set(self, key: bytes, value: bytes) -> None:
        self._append(self._enc_set(key, value))
        old = self._m.get(key)
        super().set(key, value)
        self._live_bytes += len(value) - (len(old) if old is not None else -len(key))
        self._maybe_compact()

    def delete(self, key: bytes) -> None:
        self._append(self._enc_del(key))
        old = self._m.get(key)
        if old is not None:
            self._live_bytes -= len(key) + len(old)
        super().delete(key)
        self._maybe_compact()

    def write_batch(self, ops) -> None:
        """ONE crc-framed record for the whole batch: a crash replays
        to all of the batch or none of it (the record's crc fails as a
        unit — _replay can never accept a half-applied batch). The
        encode → append → apply order means a failed append leaves the
        in-memory mirror untouched too."""
        ops = list(ops)
        payload = bytearray()
        for k, v in ops:
            payload += self._enc_del(k) if v is None else self._enc_set(k, v)
        if not payload:
            return
        self._append(bytes(payload))
        for k, v in ops:
            old = self._m.get(k)
            if v is None:
                if old is not None:
                    self._live_bytes -= len(k) + len(old)
                MemDB.delete(self, k)
            else:
                self._live_bytes += len(v) - (
                    len(old) if old is not None else -len(k)
                )
                MemDB.set(self, k, v)
        self._maybe_compact()

    def compact(self) -> None:
        tmp = self.path + ".compact"
        with open(tmp, "wb") as f:
            size = 0
            for k in sorted(self._m):
                payload = self._enc_set(k, self._m[k])
                rec = _HDR.pack(zlib.crc32(payload), len(payload)) + payload
                f.write(rec)
                size += len(rec)
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")
        self._log_bytes = size

    def close(self) -> None:
        self._f.close()
