"""Force JAX onto the CPU backend on machines whose sitecustomize
force-registers an accelerator plugin.

The env var alone is NOT enough here: this machine's axon site hook
overrides `JAX_PLATFORMS`, and when the TPU relay is wedged even
`jax.devices()` hangs in backend init. The config update after import
is what actually wins (same dance as tests/conftest.py). Call BEFORE
any device use; safe to call twice."""

from __future__ import annotations

import os


def force_cpu_backend() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          "/tmp/tm_tpu_jax_cache")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "1")
    import jax

    jax.config.update("jax_platforms", "cpu")
