"""Coordinated backpressure and load shedding (this framework's
addition; the reference relies on per-channel Go buffered channels and
has no aggregate overload picture).

The paper's premise makes the verify hot path device-bound, which
means the HOST side is what melts first under a tx/gossip/RPC flood:
unbounded queues grow until the event loop spends its time shuffling
backlog instead of advancing rounds. Every queue that can grow under
external input is therefore (a) bounded, (b) instrumented with a depth
gauge + a shed counter, and (c) registered with the process-global
OverloadController, which aggregates saturation into one
ok/pressured/shedding level published via metrics and GET /status.

The building blocks here are deliberately p2p/consensus-agnostic so
they import (and unit-test) without the heavier subsystems:

  OverloadController  registry of tracked queues -> overload level
  PriorityFunnel      two-class bounded queue (high blocks = real
                      backpressure; low drops-newest = shedding) used
                      as the consensus receive funnel
  DropOldestQueue     bounded queue that evicts the OLDEST entry on
                      overflow — for event streams where the newest
                      item is the valuable one (websocket events)
  SlowPeerTracker     pure strike/escalation bookkeeping behind the
                      p2p switch's slow-peer eviction

The closed QUEUES catalog below is linted by
tools/check_backpressure.py: every name must have a product call site,
and every depth gauge / shed counter label must come from the catalog.
"""

from __future__ import annotations

import asyncio
import collections

from . import clock
from dataclasses import dataclass

# Closed catalog of tracked bounded queues. Names label the
# overload_queue_depth / overload_queue_capacity gauges and the
# overload_shed_total counter (libs/metrics.py OverloadMetrics);
# tools/check_backpressure.py lints catalog <-> call sites <-> docs.
QUEUES = (
    "consensus.funnel.votes",   # high-priority consensus receive funnel
    "consensus.funnel.data",    # low-priority funnel (parts / catchup)
    "consensus.vote_buf",       # vote micro-batch verify buffer
    "mempool.pool",             # CheckTx admission (pool + app window)
    "mempool.preverify",        # admission-plane signature pre-verify
    "light.pending_verify",     # light serving plane verify backlog

    "rpc.http",                 # JSON-RPC in-flight request window
    "rpc.ws_events",            # websocket client event queue
    "p2p.send",                 # per-peer channel send queues (aggregate)
)

LEVELS = ("ok", "pressured", "shedding")
PRESSURED_RATIO = 0.75
SHEDDING_RATIO = 0.95


@dataclass
class _Tracked:
    name: str
    depth_fn: object       # () -> int
    capacity_fn: object    # () -> int
    advisory: bool = False  # export gauges but don't drive the level
    owner: object = None    # identity for owner-checked unregister


class OverloadController:
    """Aggregates queue-saturation signals into one overload level.

    Registration replaces by name (several in-process test nodes share
    the process-global singletons; monitoring tracks the latest).
    evaluate() is pull-based — depth functions run only on a scrape,
    a /status poll, or an explicit call, never on the hot path. The
    only hot-path entry point is shed(), one counter increment plus a
    monotonic timestamp."""

    def __init__(self, shed_window_s: float = 10.0):
        # level stays "shedding" for this long after the last shed so
        # a scrape cadence slower than a burst still sees it
        self.shed_window_s = shed_window_s
        self._queues: dict[str, _Tracked] = {}
        self._last_shed = 0.0

    # -- registration --

    def register(self, name: str, depth_fn, capacity,
                 advisory: bool = False, owner: object = None) -> None:
        """Track a bounded queue. `capacity` is an int or a callable
        (queues whose bound scales with peer count). `advisory` queues
        export depth/capacity gauges but do NOT drive the level: a
        drop-oldest buffer runs full as its NORMAL steady state under
        a slow consumer (old items evict), so its fill ratio is not a
        saturation signal — its shed events are. `owner` lets the
        registrant unregister on teardown without clobbering a newer
        same-name registration (several in-process nodes share this
        controller)."""
        cap_fn = capacity if callable(capacity) else (lambda c=capacity: c)
        self._queues[name] = _Tracked(name, depth_fn, cap_fn, advisory,
                                      owner)

    def unregister(self, name: str, owner: object = None) -> None:
        """Remove a tracked queue. With `owner` set, only removes the
        entry if that owner still holds the registration — a stopped
        service must not tear down a live replacement's gauges. A
        stopped owner's depth closure would otherwise keep reporting
        its frozen backlog (and retain its object graph) forever."""
        cur = self._queues.get(name)
        if cur is None:
            return
        if owner is not None and cur.owner is not None \
                and cur.owner is not owner:
            return
        del self._queues[name]

    # -- signals --

    def shed(self, queue: str, n: int = 1,
             advisory: bool = False) -> None:
        """Record `n` items dropped by policy from `queue`. Advisory
        sheds count (the counter is the drop evidence) but do not
        drive the level — a CLIENT-side drop-oldest eviction must not
        flip the host process's /status to shedding."""
        from .metrics import overload_metrics

        overload_metrics().shed.inc(n, queue=queue)
        if not advisory:
            self._last_shed = clock.monotonic()

    def recent_shed(self) -> bool:
        return clock.monotonic() - self._last_shed < self.shed_window_s

    # -- aggregation --

    def evaluate(self) -> dict:
        """Refresh every depth/capacity gauge and compute the level.
        A depth/capacity callable that raises (its owner was stopped
        mid-poll) reads as empty — monitoring must never take down the
        monitored."""
        from .metrics import overload_metrics

        met = overload_metrics()
        queues: dict[str, dict] = {}
        worst = 0.0
        for t in list(self._queues.values()):
            try:
                depth = float(t.depth_fn())
                cap = float(t.capacity_fn())
            except Exception:
                depth, cap = 0.0, 0.0
            fill = depth / cap if cap > 0 else 0.0
            met.queue_depth.set(depth, queue=t.name)
            met.queue_capacity.set(cap, queue=t.name)
            queues[t.name] = {"depth": int(depth), "capacity": int(cap),
                              "fill": round(fill, 3)}
            if not t.advisory:
                worst = max(worst, fill)
        if worst >= SHEDDING_RATIO or self.recent_shed():
            level = "shedding"
        elif worst >= PRESSURED_RATIO:
            level = "pressured"
        else:
            level = "ok"
        met.level.set(LEVELS.index(level))
        return {"level": level, "worst_fill": round(worst, 3),
                "queues": queues}

    def level(self) -> str:
        return self.evaluate()["level"]


# The process-global controller every subsystem registers with (the
# metrics-registry analogue).
CONTROLLER = OverloadController()


class PriorityFunnel:
    """Two-class bounded funnel for the consensus receive routine.

    High-class (state/vote/proposal) messages apply BACKPRESSURE: a
    full queue blocks the producing peer's recv task, exactly like the
    reference's `cs.peerMsgQueue <- msgInfo` channel send. Low-class
    (block parts / catchup data) messages SHED when full — they are
    re-gossiped on demand (missing-part / votebits reconciliation), so
    dropping the newest under flood is safe and keeps a data flood
    from ever wedging votes behind it. get() drains high first with
    BOUNDED aging: after LOW_SERVICE_INTERVAL consecutive high pops,
    a low item is served — but only one that ARRIVED BEFORE every
    queued high item. That order guard is load-bearing: consensus
    drops a block part processed before its proposal (the PartSet
    does not exist yet), so aging must never reorder a part ahead of
    the proposal it belongs to; at the same time, a sustained vote
    stream cannot starve parts forever, because the high queue keeps
    draining and its head sequence number always overtakes a waiting
    low item's."""

    # one aged low-class item per this many consecutive high pops
    LOW_SERVICE_INTERVAL = 8

    def __init__(self, high_capacity: int, low_capacity: int,
                 high_queue: str, low_queue: str,
                 controller: OverloadController | None = None):
        self.high_capacity = high_capacity
        self.low_capacity = low_capacity
        self.high_queue = high_queue
        self.low_queue = low_queue
        self._controller = controller or CONTROLLER
        self._high: collections.deque = collections.deque()  # (seq, item)
        self._low: collections.deque = collections.deque()   # (seq, item)
        self._high_streak = 0
        self._seq = 0  # arrival order across both classes
        self._not_empty = asyncio.Event()
        self._high_space = asyncio.Event()
        self._high_space.set()
        self._controller.register(high_queue, lambda: len(self._high),
                                  high_capacity, owner=self)
        self._controller.register(low_queue, lambda: len(self._low),
                                  low_capacity, owner=self)

    def close(self) -> None:
        """Drop this funnel's registrations on owner teardown (no-op
        if a newer funnel took over the names)."""
        self._controller.unregister(self.high_queue, owner=self)
        self._controller.unregister(self.low_queue, owner=self)

    def high_depth(self) -> int:
        return len(self._high)

    def low_depth(self) -> int:
        return len(self._low)

    def qsize(self) -> int:
        return len(self._high) + len(self._low)

    def pressured(self, ratio: float = 0.5) -> bool:
        """Cheap saturation probe for admission-time decisions (e.g.
        shed duplicate votes only once the funnel is half full)."""
        return (len(self._high) >= ratio * self.high_capacity
                or len(self._low) >= ratio * self.low_capacity)

    async def get(self):
        """Next message — high class first; after LOW_SERVICE_INTERVAL
        consecutive high pops, serve a low item IF it arrived before
        every queued high item (aging that can never invert arrival
        order — see the class docstring for why that guard is
        load-bearing). Single-consumer (the serialized receive
        routine); safe against the consumer's wait-future being
        cancelled between items."""
        while True:
            aged_low = (self._low
                        and self._high_streak >= self.LOW_SERVICE_INTERVAL
                        and (not self._high
                             or self._low[0][0] < self._high[0][0]))
            if self._high and not aged_low:
                _, item = self._high.popleft()
                self._high_streak += 1
                if len(self._high) < self.high_capacity:
                    self._high_space.set()
                return item
            if self._low:
                self._high_streak = 0
                return self._low.popleft()[1]
            self._not_empty.clear()
            await self._not_empty.wait()

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    async def put_high(self, item) -> None:
        """Blocking admit — backpressure onto the caller when full."""
        while len(self._high) >= self.high_capacity:
            self._high_space.clear()
            await self._high_space.wait()
        self._high.append((self._next_seq(), item))
        self._not_empty.set()

    def put_high_nowait(self, item) -> None:
        """Non-blocking admit; raises QueueFull (sync test hooks)."""
        if len(self._high) >= self.high_capacity:
            raise asyncio.QueueFull
        self._high.append((self._next_seq(), item))
        self._not_empty.set()

    def put_low(self, item) -> bool:
        """Admit-or-shed: a full data queue drops the NEWEST message
        (counted), never blocks — a block-part flood must not stall
        the peer's recv loop or starve the vote class behind it."""
        if len(self._low) >= self.low_capacity:
            self._controller.shed(self.low_queue)
            return False
        self._low.append((self._next_seq(), item))
        self._not_empty.set()
        return True


class DropOldestQueue:
    """Bounded queue that evicts the OLDEST item when full — for event
    streams where a slow consumer should lose history, not memory.
    put_nowait never fails; drops are counted via the controller (and
    an optional extra hook, e.g. rpc_ws_events_dropped_total)."""

    def __init__(self, maxsize: int, queue: str = "",
                 controller: OverloadController | None = None,
                 on_drop=None):
        self.maxsize = maxsize
        self.queue = queue
        self._controller = controller or CONTROLLER
        self._on_drop = on_drop
        self._d: collections.deque = collections.deque()
        self._not_empty = asyncio.Event()
        self.dropped = 0
        if queue:
            # every cataloged queue exports depth/capacity, not just
            # shed — registration replaces by name, so with several
            # instances (one per ws client) monitoring tracks the
            # latest. Advisory: a drop-oldest queue legitimately sits
            # full under a slow consumer; only its shed events drive
            # the overload level.
            self._controller.register(queue, self.qsize, maxsize,
                                      advisory=True, owner=self)

    def qsize(self) -> int:
        return len(self._d)

    def empty(self) -> bool:
        return not self._d

    def put_nowait(self, item) -> None:
        if len(self._d) >= self.maxsize:
            self._d.popleft()
            self.dropped += 1
            if self.queue:
                self._controller.shed(self.queue, advisory=True)
            if self._on_drop is not None:
                self._on_drop()
        self._d.append(item)
        self._not_empty.set()

    def close(self) -> None:
        """Drop the controller registration (and with it the strong
        reference keeping this queue alive) — a closed client's queue
        must not keep exporting stale depth. Owner-checked: a newer
        same-name queue's registration is left untouched."""
        if self.queue:
            self._controller.unregister(self.queue, owner=self)

    async def put(self, item) -> None:  # Queue-compatible signature
        self.put_nowait(item)

    async def get(self):
        while True:
            if self._d:
                return self._d.popleft()
            self._not_empty.clear()
            await self._not_empty.wait()

    def get_nowait(self):
        if not self._d:
            raise asyncio.QueueEmpty
        return self._d.popleft()


@dataclass
class SlowPeerPolicy:
    """Escalation thresholds for the p2p slow-peer monitor. Strikes
    are consecutive scan intervals with pending_send_bytes at or above
    the high-water mark; one healthy scan clears them."""

    pending_bytes_hiwater: int = 1 << 20   # 1 MiB of unsent backlog
    skip_strikes: int = 2                  # -> pause tx gossip
    demote_strikes: int = 4                # -> pause bulk data gossip
    disconnect_strikes: int = 8            # -> drop (non-persistent)


class SlowPeerTracker:
    """Pure bookkeeping behind Switch._scan_slow_peers: feed one
    observation per peer per scan, get back the escalation TRANSITION
    to act on (None when the level is unchanged).

    Levels: 0 healthy, 1 skip (tx gossip paused), 2 demote (bulk data
    gossip paused too; votes/state keep flowing — a slow peer must
    still count toward consensus). Persistent peers never pass level
    2: operators pinned them on purpose, so eviction is not ours to
    decide — they park at demote until they drain."""

    LEVEL_OK, LEVEL_SKIP, LEVEL_DEMOTE = 0, 1, 2

    def __init__(self, policy: SlowPeerPolicy | None = None):
        self.policy = policy or SlowPeerPolicy()
        self._strikes: dict[str, int] = {}
        self._level: dict[str, int] = {}

    def level(self, peer_id: str) -> int:
        return self._level.get(peer_id, 0)

    def forget(self, peer_id: str) -> None:
        self._strikes.pop(peer_id, None)
        self._level.pop(peer_id, None)

    def observe(self, peer_id: str, pending_bytes: int,
                persistent: bool) -> str | None:
        """Returns "skip" | "demote" | "disconnect" | "recover" on a
        level transition, None otherwise. A "disconnect" implies the
        caller removes the peer (and its state here is forgotten)."""
        p = self.policy
        if pending_bytes < p.pending_bytes_hiwater:
            self._strikes[peer_id] = 0
            if self._level.get(peer_id, 0) > 0:
                self._level[peer_id] = 0
                return "recover"
            return None
        strikes = self._strikes.get(peer_id, 0) + 1
        self._strikes[peer_id] = strikes
        cur = self._level.get(peer_id, 0)
        if strikes >= p.disconnect_strikes and not persistent:
            self.forget(peer_id)
            return "disconnect"
        if strikes >= p.demote_strikes and cur < self.LEVEL_DEMOTE:
            self._level[peer_id] = self.LEVEL_DEMOTE
            return "demote"
        if strikes >= p.skip_strikes and cur < self.LEVEL_SKIP:
            self._level[peer_id] = self.LEVEL_SKIP
            return "skip"
        return None
