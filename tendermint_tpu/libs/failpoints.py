"""Named chaos-failpoint registry (generalizes libs/fail.py).

The reference's libs/fail (FAIL_TEST_INDEX: the n-th fail() call-site
os.Exit(1)s) can inject exactly one fault shape — a hard crash at a
persistence boundary. Production failure modes on the tpu-backed path
are wider: a wedged device runtime raises, a slow disk stalls fsync,
a torn write corrupts the WAL tail mid-record, a flaky peer garbles a
packet. This registry gives every interesting boundary a STABLE NAME
and lets tests/operators arm an ACTION on it:

    crash         os._exit(1), no cleanup (the legacy behavior)
    error         raise FailpointError(name) from the call site
    delay         time.sleep(delay_ms) at the call site (stall shape)
    corrupt       the call site's payload bytes come back bit-flipped
                  and truncated (torn-write shape); on a point with no
                  payload it degrades to `error`

with a TRIGGER spec deciding which armed hits fire:

    nth=N         only the N-th armed hit (1-based)
    every=N       every N-th armed hit
    prob=P        each hit with probability P
    count=N       auto-disarm after N fires

Control surfaces (all reach the same registry):

  * env:    TM_TPU_FAILPOINTS="wal.fsync=error;nth=3,db.set=delay:50"
            parsed once at first hit; malformed entries are LOGGED and
            ignored — a typo'd chaos var must never itself become the
            fault being injected.
  * config: [chaos] failpoints = "<same spec>" (strict: a bad spec
            fails Config.validate_basic, not a running node).
  * HTTP:   POST /debug/failpoint on the DebugServer (libs/debugsrv.py)
            with {"name": ..., "action": ..., "nth": ...}; GET lists
            every point with its armed spec and hit/fire counters.

Per-point counters feed the `failpoint` metrics namespace
(failpoint_hits_total / failpoint_fires_total) so a chaos run's blast
radius is visible on the same scrape as its effects.

Hot-path cost when nothing is armed: one dict.get on an empty dict
(plus, on the six legacy crash sites only, an is-None check) — the
same order as the old fail() env probe, without the per-call getenv.

FAIL_TEST_INDEX keeps its exact legacy semantics for the six original
crash sites (consensus.commit.* / state.apply.*): the n-th such site
reached in the process exits hard. The env var is parsed ONCE at first
use; a malformed value is logged and ignored instead of raising from
inside consensus (it used to int() on every call).
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from dataclasses import dataclass

logger = logging.getLogger("failpoints")

ENV_VAR = "TM_TPU_FAILPOINTS"
LEGACY_ENV_VAR = "FAIL_TEST_INDEX"

ACTIONS = ("crash", "error", "delay", "corrupt")
MAX_DELAY_MS = 60_000.0


class FailpointError(Exception):
    """Raised by an armed `error` (or payload-less `corrupt`) point."""

    def __init__(self, name: str):
        super().__init__(f"injected failpoint {name!r}")
        self.name = name


@dataclass(frozen=True)
class FailpointDef:
    name: str
    description: str
    # participates in the legacy FAIL_TEST_INDEX ordinal (the six
    # original fail() persistence-boundary crash sites, in call order)
    legacy_index: bool = False
    # the call site passes bytes through hit(); `corrupt` transforms it
    payload: bool = False


# The closed catalog. tools/check_failpoints.py lints that every name
# here is documented in docs/CHAOS.md, exercised by at least one test,
# and that every hit() call site names a registered point.
CATALOG: tuple[FailpointDef, ...] = (
    FailpointDef(
        "consensus.commit.block_saved",
        "block saved to the store, WAL end-height not yet written",
        legacy_index=True),
    FailpointDef(
        "consensus.commit.wal_delimited",
        "WAL end-height written, state not yet applied",
        legacy_index=True),
    FailpointDef(
        "state.apply.block_executed",
        "block executed on the app, ABCI responses not yet saved",
        legacy_index=True),
    FailpointDef(
        "state.apply.responses_saved",
        "ABCI responses saved, state not yet updated",
        legacy_index=True),
    FailpointDef(
        "state.apply.app_committed",
        "app committed, state not yet saved",
        legacy_index=True),
    FailpointDef(
        "state.apply.state_saved",
        "everything saved, events not yet fired",
        legacy_index=True),
    FailpointDef(
        "wal.fsync",
        "consensus WAL flush+fsync (write_sync durability barrier)"),
    FailpointDef(
        "wal.torn_write",
        "the crc-framed record bytes about to be appended to the WAL "
        "head (corrupt = torn write mid-record)",
        payload=True),
    FailpointDef(
        "db.set",
        "a persistent KV-store write (SqliteDB set/batch, FileDB "
        "append)"),
    FailpointDef(
        "device.verify",
        "a device batch-verification kernel launch (ed25519 general "
        "kernel, sr25519 kernel; the CPU-jit degraded path is exempt)"),
    FailpointDef(
        "device.shard_fail",
        "one device of the verify mesh, evaluated per device in "
        "deterministic order at every sharded dispatch "
        "(crypto/tpu/verify.py effective_mesh — payload is the device "
        "string, so `nth=K` selects the K-th device; `error` models a "
        "raising chip, `corrupt` a NaN-verdict chip — either must "
        "evict ONLY that device while the fabric reshards over the "
        "survivors)",
        payload=True),
    FailpointDef(
        "abci.deliver",
        "an ABCI request leaving a proxy connection (all client "
        "types: local, socket, gRPC)"),
    FailpointDef(
        "p2p.send",
        "a packet about to be written to a peer's MConnection "
        "(corrupt = wire garbage; the peer must detect and drop)",
        payload=True),
    FailpointDef(
        "statesync.chunk",
        "a snapshot chunk accepted from a peer (corrupt = bad chunk "
        "bytes; restore must fail the snapshot, not apply them)",
        payload=True),
    FailpointDef(
        "statesync.offer",
        "a discovered snapshot about to be offered to the app over "
        "the snapshot ABCI connection (statesync/syncer.py _sync — "
        "`crash` here must restart into clean discovery with no "
        "partial restore state served)"),
    FailpointDef(
        "statesync.apply",
        "a snapshot chunk about to be applied to the app (payload is "
        "the chunk bytes; `corrupt` models a poisoned chunk reaching "
        "the apply boundary — restore must retry with a new peer mix, "
        "never serve the garbage; `crash` mid-restore must restart "
        "into clean discovery)",
        payload=True),
    FailpointDef(
        "statesync.serve",
        "a snapshot chunk about to be served to a requesting peer "
        "(statesync/reactor.py — payload is the chunk bytes; "
        "`corrupt` turns THIS node into a chunk poisoner, the e2e "
        "statesync_poison perturbation's attack shape: syncing peers "
        "must quarantine it and restore from honest peers)",
        payload=True),
    FailpointDef(
        "mempool.admission.verify",
        "the admission plane's batched tx-signature verification "
        "launch (mempool/admission.py — device or host backend; "
        "`delay` models a slow verify so the pre-verify queue backs "
        "up and sheds, `error` a failed launch that must degrade to "
        "the host oracle)"),
    FailpointDef(
        "light.verify",
        "the light serving plane's coalesced header-commit "
        "verification launch (light/serving.py — device or host "
        "backend; `delay` models a slow verify so the pending-verify "
        "queue backs up and sheds requests with 429s, `error` a "
        "failed launch that must degrade to the host oracle, never "
        "fail the requests)"),
    FailpointDef(
        "consensus.speculate",
        "a precommit lane entering a speculative verify-ahead launch "
        "(consensus/speculation.py — payload is the lane's observed "
        "timestamp bytes; `corrupt` models a wrong-timestamp flood so "
        "every speculated lane mismatches at commit and falls back to "
        "the breaker-aware verify path, `error` abandons the launch, "
        "`delay` stalls it past the commit)",
        payload=True),
    FailpointDef(
        "store.save_block",
        "a block about to be persisted to the block store (one atomic "
        "batch: meta + parts + commits + store state)"),
    FailpointDef(
        "privval.save",
        "LastSignState about to be persisted (tmp+rename+fsync) — a "
        "crash here must never let an unpersisted signature escape"),
)

BY_NAME: dict[str, FailpointDef] = {d.name: d for d in CATALOG}
_LEGACY_SITES = frozenset(d.name for d in CATALOG if d.legacy_index)

# The per-height COMMIT PIPELINE crash points, in persistence order:
# every one of these sits between two durability steps of committing a
# height, so a crash there leaves a legal cross-store skew the startup
# reconciler (consensus/replay.py) must heal. tools/crash_sweep.py
# arms each with `crash` against a real subprocess node and
# tools/check_recovery.py lints that this tuple, the sweep's coverage
# and the docs/CHAOS.md runbook table stay in sync.
COMMIT_PIPELINE: tuple[str, ...] = (
    "wal.fsync",
    "db.set",
    "store.save_block",
    "consensus.commit.block_saved",
    "consensus.commit.wal_delimited",
    "state.apply.block_executed",
    "state.apply.responses_saved",
    "state.apply.app_committed",
    "state.apply.state_saved",
    "privval.save",
)
assert all(n in BY_NAME for n in COMMIT_PIPELINE)


class _Armed:
    __slots__ = ("action", "delay_ms", "nth", "every", "prob",
                 "count", "hits", "fires")

    def __init__(self, action: str, delay_ms: float = 0.0,
                 nth: int | None = None, every: int | None = None,
                 prob: float | None = None, count: int | None = None):
        self.action = action
        self.delay_ms = delay_ms
        self.nth = nth
        self.every = every
        self.prob = prob
        self.count = count  # remaining fires before auto-disarm
        self.hits = 0
        self.fires = 0

    def spec(self) -> dict:
        out: dict = {"action": self.action}
        if self.action == "delay":
            out["delay_ms"] = self.delay_ms
        for k in ("nth", "every", "prob", "count"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        return out


_lock = threading.Lock()
_ACTIVE: dict[str, _Armed] = {}
# lifetime counters survive disarm so a sweep can assert blast radius
_TOTALS: dict[str, list] = {}  # name -> [hits, fires]

# -- legacy FAIL_TEST_INDEX (parse once; malformed -> log + ignore) --

_legacy_parsed = False
_legacy_index: int | None = None
_legacy_counter = -1


def _legacy_target() -> int | None:
    global _legacy_parsed, _legacy_index
    if not _legacy_parsed:
        _legacy_parsed = True
        env = os.environ.get(LEGACY_ENV_VAR)
        if env is not None:
            try:
                _legacy_index = int(env)
            except ValueError:
                logger.warning(
                    "ignoring malformed %s=%r (not an integer)",
                    LEGACY_ENV_VAR, env)
                _legacy_index = None
    return _legacy_index


_env_pending = True


def _install_env_spec() -> None:
    global _env_pending
    _env_pending = False
    spec = os.environ.get(ENV_VAR)
    if spec:
        install_spec(spec, source="env", strict=False)


# -- arming -----------------------------------------------------------


def _validate(name: str, action: str, delay_ms: float = 0.0,
              nth: int | None = None, every: int | None = None,
              prob: float | None = None,
              count: int | None = None) -> None:
    if name not in BY_NAME:
        raise ValueError(f"unknown failpoint {name!r}")
    if action not in ACTIONS:
        raise ValueError(f"unknown failpoint action {action!r}")
    if not 0.0 <= delay_ms <= MAX_DELAY_MS:
        raise ValueError(f"delay_ms {delay_ms} out of [0, {MAX_DELAY_MS}]")
    for label, v in (("nth", nth), ("every", every), ("count", count)):
        if v is not None and v < 1:
            raise ValueError(f"{label} must be >= 1")
    if prob is not None and not 0.0 <= prob <= 1.0:
        raise ValueError("prob must be in [0, 1]")


def validate_spec(spec: str) -> None:
    """Full dry-run validation of a spec string — grammar AND the same
    per-entry checks arm() enforces, so a strict surface (config
    validate_basic) rejects everything install_spec would reject."""
    for name, kwargs in parse_spec(spec):
        _validate(name, **kwargs)


def arm(name: str, action: str, *, delay_ms: float = 0.0,
        nth: int | None = None, every: int | None = None,
        prob: float | None = None, count: int | None = None) -> None:
    """Arm `name` with `action`. Raises ValueError on an unknown point
    or malformed spec (callers wanting lenience catch it)."""
    _validate(name, action, delay_ms, nth, every, prob, count)
    with _lock:
        _ACTIVE[name] = _Armed(action, delay_ms, nth, every, prob, count)
    logger.warning("failpoint armed: %s %s", name,
                   _ACTIVE[name].spec())


def disarm(name: str) -> bool:
    with _lock:
        armed = _ACTIVE.pop(name, None)
    if armed is not None:
        logger.warning("failpoint disarmed: %s", name)
    return armed is not None


def disarm_all() -> int:
    with _lock:
        n = len(_ACTIVE)
        _ACTIVE.clear()
    if n:
        logger.warning("all failpoints disarmed (%d)", n)
    return n


def parse_spec(spec: str) -> list[tuple[str, dict]]:
    """Parse "name=action[:arg][;trig=val...]" comma-separated entries
    into [(name, arm-kwargs)]. Raises ValueError on the first bad
    entry (callers choose strictness)."""
    out: list[tuple[str, dict]] = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        head, *trigs = entry.split(";")
        name, sep, action = head.partition("=")
        if not sep:
            raise ValueError(f"missing '=' in failpoint entry {entry!r}")
        name, action = name.strip(), action.strip()
        kwargs: dict = {}
        action, colon, arg = action.partition(":")
        if action == "delay":
            kwargs["delay_ms"] = float(arg) if colon else 100.0
        elif colon:
            raise ValueError(
                f"action {action!r} takes no argument ({entry!r})")
        for t in trigs:
            k, sep2, v = t.partition("=")
            k, v = k.strip(), v.strip()
            if not sep2 or k not in ("nth", "every", "prob", "count"):
                raise ValueError(f"bad trigger {t!r} in {entry!r}")
            kwargs[k] = float(v) if k == "prob" else int(v)
        out.append((name, {"action": action, **kwargs}))
    return out


def install_spec(spec: str, source: str = "config",
                 strict: bool = True) -> int:
    """Arm every entry of a spec string. strict=True raises on the
    first malformed entry (config path: fail fast at validate);
    strict=False logs and skips bad entries (env path: a chaos typo
    must not take the node down on its own)."""
    armed = 0
    try:
        entries = parse_spec(spec)
    except ValueError as e:
        if strict:
            raise
        logger.warning("ignoring malformed %s failpoint spec: %s",
                       source, e)
        return 0
    for name, kwargs in entries:
        try:
            arm(name, **kwargs)
            armed += 1
        except ValueError as e:
            if strict:
                raise
            logger.warning("ignoring bad %s failpoint entry %r: %s",
                           source, name, e)
    return armed


# -- the call-site hook -----------------------------------------------


def _metrics():
    from .metrics import failpoint_metrics

    return failpoint_metrics()


def _corrupt_bytes(data: bytes) -> bytes:
    """Deterministic torn-write shape: flip one bit mid-payload and
    drop the final byte (if any) — enough to break any crc/auth tag
    without being ignorable."""
    b = bytearray(data)
    if not b:
        return b"\xff"
    b[len(b) // 2] ^= 0x01
    return bytes(b[:-1]) if len(b) > 1 else bytes(b)


def _decide(name: str) -> tuple[str, float] | None:
    """Shared per-hit bookkeeping: env parse, legacy ordinal, trigger
    evaluation, counters, metrics. Returns (action, delay_ms) when the
    point fires, None otherwise."""
    if _env_pending:
        _install_env_spec()
    if name in _LEGACY_SITES and _legacy_target() is not None:
        global _legacy_counter
        _legacy_counter += 1
        if _legacy_counter == _legacy_target():
            os._exit(1)
    armed = _ACTIVE.get(name)
    if armed is None:
        return None

    with _lock:
        if _ACTIVE.get(name) is not armed:  # racing disarm/re-arm
            return None
        armed.hits += 1
        totals = _TOTALS.setdefault(name, [0, 0])
        totals[0] += 1
        fire = True
        if armed.nth is not None:
            fire = armed.hits == armed.nth
        elif armed.every is not None:
            fire = armed.hits % armed.every == 0
        if fire and armed.prob is not None:
            fire = random.random() < armed.prob
        if fire:
            armed.fires += 1
            totals[1] += 1
            if armed.count is not None:
                armed.count -= 1
                if armed.count <= 0:
                    _ACTIVE.pop(name, None)
        action = armed.action
        delay_ms = armed.delay_ms
    try:
        m = _metrics()
        m.hits.inc(point=name)
        if fire:
            m.fires.inc(point=name, action=action)
    except Exception:  # metrics must never be the injected fault
        logger.exception("failpoint metrics update failed")
    if not fire:
        return None
    logger.warning("failpoint firing: %s action=%s", name, action)
    return action, delay_ms


def hit(name: str, payload: bytes | None = None):
    """The call-site function for SYNCHRONOUS sites (WAL fsync, DB
    writes, kernel launches — places that block the caller anyway, so
    a `delay` there faithfully models a slow disk/device). Returns
    `payload` (transformed by an armed `corrupt`) — call sites with a
    payload MUST use the return value. No-op (beyond an empty dict
    probe) when nothing is armed."""
    decided = _decide(name)
    if decided is None:
        return payload
    action, delay_ms = decided
    if action == "crash":
        os._exit(1)
    if action == "delay":
        time.sleep(delay_ms / 1000.0)
        return payload
    if action == "corrupt" and payload is not None:
        return _corrupt_bytes(payload)
    raise FailpointError(name)


async def hit_async(name: str, payload: bytes | None = None):
    """hit() for coroutine call sites (abci.deliver, p2p.send): the
    `delay` action awaits asyncio.sleep instead of blocking the event
    loop, so an injected stall slows the TARGETED component the way a
    real slow app/peer would — consensus, RPC and crucially the
    disarm endpoint keep running."""
    decided = _decide(name)
    if decided is None:
        return payload
    action, delay_ms = decided
    if action == "crash":
        os._exit(1)
    if action == "delay":
        import asyncio

        await asyncio.sleep(delay_ms / 1000.0)
        return payload
    if action == "corrupt" and payload is not None:
        return _corrupt_bytes(payload)
    raise FailpointError(name)


# -- introspection (debug endpoint, tools) ----------------------------


def state() -> dict:
    """{name: {description, armed: spec|None, hits, fires}} over the
    whole catalog — the GET /debug/failpoint body."""
    with _lock:
        active = {k: v.spec() for k, v in _ACTIVE.items()}
        totals = {k: list(v) for k, v in _TOTALS.items()}
    out = {}
    for d in CATALOG:
        h, f = totals.get(d.name, (0, 0))
        out[d.name] = {
            "description": d.description,
            "armed": active.get(d.name),
            "hits": h,
            "fires": f,
        }
    return out


def any_armed() -> list[str]:
    """Names of currently armed points (the /status chaos flag)."""
    with _lock:
        return sorted(_ACTIVE)


# -- legacy shim + test reset -----------------------------------------


def legacy_fail() -> None:
    """Exact libs/fail.py fail() behavior for any remaining direct
    callers: participates in the same FAIL_TEST_INDEX ordinal as the
    six named legacy sites."""
    if _legacy_target() is None:
        return
    global _legacy_counter
    _legacy_counter += 1
    if _legacy_counter == _legacy_target():
        os._exit(1)


def reset() -> None:
    """Full test reset: disarm everything, clear counters, re-read the
    legacy env var on next use."""
    global _legacy_parsed, _legacy_index, _legacy_counter, _env_pending
    with _lock:
        _ACTIVE.clear()
        _TOTALS.clear()
    _legacy_parsed = False
    _legacy_index = None
    _legacy_counter = -1
    _env_pending = True
