"""RPC route implementations bound to a node
(reference: rpc/core/ — routes.go:10-47, env.go:68 Environment).

Results are JSON-shaped dicts mirroring the reference's response
types; bytes render as hex (hashes/addresses) or base64 (txs/values),
matching the reference's JSON conventions."""

from __future__ import annotations

import asyncio
import base64

from ..abci import types as abci
from ..crypto import tmhash
from ..libs.pubsub import Query
from ..types.events import (
    EventDataNewBlock, EventDataTx, query_for_event,
)
from .jsonrpc import RawStr, RPCError, UriStr

_SUBSCRIBER_PREFIX = "ws-"


def _tx_bytes(v) -> bytes:
    """Byte-typed RPC param from either transport (reference: the URI
    handler decodes quoted values as raw content and 0x-values as hex,
    while JSON-RPC carries []byte base64-encoded). RawStr marks a
    URI-quoted value; `curl '...?tx="k=v"'` is the documented usage."""
    if isinstance(v, bytes):
        return v
    if isinstance(v, RawStr):
        return v.encode()
    if isinstance(v, UriStr) and v.startswith("0x"):
        # URI-only: a JSON-RPC base64 payload that happens to look
        # like 0x-hex must not be hex-decoded. Malformed hex is an
        # error, not a base64 fallback (a typo'd hex tx that survives
        # base64 decoding would broadcast garbage bytes).
        try:
            return bytes.fromhex(v[2:])
        except ValueError as e:
            raise RPCError(-32602, "invalid 0x-hex byte param") from e
    try:
        return base64.b64decode(v, validate=True)
    except Exception as e:
        raise RPCError(
            -32602,
            "invalid byte param: expected base64 (JSON-RPC), a "
            '"quoted" raw string, or 0x-hex (URI)') from e


def coerce_hex_param(data) -> str:
    """All-digit hex strings arrive int-coerced from URI params;
    re-render losslessly (hex data always has even length, so a
    leading zero is the only ambiguity — restore it by parity).
    Shared by the node's abci_query and the light proxy's key check."""
    if isinstance(data, int):
        data = str(data)
        if len(data) % 2:
            data = "0" + data
    return data


def hexbytes_param(data) -> bytes:
    """HexBytes-typed RPC param (abci_query data): hex string from
    JSON-RPC (the reference's HexBytes JSON encoding), while the URI
    handler passes "quoted" values as RAW content and 0x-values as
    hex. Shared with the light proxy's verified abci_query."""
    if isinstance(data, bytes):
        return data
    if isinstance(data, RawStr):
        return data.encode()
    data = coerce_hex_param(data)
    if not data:
        return b""
    if data.startswith("0x"):
        data = data[2:]
    try:
        return bytes.fromhex(data)
    except ValueError as e:
        raise RPCError(
            -32602,
            'invalid hex-bytes param: expected hex, 0x-hex, or a '
            '"quoted" raw string (URI)') from e


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _hex(b: bytes) -> str:
    return b.hex().upper()


def _header_json(h) -> dict:
    return {
        "version": {"block": h.version_block, "app": h.version_app},
        "chain_id": h.chain_id,
        "height": str(h.height),
        "time": str(h.time),
        "last_block_id": _block_id_json(h.last_block_id),
        "last_commit_hash": _hex(h.last_commit_hash),
        "data_hash": _hex(h.data_hash),
        "validators_hash": _hex(h.validators_hash),
        "next_validators_hash": _hex(h.next_validators_hash),
        "consensus_hash": _hex(h.consensus_hash),
        "app_hash": _hex(h.app_hash),
        "last_results_hash": _hex(h.last_results_hash),
        "evidence_hash": _hex(h.evidence_hash),
        "proposer_address": _hex(h.proposer_address),
    }


def _block_id_json(bid) -> dict:
    if bid is None:
        return {"hash": "", "parts": {"total": 0, "hash": ""}}
    psh = bid.part_set_header
    return {"hash": _hex(bid.hash),
            "parts": {"total": psh.total if psh else 0,
                      "hash": _hex(psh.hash) if psh else ""}}


def _commit_json(c) -> dict:
    if c is None:
        return None
    return {
        "height": str(c.height), "round": c.round,
        "block_id": _block_id_json(c.block_id),
        "signatures": [{
            "block_id_flag": s.block_id_flag,
            "validator_address": _hex(s.validator_address),
            "timestamp": str(s.timestamp),
            "signature": _b64(s.signature),
        } for s in c.signatures],
    }


def _block_json(b) -> dict:
    return {
        "header": _header_json(b.header),
        "data": {"txs": [_b64(tx) for tx in b.data.txs]},
        "evidence": {"evidence": [
            {"type": type(e).__name__, "bytes": _b64(e.to_bytes())}
            for e in b.evidence.evidence]},
        "last_commit": _commit_json(b.last_commit),
    }


def _validator_json(v) -> dict:
    return {"address": _hex(v.address),
            "pub_key": {"type": "ed25519", "value": _b64(v.pub_key.bytes())},
            "voting_power": str(v.voting_power),
            "proposer_priority": str(v.proposer_priority)}


class Environment:
    """reference: rpc/core/env.go:68."""

    def __init__(self, node):
        self.node = node
        self._next_sub = 0
        self._bg_tasks: set = set()

    # -- build the route tables --

    def routes(self) -> dict:
        return {
            "health": self.health,
            "status": self.status,
            "net_info": self.net_info,
            "genesis": self.genesis,
            "block": self.block,
            "block_by_hash": self.block_by_hash,
            "block_results": self.block_results,
            "blockchain": self.blockchain,
            "commit": self.commit,
            "validators": self.validators,
            "consensus_params": self.consensus_params,
            "consensus_state": self.consensus_state,
            "dump_consensus_state": self.dump_consensus_state,
            "abci_info": self.abci_info,
            "abci_query": self.abci_query,
            "broadcast_tx_async": self.broadcast_tx_async,
            "broadcast_tx_sync": self.broadcast_tx_sync,
            "broadcast_tx_commit": self.broadcast_tx_commit,
            "unconfirmed_txs": self.unconfirmed_txs,
            "num_unconfirmed_txs": self.num_unconfirmed_txs,
            "tx": self.tx,
            "tx_search": self.tx_search,
            "block_search": self.block_search,
            "genesis_chunked": self.genesis_chunked,
            "broadcast_evidence": self.broadcast_evidence,
            "check_tx": self.check_tx,
            # unsafe routes (reference routes.go AddUnsafeRoutes;
            # exposed only with rpc.unsafe = true)
            **({
                "unsafe_flush_mempool": self.unsafe_flush_mempool,
                "unsafe_net_sever": self.unsafe_net_sever,
                "dial_seeds": self.dial_seeds,
                "dial_peers": self.dial_peers,
            } if getattr(self.node.config.rpc, "unsafe", False) else {}),
        }

    def ws_routes(self) -> dict:
        return {
            "subscribe": self.subscribe,
            "unsubscribe": self.unsubscribe,
            "unsubscribe_all": self.unsubscribe_all,
        }

    # -- info --

    async def health(self, ctx) -> dict:
        return {}

    async def status(self, ctx) -> dict:
        n = self.node
        latest_h = n.block_store.height
        meta = n.block_store.load_block_meta(latest_h) if latest_h else None
        pv = n.priv_validator
        val_info = {}
        if pv is not None:
            addr = pv.get_pub_key().address()
            _, val = n.consensus_state.rs.validators.get_by_address(addr) \
                if n.consensus_state.rs.validators else (-1, None)
            val_info = {
                "address": _hex(addr),
                "pub_key": {"type": "ed25519",
                            "value": _b64(pv.get_pub_key().bytes())},
                "voting_power": str(val.voting_power if val else 0),
            }
        return {
            "node_info": {
                "id": n.node_key.id,
                "listen_addr": n.listen_addr,
                "network": n.genesis_doc.chain_id,
                "moniker": n.config.base.moniker,
                "version": "tendermint-tpu/0.1",
            },
            "sync_info": {
                "latest_block_height": str(latest_h),
                "latest_block_hash":
                    _hex(meta.block_id.hash) if meta else "",
                "latest_app_hash": _hex(n.state.app_hash),
                "latest_block_time":
                    str(meta.header.time) if meta else "0",
                "earliest_block_height": str(n.block_store.base),
                "catching_up": not n.bc_reactor.synced.is_set(),
            },
            "validator_info": val_info,
        }

    async def net_info(self, ctx) -> dict:
        sw = self.node.switch
        return {
            "listening": True,
            "listeners": [self.node.listen_addr],
            "n_peers": str(sw.n_peers()),
            "peers": [{
                "node_info": {"id": p.id, "moniker": p.node_info.moniker,
                              "listen_addr": p.node_info.listen_addr},
                "is_outbound": p.outbound,
                "remote_ip": p.socket_addr,
            } for p in sw.peers.values()],
        }

    async def genesis(self, ctx) -> dict:
        import json as _json

        return {"genesis": _json.loads(self.node.genesis_doc.to_json())}

    # -- blocks --

    def _height_param(self, height, default_latest=True) -> int:
        latest = self.node.block_store.height
        if height in (None, 0, "0", ""):
            if not default_latest:
                raise RPCError(-32602, "height required")
            return latest
        h = int(height)
        if h < self.node.block_store.base or h > latest:
            raise RPCError(
                -32603, f"height {h} not available "
                f"(base {self.node.block_store.base}, latest {latest})")
        return h

    async def block(self, ctx, height=None) -> dict:
        h = self._height_param(height)
        block = self.node.block_store.load_block(h)
        meta = self.node.block_store.load_block_meta(h)
        if block is None or meta is None:
            raise RPCError(-32603, f"no block at height {h}")
        return {"block_id": _block_id_json(meta.block_id),
                "block": _block_json(block)}

    async def block_by_hash(self, ctx, hash=None) -> dict:
        if not hash:
            raise RPCError(-32602, "hash required")
        block = self.node.block_store.load_block_by_hash(
            bytes.fromhex(hash))
        if block is None:
            raise RPCError(-32603, f"block {hash} not found")
        return await self.block(ctx, height=block.header.height)

    async def block_results(self, ctx, height=None) -> dict:
        h = self._height_param(height)
        resp = self.node.state_store.load_abci_responses(h)
        if resp is None:
            raise RPCError(-32603, f"no results for height {h}")
        deliver = [
            {"code": getattr(r, "code", 0),
             "data": _b64(getattr(r, "data", b"") or b""),
             "log": getattr(r, "log", ""),
             "gas_wanted": str(getattr(r, "gas_wanted", 0)),
             "gas_used": str(getattr(r, "gas_used", 0)),
             "events": getattr(r, "events", [])}
            for r in resp.get("deliver_txs", [])
        ]
        end = resp.get("end_block")
        return {
            "height": str(h),
            "txs_results": deliver,
            "validator_updates": [
                {"pub_key": _b64(vu.pub_key), "power": str(vu.power)}
                for vu in (end.validator_updates if end else [])],
        }

    async def blockchain(self, ctx, min_height=None, max_height=None) -> dict:
        store = self.node.block_store
        max_h = self._height_param(max_height)
        min_h = max(int(min_height or 1), store.base)
        min_h = max(min_h, max_h - 19)  # reference caps at 20 metas
        metas = []
        for h in range(max_h, min_h - 1, -1):
            m = store.load_block_meta(h)
            if m is not None:
                metas.append({
                    "block_id": _block_id_json(m.block_id),
                    "block_size": str(m.block_size),
                    "header": _header_json(m.header),
                    "num_txs": str(m.num_txs),
                })
        return {"last_height": str(store.height), "block_metas": metas}

    async def commit(self, ctx, height=None) -> dict:
        h = self._height_param(height)
        store = self.node.block_store
        meta = store.load_block_meta(h)
        if meta is None:
            raise RPCError(-32603, f"no block at height {h}")
        commit = store.load_block_commit(h)
        canonical = True
        if commit is None:
            commit = store.load_seen_commit(h)
            canonical = False
        return {
            "signed_header": {"header": _header_json(meta.header),
                              "commit": _commit_json(commit)},
            "canonical": canonical,
        }

    async def validators(self, ctx, height=None, page=1,
                         per_page=30) -> dict:
        h = self._height_param(height)
        vals = self.node.state_store.load_validators(h)
        if vals is None:
            raise RPCError(-32603, f"no validators for height {h}")
        page, per_page = max(int(page), 1), min(max(int(per_page), 1), 100)
        start = (page - 1) * per_page
        sel = vals.validators[start:start + per_page]
        return {"block_height": str(h),
                "validators": [_validator_json(v) for v in sel],
                "count": str(len(sel)), "total": str(len(vals))}

    async def consensus_params(self, ctx, height=None) -> dict:
        h = self._height_param(height)
        params = self.node.state_store.load_consensus_params(h) or \
            self.node.state.consensus_params
        return {
            "block_height": str(h),
            "consensus_params": {
                "block": {"max_bytes": str(params.block.max_bytes),
                          "max_gas": str(params.block.max_gas)},
                "evidence": {
                    "max_age_num_blocks":
                        str(params.evidence.max_age_num_blocks),
                    "max_age_duration":
                        str(params.evidence.max_age_duration_ns),
                    "max_bytes": str(params.evidence.max_bytes)},
                "validator": {
                    "pub_key_types": params.validator.pub_key_types},
                "version": {
                    "app_version": str(params.version.app_version)},
            },
        }

    async def consensus_state(self, ctx) -> dict:
        rs = self.node.consensus_state.rs
        return {"round_state": {
            "height": str(rs.height), "round": rs.round,
            "step": int(rs.step),
            "start_time": str(rs.start_time),
            "proposal_block_hash":
                _hex(rs.proposal_block.hash()) if rs.proposal_block
                else "",
            "locked_block_hash":
                _hex(rs.locked_block.hash()) if rs.locked_block else "",
            "valid_block_hash":
                _hex(rs.valid_block.hash()) if rs.valid_block else "",
        }}

    async def dump_consensus_state(self, ctx) -> dict:
        base = await self.consensus_state(ctx)
        rs = self.node.consensus_state.rs
        # Per-round vote tallies (reference dump includes the
        # HeightVoteSet's bit-array renderings).
        votes = []
        if rs.votes is not None:
            for rnd in sorted(rs.votes._round_vote_sets):
                pv = rs.votes.prevotes(rnd)
                pc = rs.votes.precommits(rnd)
                votes.append({
                    "round": rnd,
                    "prevotes": str(pv.bit_array()) if pv else "",
                    "prevotes_power": str(pv.sum if pv else 0),
                    "precommits": str(pc.bit_array()) if pc else "",
                    "precommits_power": str(pc.sum if pc else 0),
                })
        base["round_state"]["height_vote_set"] = votes
        reactor = self.node.consensus_reactor
        base["peers"] = [{
            "node_address": pid,
            "peer_state": {"height": str(ps.height), "round": ps.round,
                           "step": int(ps.step)},
        } for pid, ps in reactor.peer_states.items()]
        return base

    # -- abci --

    async def abci_info(self, ctx) -> dict:
        res = await self.node.proxy_app.query.info(abci.RequestInfo())
        return {"response": {
            "data": res.data, "version": res.version,
            "app_version": str(res.app_version),
            "last_block_height": str(res.last_block_height),
            "last_block_app_hash": _b64(res.last_block_app_hash),
        }}

    async def abci_query(self, ctx, path="", data="", height=0,
                         prove=False) -> dict:
        res = await self.node.proxy_app.query.query(abci.RequestQuery(
            data=hexbytes_param(data),
            path=path, height=int(height), prove=bool(prove)))
        out = {
            "code": res.code, "log": res.log, "index": str(res.index),
            "key": _b64(res.key or b""), "value": _b64(res.value or b""),
            "height": str(res.height),
        }
        if res.proof_ops:
            out["proof_ops"] = {"ops": [
                {"type": op["type"], "key": _b64(op["key"]),
                 "data": _b64(op["data"])} for op in res.proof_ops]}
        return {"response": out}

    # -- txs --

    @staticmethod
    def _busy_error(e: Exception) -> RPCError:
        """Admission sheds surface as explicit 429-style errors so a
        load generator can distinguish 'back off' from 'bad tx'."""
        from .jsonrpc import CODE_BUSY

        return RPCError(CODE_BUSY, f"mempool overloaded: {e}")

    async def broadcast_tx_async(self, ctx, tx="") -> dict:
        raw = _tx_bytes(tx)
        # Preflight admission: fire-and-forget must still SHED visibly
        # when the pool/app window is saturated — silently spawning a
        # doomed CheckTx task hides overload from the one caller who
        # could slow down.
        mp = self.node.mempool
        admission_err = getattr(mp, "admission_error",
                                lambda n=0, tx=None: None)(len(raw), raw)
        if admission_err is not None:
            # count the shed here: the CheckTx task that would have
            # recorded it is never spawned, and a flood rejected only
            # on this path must still move overload_shed_total and
            # the /status level (parity with broadcast_tx_sync) —
            # same routing as check_tx via shed_admission_error
            mp.shed_admission_error(admission_err)
            raise self._busy_error(admission_err)
        # hold a strong ref: the loop only weak-refs tasks, and a GC'd
        # task would silently drop the tx
        task = asyncio.get_running_loop().create_task(
            self._checked_check_tx(raw))
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)
        return {"code": 0, "data": "", "log": "",
                "hash": _hex(tmhash.sum256(raw))}

    async def _checked_check_tx(self, raw: bytes):
        try:
            return await self.node.mempool.check_tx(raw)
        except Exception as e:
            return e

    async def broadcast_tx_sync(self, ctx, tx="") -> dict:
        from ..mempool.admission import AdmissionQueueFullError
        from ..mempool.clist_mempool import MempoolBusyError, \
            MempoolFullError

        raw = _tx_bytes(tx)
        try:
            res = await self.node.mempool.check_tx(raw)
        except (MempoolBusyError, MempoolFullError,
                AdmissionQueueFullError) as e:
            raise self._busy_error(e) from e
        except Exception as e:
            raise RPCError(-32603, f"tx rejected: {e}") from e
        return {"code": res.code, "data": _b64(res.data or b""),
                "log": res.log, "hash": _hex(tmhash.sum256(raw))}

    async def check_tx(self, ctx, tx="") -> dict:
        """Run CheckTx against the app WITHOUT adding to the mempool
        (reference: rpc/core/mempool.go CheckTx)."""
        from ..abci.types import RequestCheckTx

        raw = _tx_bytes(tx)
        res = await self.node.proxy_app.mempool.check_tx(
            RequestCheckTx(raw))
        return {"code": res.code, "data": _b64(res.data or b""),
                "log": res.log, "gas_wanted": str(res.gas_wanted),
                "gas_used": str(res.gas_used)}

    async def unsafe_flush_mempool(self, ctx) -> dict:
        """reference: rpc/core/mempool.go UnsafeFlushMempool."""
        await self.node.mempool.flush()
        return {}

    async def unsafe_net_sever(self, ctx, seconds="3") -> dict:
        """Test hook (no reference route — the reference e2e runner
        severs the docker network instead, perturb.go:12-60): hard-drop
        every p2p connection and refuse dials/accepts for `seconds`,
        so peers observe connection loss (not a stall) and the
        reconnect/backoff/PEX paths run for real."""
        secs = float(seconds)
        if not 0 < secs <= 60:
            raise RPCError(-32602, "seconds must be in (0, 60]")
        dropped = await self.node.switch.sever(secs)
        return {"severed_for": secs, "connections_dropped": dropped}

    async def dial_seeds(self, ctx, seeds=()) -> dict:
        """reference: rpc/core/net.go UnsafeDialSeeds."""
        if not seeds:
            raise RPCError(-32602, "no seeds provided")
        await self.node.switch.dial_peers_async(list(seeds))
        return {"log": f"dialing seeds in progress. see /net_info "
                       f"for details ({len(seeds)})"}

    async def dial_peers(self, ctx, peers=(), persistent=False) -> dict:
        """reference: rpc/core/net.go UnsafeDialPeers."""
        if not peers:
            raise RPCError(-32602, "no peers provided")
        if persistent:
            self.node.switch.add_persistent_peers(list(peers))
        await self.node.switch.dial_peers_async(list(peers))
        return {"log": f"dialing peers in progress ({len(peers)})"}

    async def broadcast_tx_commit(self, ctx, tx="") -> dict:
        """CheckTx, then wait for the tx to land in a block
        (reference: rpc/core/mempool.go BroadcastTxCommit)."""
        raw = _tx_bytes(tx)
        h = tmhash.sum256(raw)
        bus = self.node.event_bus
        subscriber = f"tx-commit-{h.hex()[:16]}"
        sub = bus.subscribe(subscriber, query_for_event("Tx"))
        try:
            from ..mempool.admission import AdmissionQueueFullError
            from ..mempool.clist_mempool import MempoolBusyError, \
                MempoolFullError

            try:
                check = await self.node.mempool.check_tx(raw)
            except (MempoolBusyError, MempoolFullError,
                    AdmissionQueueFullError) as e:
                raise self._busy_error(e) from e
            except Exception as e:
                raise RPCError(-32603, f"tx rejected: {e}") from e
            if check.code != abci.CODE_TYPE_OK:
                return {"check_tx": {"code": check.code, "log": check.log},
                        "deliver_tx": {}, "hash": _hex(h), "height": "0"}
            timeout = self.node.config.rpc.\
                timeout_broadcast_tx_commit_ms / 1000.0
            deadline = asyncio.get_running_loop().time() + timeout
            while True:
                remaining = deadline - asyncio.get_running_loop().time()
                if remaining <= 0:
                    raise RPCError(-32603,
                                   "timed out waiting for tx commit")
                try:
                    msg = await asyncio.wait_for(sub.next(), remaining)
                except asyncio.TimeoutError:
                    raise RPCError(
                        -32603, "timed out waiting for tx commit") from None
                data = msg.data
                if isinstance(data, EventDataTx) and data.tx == raw:
                    r = data.result
                    return {
                        "check_tx": {"code": check.code, "log": check.log},
                        "deliver_tx": {
                            "code": r.get("code", 0),
                            "log": r.get("log", ""),
                            "events": r.get("events", [])},
                        "hash": _hex(h),
                        "height": str(data.height),
                    }
        finally:
            bus.unsubscribe_all(subscriber)

    async def unconfirmed_txs(self, ctx, limit=30) -> dict:
        txs = self.node.mempool.reap_max_txs(min(int(limit), 100))
        return {"n_txs": str(len(txs)),
                "total": str(self.node.mempool.size()),
                "total_bytes": str(self.node.mempool.tx_bytes()),
                "txs": [_b64(t) for t in txs]}

    async def num_unconfirmed_txs(self, ctx) -> dict:
        return {"n_txs": str(self.node.mempool.size()),
                "total": str(self.node.mempool.size()),
                "total_bytes": str(self.node.mempool.tx_bytes())}

    async def tx(self, ctx, hash="", prove=False) -> dict:
        if self.node.tx_indexer is None:
            raise RPCError(-32603, "tx indexing disabled")
        tr = self.node.tx_indexer.get(bytes.fromhex(hash))
        if tr is None:
            raise RPCError(-32603, f"tx {hash} not found")
        out = {"hash": hash.upper(), "height": str(tr.height),
               "index": tr.index,
               "tx_result": tr.result, "tx": _b64(tr.tx)}
        if prove:
            block = self.node.block_store.load_block(tr.height)
            if block is not None:
                from ..crypto import merkle

                root, proofs = merkle.proofs_from_byte_slices(
                    [bytes(t) for t in block.data.txs])
                p = proofs[tr.index]
                out["proof"] = {
                    "root_hash": _hex(root),
                    "data": _b64(tr.tx),
                    "proof": {"total": p.total, "index": p.index,
                              "leaf_hash": _b64(p.leaf_hash),
                              "aunts": [_b64(a) for a in p.aunts]},
                }
        return out

    async def tx_search(self, ctx, query="", prove=False, page=1,
                        per_page=30, order_by="asc") -> dict:
        if self.node.tx_indexer is None:
            raise RPCError(-32603, "tx indexing disabled")
        results = self.node.tx_indexer.search(Query.parse(query))
        if order_by == "desc":
            results = list(reversed(results))
        page, per_page = max(int(page), 1), min(max(int(per_page), 1), 100)
        start = (page - 1) * per_page
        sel = results[start:start + per_page]
        return {"total_count": str(len(results)), "txs": [
            {"hash": _hex(t.hash()), "height": str(t.height),
             "index": t.index, "tx_result": t.result, "tx": _b64(t.tx)}
            for t in sel]}

    async def block_search(self, ctx, query="", page=1, per_page=30,
                           order_by="asc") -> dict:
        """Search blocks by BeginBlock/EndBlock events (released
        v0.34.x BlockSearch; the pinned reference predates the route —
        query language and paging match tx_search)."""
        bi = getattr(self.node, "block_indexer", None)
        if bi is None:
            raise RPCError(-32603, "block indexing disabled")
        heights = bi.search(Query.parse(query))
        if order_by == "desc":
            heights = list(reversed(heights))
        page, per_page = max(int(page), 1), min(max(int(per_page), 1), 100)
        start = (page - 1) * per_page
        blocks = []
        for h in heights[start:start + per_page]:
            meta = self.node.block_store.load_block_meta(h)
            block = self.node.block_store.load_block(h)
            if meta is None or block is None:
                continue
            blocks.append({"block_id": _block_id_json(meta.block_id),
                           "block": _block_json(block)})
        return {"total_count": str(len(heights)), "blocks": blocks}

    _GENESIS_CHUNK = 16 * 1024 * 1024

    async def genesis_chunked(self, ctx, chunk=0) -> dict:
        """Paged genesis download for documents too big for one
        response (released v0.34.x GenesisChunked; 16 MiB chunks).
        Chunks are computed once — the genesis doc is immutable, and a
        big doc is the only reason this route gets called."""
        chunks = getattr(self, "_genesis_chunks", None)
        if chunks is None:
            data = self.node.genesis_doc.to_json().encode()
            chunks = self._genesis_chunks = [
                data[i:i + self._GENESIS_CHUNK]
                for i in range(0, len(data), self._GENESIS_CHUNK)] or [b""]
        i = int(chunk)
        if not 0 <= i < len(chunks):
            raise RPCError(
                -32603, f"there are {len(chunks)} chunks, "
                f"{i} is invalid (should be between 0 and {len(chunks)-1})")
        return {"chunk": str(i), "total": str(len(chunks)),
                "data": _b64(chunks[i])}

    async def broadcast_evidence(self, ctx, evidence="") -> dict:
        from ..types.evidence import evidence_from_bytes

        ev = evidence_from_bytes(_tx_bytes(evidence))
        self.node.evpool.add_evidence(ev)
        return {"hash": _hex(ev.hash())}

    # -- subscriptions (ws only) --

    async def subscribe(self, ctx, query="") -> dict:
        if ctx.ws is None:
            raise RPCError(-32603, "subscribe requires a websocket")
        q = Query.parse(query)
        ws = ctx.ws
        subs = getattr(ws, "_subs", None)
        if subs is None:
            subs = ws._subs = {}
        if query in subs:
            raise RPCError(-32603, f"already subscribed to {query!r}")
        max_subs = self.node.config.rpc.max_subscriptions_per_client
        if len(subs) >= max_subs:
            raise RPCError(-32603, "too many subscriptions")
        self._next_sub += 1
        subscriber = f"{_SUBSCRIBER_PREFIX}{id(ws)}-{self._next_sub}"
        sub = self.node.event_bus.subscribe(subscriber, q)

        async def next_notification():
            msg = await sub.next()
            ev_name = (msg.attrs.get("tm.event") or [None])[0]
            return {
                "jsonrpc": "2.0", "id": None,
                "result": {"query": query,
                           "data": _event_json(msg.data, ev_name),
                           "events": msg.attrs},
            }

        from .jsonrpc import relay_events

        task = asyncio.get_running_loop().create_task(
            relay_events(ws, next_notification),
            name=f"ws-sub-{subscriber}")
        subs[query] = (subscriber, task)
        return {}

    async def unsubscribe(self, ctx, query="") -> dict:
        ws = ctx.ws
        subs = getattr(ws, "_subs", {}) if ws else {}
        ent = subs.pop(query, None)
        if ent is None:
            raise RPCError(-32603, f"not subscribed to {query!r}")
        subscriber, task = ent
        self.node.event_bus.unsubscribe_all(subscriber)
        task.cancel()
        return {}

    async def unsubscribe_all(self, ctx) -> dict:
        ws = ctx.ws
        for subscriber, task in getattr(ws, "_subs", {}).values():
            self.node.event_bus.unsubscribe_all(subscriber)
            task.cancel()
        if ws is not None:
            ws._subs = {}
        return {}

    def on_ws_close(self, ws) -> None:
        for subscriber, task in getattr(ws, "_subs", {}).values():
            self.node.event_bus.unsubscribe_all(subscriber)
            task.cancel()


def _event_json(data, event: str | None = None) -> dict:
    """JSON form of an event payload. `event` is the tm.event name
    from the pubsub attributes — round-state payloads share one
    dataclass across many event types (TimeoutPropose, Unlock, ...),
    so the name must come from the subscription, not the payload."""
    if isinstance(data, EventDataNewBlock):
        return {"type": "NewBlock", "block": _block_json(data.block)}
    if isinstance(data, EventDataTx):
        return {"type": "Tx", "height": str(data.height),
                "index": data.index, "tx": _b64(data.tx),
                "result": data.result}
    out = {"type": event or type(data).__name__}
    for k in ("height", "round", "step"):
        if hasattr(data, k):
            out[k] = getattr(data, k)
    return out


# --- JSON → types (for the RPC light provider) --------------------------------


def header_from_json(d: dict):
    from ..types.block import BlockID, Header, PartSetHeader

    def _bid(j):
        if not j or not j.get("hash"):
            return None
        return BlockID(bytes.fromhex(j["hash"]),
                       PartSetHeader(j["parts"]["total"],
                                     bytes.fromhex(j["parts"]["hash"])
                                     if j["parts"]["hash"] else b""))

    return Header(
        version_block=d["version"]["block"],
        version_app=d["version"]["app"],
        chain_id=d["chain_id"], height=int(d["height"]),
        time=int(d["time"]), last_block_id=_bid(d["last_block_id"]),
        last_commit_hash=bytes.fromhex(d["last_commit_hash"]),
        data_hash=bytes.fromhex(d["data_hash"]),
        validators_hash=bytes.fromhex(d["validators_hash"]),
        next_validators_hash=bytes.fromhex(d["next_validators_hash"]),
        consensus_hash=bytes.fromhex(d["consensus_hash"]),
        app_hash=bytes.fromhex(d["app_hash"]),
        last_results_hash=bytes.fromhex(d["last_results_hash"]),
        evidence_hash=bytes.fromhex(d["evidence_hash"]),
        proposer_address=bytes.fromhex(d["proposer_address"]),
    )


def commit_from_json(d: dict):
    from ..types.block import BlockID, Commit, CommitSig, PartSetHeader

    bid_j = d["block_id"]
    bid = BlockID(bytes.fromhex(bid_j["hash"]),
                  PartSetHeader(bid_j["parts"]["total"],
                                bytes.fromhex(bid_j["parts"]["hash"])))
    sigs = [CommitSig(
        block_id_flag=s["block_id_flag"],
        validator_address=bytes.fromhex(s["validator_address"]),
        timestamp=int(s["timestamp"]),
        signature=base64.b64decode(s["signature"]),
    ) for s in d["signatures"]]
    return Commit(int(d["height"]), d["round"], bid, sigs)


def validator_set_from_json(vals_json: list):
    from ..crypto.ed25519 import Ed25519PubKey
    from ..types.validator import Validator
    from ..types.validator_set import ValidatorSet

    vals = []
    for v in vals_json:
        pk = Ed25519PubKey(base64.b64decode(v["pub_key"]["value"]))
        vals.append(Validator(pk.address(), pk, int(v["voting_power"]),
                              int(v.get("proposer_priority", 0))))
    # Restore EXACTLY (order + proposer priorities): the ValidatorSet
    # constructor re-runs proposer-priority rotation, which would
    # desynchronize a state-synced node's proposer schedule from the
    # chain's (it would then reject every real proposer's signature).
    # The proposer resolves lazily from the restored priorities.
    vs = ValidatorSet([])
    vs.validators = vals
    if vals:
        vs.proposer = vs._find_proposer()  # from restored priorities
    return vs


async def serve(env: Environment, host: str, port: int):
    """Build the server and start listening; returns (server, port)."""
    from .jsonrpc import JSONRPCServer

    rpc_cfg = env.node.config.rpc
    srv = JSONRPCServer(
        env.routes(), env.ws_routes(),
        max_body=rpc_cfg.max_body_bytes,
        max_concurrent=rpc_cfg.max_concurrent_requests,
        rate_limit_rps=rpc_cfg.rate_limit_rps)
    srv._on_ws_close = env.on_ws_close
    actual = await srv.listen(host, port)
    return srv, actual
