"""JSON-RPC API layer (reference: rpc/).

Server: HTTP POST JSON-RPC 2.0, GET URI routes, and WebSocket
subscriptions, all on one listener (reference rpc/jsonrpc/server/).
Routes: reference rpc/core/routes.go:10-47. Clients: HTTP + WebSocket
(reference rpc/client/)."""
