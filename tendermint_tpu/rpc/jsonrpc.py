"""Minimal asyncio HTTP/1.1 + WebSocket JSON-RPC server and clients
(reference: rpc/jsonrpc/server/http_json_handler.go, ws_handler.go).

One listener serves three surfaces, like the reference:
  POST /            JSON-RPC 2.0 (single or batch)
  GET  /<method>?k=v  URI routes (params as query strings)
  GET  /websocket   WebSocket upgrade; JSON-RPC frames; server pushes
                    subscription events as jsonrpc notifications

Handlers are `async fn(ctx, **params) -> dict`; the registry maps
method name → handler. Stdlib-only (no aiohttp in the image)."""

from __future__ import annotations

import asyncio
import base64
import hashlib
import json
import logging
import time
import urllib.parse

from ..libs.overload import CONTROLLER, DropOldestQueue

logger = logging.getLogger("rpc.server")

_WS_MAGIC = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"
MAX_BODY = 1_000_000
# 429-style JSON-RPC error code for overload-limiter rejections (the
# JSON-RPC spec reserves no code for this; the HTTP status number is
# the conventional vocabulary and greppable in client logs).
CODE_BUSY = 429
# Bound on a WSClient's buffered notifications: a slow consumer loses
# the OLDEST events (counted in rpc_ws_events_dropped_total), never
# grows memory without limit.
WS_EVENTS_MAX = 1024


class RPCError(Exception):
    def __init__(self, code: int, message: str, data: str = ""):
        self.code = code
        self.message = message
        self.data = data
        super().__init__(message)


def _rpc_error(id_, code, message, data=""):
    err = {"code": code, "message": message}
    if data:
        err["data"] = data
    return {"jsonrpc": "2.0", "id": id_, "error": err}


def _rpc_result(id_, result):
    return {"jsonrpc": "2.0", "id": id_, "result": result}


class WSConnection:
    """Server side of one upgraded websocket (RFC6455, server never
    masks; close/ping handled inline)."""

    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer
        self.closed = False

    async def read_frame(self) -> tuple[int, bytes] | None:
        try:
            hdr = await self.reader.readexactly(2)
        except (asyncio.IncompleteReadError, ConnectionError):
            return None
        opcode = hdr[0] & 0x0F
        masked = hdr[1] & 0x80
        ln = hdr[1] & 0x7F
        if ln == 126:
            ln = int.from_bytes(await self.reader.readexactly(2), "big")
        elif ln == 127:
            ln = int.from_bytes(await self.reader.readexactly(8), "big")
        if ln > MAX_BODY:
            return None
        mask = await self.reader.readexactly(4) if masked else b""
        payload = await self.reader.readexactly(ln) if ln else b""
        if masked:
            payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        return opcode, payload

    def send_frame(self, payload: bytes, opcode: int = 0x1) -> None:
        if self.closed:
            return
        ln = len(payload)
        if ln < 126:
            hdr = bytes([0x80 | opcode, ln])
        elif ln < 1 << 16:
            hdr = bytes([0x80 | opcode, 126]) + ln.to_bytes(2, "big")
        else:
            hdr = bytes([0x80 | opcode, 127]) + ln.to_bytes(8, "big")
        self.writer.write(hdr + payload)

    def send_json(self, obj) -> None:
        self.send_frame(json.dumps(obj).encode())

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            try:
                self.send_frame(b"", opcode=0x8)
                self.writer.close()
            except Exception:
                pass


class JSONRPCServer:
    def __init__(self, routes: dict, ws_routes: dict | None = None,
                 max_body: int = MAX_BODY, max_concurrent: int = 0,
                 rate_limit_rps: float = 0.0):
        """routes: name → async fn(ctx, **params). ws_routes: extra
        routes only valid on a websocket (subscribe/unsubscribe); their
        ctx gets .ws set. max_concurrent / rate_limit_rps (0 = off)
        shed excess requests with a 429-style error instead of
        queueing them — protecting the event loop, which also runs
        consensus, from an RPC flood."""
        self.routes = routes
        self.ws_routes = ws_routes or {}
        self.max_body = max_body
        self.max_concurrent = max_concurrent
        self.rate_limit_rps = rate_limit_rps
        self._in_flight = 0
        self._tokens = float(max(rate_limit_rps, 1.0))
        self._tokens_t = time.monotonic()
        self._server: asyncio.AbstractServer | None = None
        self._on_ws_close = None
        if max_concurrent > 0:
            CONTROLLER.register("rpc.http", lambda: self._in_flight,
                                max_concurrent, owner=self)

    # -- overload limiter --

    def _admit(self) -> str | None:
        """None to admit; otherwise the rejection reason. Concurrency
        is checked FIRST so a request rejected for concurrency does
        not also burn a rate token — rejected traffic must not eat
        the budget of future legitimate requests. One token per
        admitted request, ~1 s of burst."""
        if 0 < self.max_concurrent <= self._in_flight:
            return "concurrency"
        if self.rate_limit_rps > 0:
            now = time.monotonic()
            # burst cap never below one whole token: a sub-1 rps limit
            # must still admit a request every 1/rate seconds, not
            # reject everything forever
            self._tokens = min(
                max(self.rate_limit_rps, 1.0),
                self._tokens + (now - self._tokens_t)
                * self.rate_limit_rps)
            self._tokens_t = now
            if self._tokens < 1.0:
                return "rate"
            self._tokens -= 1.0
        return None

    def _reject(self, id_, reason: str) -> dict:
        from ..libs.metrics import rpc_metrics

        rpc_metrics().requests_rejected.inc(reason=reason)
        CONTROLLER.shed("rpc.http")
        return _rpc_error(id_, CODE_BUSY,
                          "server overloaded; retry later", reason)

    def _gauge_in_flight(self) -> None:
        from ..libs.metrics import rpc_metrics

        rpc_metrics().requests_in_flight.set(self._in_flight)

    async def listen(self, host: str, port: int) -> int:
        self._server = await asyncio.start_server(self._serve_conn, host,
                                                  port)
        return self._server.sockets[0].getsockname()[1]

    def close(self) -> None:
        CONTROLLER.unregister("rpc.http", owner=self)
        if self._server is not None:
            self._server.close()

    # -- connection handling --

    async def _serve_conn(self, reader, writer) -> None:
        try:
            while True:
                req = await self._read_request(reader)
                if req is None:
                    break
                method, target, headers, body = req
                if headers.get("upgrade", "").lower() == "websocket":
                    await self._serve_websocket(reader, writer, headers)
                    return
                if method == "GET" and target.partition("?")[0] == "/metrics":
                    # Prometheus text exposition (reference serves this
                    # on the instrumentation listener; we also serve it
                    # here for one-port deployments).
                    from ..libs.metrics import DEFAULT as METRICS
                    from ..libs.metrics import node_metrics

                    node_metrics()  # full catalog on every scrape
                    keep = headers.get("connection", "").lower() != "close"
                    text = METRICS.render_text().encode()
                    writer.write(
                        b"HTTP/1.1 200 OK\r\n"
                        b"Content-Type: text/plain; version=0.0.4\r\n"
                        b"Content-Length: " + str(len(text)).encode() +
                        b"\r\nConnection: " +
                        (b"keep-alive" if keep else b"close") +
                        b"\r\n\r\n" + text)
                    await writer.drain()
                    if not keep:
                        break
                    continue
                reason = self._admit()
                if reason is not None:
                    resp, keep = self._reject(None, reason), True
                else:
                    self._in_flight += 1
                    self._gauge_in_flight()
                    try:
                        resp, keep = await self._dispatch_http(
                            method, target, body)
                    finally:
                        self._in_flight -= 1
                        self._gauge_in_flight()
                if headers.get("connection", "").lower() == "close":
                    keep = False
                self._write_response(writer, resp, keep)
                await writer.drain()
                if not keep:
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except Exception:
            logger.exception("rpc connection handler died")
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _read_request(self, reader):
        line = await reader.readline()
        if not line:
            return None
        try:
            method, target, _ = line.decode().split(" ", 2)
        except ValueError:
            return None
        headers = {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            k, _, v = h.decode().partition(":")
            headers[k.strip().lower()] = v.strip()
        ln = int(headers.get("content-length", 0))
        if ln > self.max_body:
            return None
        body = await reader.readexactly(ln) if ln else b""
        return method, target, headers, body

    def _write_response(self, writer, payload: dict | list,
                        keep: bool) -> None:
        body = json.dumps(payload).encode()
        writer.write(
            b"HTTP/1.1 200 OK\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: " + str(len(body)).encode() + b"\r\n"
            b"Connection: " + (b"keep-alive" if keep else b"close") +
            b"\r\n\r\n" + body)

    # -- dispatch --

    async def _dispatch_http(self, method: str, target: str, body: bytes):
        if method == "POST":
            try:
                req = json.loads(body or b"{}")
            except json.JSONDecodeError as e:
                return _rpc_error(None, -32700, "parse error", str(e)), False
            if isinstance(req, list):
                # Per-element admission: the connection handler charged
                # ONE admission for the HTTP request, which covers the
                # first element — every further element must pass the
                # limiter itself, or a single 1 MB batch body would
                # smuggle thousands of calls past the rate bucket.
                out, first = [], True
                for r in req:
                    reason = None if first else self._admit()
                    first = False
                    if reason is not None:
                        out.append(self._reject(
                            r.get("id") if isinstance(r, dict) else None,
                            reason))
                    else:
                        out.append(await self._call_one(r, None))
                return out, True
            return await self._call_one(req, None), True
        if method == "GET":
            path, _, query = target.partition("?")
            name = path.strip("/")
            if not name:
                return self._index(), True
            params = {k: _uri_param(v[0]) for k, v in
                      urllib.parse.parse_qs(query).items()}
            return await self._call_one(
                {"jsonrpc": "2.0", "id": -1, "method": name,
                 "params": params}, None), True
        return _rpc_error(None, -32600, f"unsupported method {method}"), \
            False

    def _index(self) -> dict:
        return _rpc_result(-1, {
            "routes": sorted(self.routes) + sorted(self.ws_routes)})

    async def _call_one(self, req: dict, ws) -> dict:
        if not isinstance(req, dict):
            return _rpc_error(None, -32600, "invalid request")
        id_ = req.get("id")
        name = req.get("method", "")
        handler = self.routes.get(name)
        if handler is None and ws is not None:
            handler = self.ws_routes.get(name)
        if handler is None:
            return _rpc_error(id_, -32601, f"method {name!r} not found")
        params = req.get("params") or {}
        if not isinstance(params, dict):
            return _rpc_error(id_, -32602, "params must be a map")
        ctx = _Ctx(ws)
        try:
            result = await handler(ctx, **params)
            return _rpc_result(id_, result)
        except RPCError as e:
            return _rpc_error(id_, e.code, e.message, e.data)
        except TypeError as e:
            return _rpc_error(id_, -32602, f"invalid params: {e}")
        except Exception as e:
            logger.exception("handler %s failed", name)
            return _rpc_error(id_, -32603, "internal error", str(e))

    # -- websocket --

    async def _serve_websocket(self, reader, writer, headers) -> None:
        key = headers.get("sec-websocket-key", "")
        accept = base64.b64encode(hashlib.sha1(
            (key + _WS_MAGIC).encode()).digest()).decode()
        writer.write(
            b"HTTP/1.1 101 Switching Protocols\r\n"
            b"Upgrade: websocket\r\nConnection: Upgrade\r\n"
            b"Sec-WebSocket-Accept: " + accept.encode() + b"\r\n\r\n")
        await writer.drain()
        ws = WSConnection(reader, writer)
        try:
            while True:
                frame = await ws.read_frame()
                if frame is None:
                    break
                opcode, payload = frame
                if opcode == 0x8:  # close
                    break
                if opcode == 0x9:  # ping
                    ws.send_frame(payload, opcode=0xA)
                    await writer.drain()
                    continue
                if opcode not in (0x1, 0x2):
                    continue
                try:
                    req = json.loads(payload)
                except json.JSONDecodeError:
                    ws.send_json(_rpc_error(None, -32700, "parse error"))
                    continue
                reqs = req if isinstance(req, list) else [req]
                for r in reqs:
                    reason = self._admit()
                    if reason is not None:
                        ws.send_json(self._reject(
                            r.get("id") if isinstance(r, dict) else None,
                            reason))
                        continue
                    self._in_flight += 1
                    self._gauge_in_flight()
                    try:
                        ws.send_json(await self._call_one(r, ws))
                    finally:
                        self._in_flight -= 1
                        self._gauge_in_flight()
                await writer.drain()
        finally:
            if self._on_ws_close is not None:
                try:
                    self._on_ws_close(ws)
                except Exception:
                    logger.exception("ws close hook failed")
            ws.close()


class _Ctx:
    def __init__(self, ws):
        self.ws = ws


class RawStr(str):
    """A URI param that arrived in explicit quotes (`tx="vk=v"`).

    The reference's URI handler decodes quoted values as RAW content
    while the JSON-RPC path carries byte params base64-encoded
    (rpc/jsonrpc/server/http_uri_handler.go vs JSON unmarshalling).
    Handlers with byte-typed params need that provenance to pick the
    right decoding — this marker carries it across the generic
    param-coercion boundary."""


class UriStr(str):
    """An UNQUOTED string param that arrived via the URI interface.

    Byte-typed handlers accept `0x`-hex only from URI values (the
    reference's URI-handler convention); a JSON-RPC base64 payload
    that merely LOOKS like 0x-hex must never be hex-decoded, so the
    0x branch is gated on this provenance marker."""


def _uri_param(v: str):
    """URI params arrive as strings; JSON-ify the obvious scalars
    (reference uri handler's type coercion). Int-coerce ONLY when the
    round trip is lossless: "0012" must stay a string — an all-digit
    hex payload (e.g. abci_query data) with leading zeros would
    otherwise be silently corrupted downstream."""
    if v in ("true", "false"):
        return v == "true"
    if v.startswith('"') and v.endswith('"') and len(v) >= 2:
        return RawStr(v[1:-1])
    try:
        n = int(v)
    except ValueError:
        return UriStr(v)
    return n if str(n) == v else UriStr(v)


# --- clients ------------------------------------------------------------------


class HTTPClient:
    """Async JSON-RPC-over-HTTP client (reference: rpc/client/http)."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.host = host
        self.port = port
        self.timeout = timeout
        self._id = 0

    async def call(self, method: str, **params):
        self._id += 1
        body = json.dumps({"jsonrpc": "2.0", "id": self._id,
                           "method": method, "params": params}).encode()
        reader, writer = await asyncio.open_connection(self.host, self.port)
        try:
            writer.write(
                b"POST / HTTP/1.1\r\nHost: rpc\r\n"
                b"Content-Type: application/json\r\n"
                b"Connection: close\r\n"
                b"Content-Length: " + str(len(body)).encode() +
                b"\r\n\r\n" + body)
            await writer.drain()
            raw = await asyncio.wait_for(reader.read(-1), self.timeout)
        finally:
            writer.close()
        _, _, payload = raw.partition(b"\r\n\r\n")
        resp = json.loads(payload)
        if resp.get("error"):
            e = resp["error"]
            raise RPCError(e.get("code", -1), e.get("message", ""),
                           e.get("data", ""))
        return resp["result"]


async def relay_events(ws, get_msg, drain_timeout: float = 30.0) -> None:
    """Pump `await get_msg()` results to a downstream websocket with
    backpressure: a subscriber that stops reading must not buffer
    event JSON in memory forever — it gets disconnected after
    drain_timeout instead. Shared by the node's subscribe pump
    (rpc/core.py) and the light proxy's passthrough."""
    while True:
        try:
            msg = await get_msg()
        except asyncio.CancelledError:
            return
        ws.send_json(msg)
        try:
            await asyncio.wait_for(ws.writer.drain(), drain_timeout)
        except (asyncio.TimeoutError, ConnectionError):
            ws.close()
            return


def _count_ws_event_drop() -> None:
    from ..libs.metrics import rpc_metrics

    rpc_metrics().ws_events_dropped.inc()


class WSClient:
    """Websocket JSON-RPC client with a BOUNDED notification queue
    (reference: rpc/jsonrpc/client/ws_client.go)."""

    def __init__(self, host: str, port: int,
                 events_max: int = WS_EVENTS_MAX):
        self.host = host
        self.port = port
        # Bounded drop-OLDEST buffer: a subscriber that stops reading
        # loses history (counted), not the process's memory. Newest
        # events win — they are the ones a catching-up consumer needs.
        self.events = DropOldestQueue(events_max, queue="rpc.ws_events",
                                      on_drop=_count_ws_event_drop)
        self._pending: dict[int, asyncio.Future] = {}
        self._id = 0
        self._task = None

    async def connect(self) -> None:
        self.reader, self.writer = await asyncio.open_connection(
            self.host, self.port)
        key = base64.b64encode(b"0123456789abcdef").decode()
        self.writer.write(
            b"GET /websocket HTTP/1.1\r\nHost: rpc\r\n"
            b"Upgrade: websocket\r\nConnection: Upgrade\r\n"
            b"Sec-WebSocket-Key: " + key.encode() +
            b"\r\nSec-WebSocket-Version: 13\r\n\r\n")
        await self.writer.drain()
        while True:  # consume the 101 response headers
            line = await self.reader.readline()
            if line in (b"\r\n", b""):
                break
        self._ws = WSConnection(self.reader, self.writer)
        self._task = asyncio.get_running_loop().create_task(
            self._recv_loop(), name="ws-client-recv")

    async def call(self, method: str, timeout: float = 10.0, **params):
        self._id += 1
        fut = asyncio.get_running_loop().create_future()
        self._pending[self._id] = fut
        self._send_json({"jsonrpc": "2.0", "id": self._id,
                         "method": method, "params": params})
        await self.writer.drain()
        return await asyncio.wait_for(fut, timeout)

    def _send_json(self, obj) -> None:
        # clients MUST mask frames (RFC6455 §5.3)
        payload = json.dumps(obj).encode()
        import os as _os

        mask = _os.urandom(4)
        masked = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        ln = len(masked)
        if ln < 126:
            hdr = bytes([0x81, 0x80 | ln])
        elif ln < 1 << 16:
            hdr = bytes([0x81, 0x80 | 126]) + ln.to_bytes(2, "big")
        else:
            hdr = bytes([0x81, 0x80 | 127]) + ln.to_bytes(8, "big")
        self.writer.write(hdr + mask + masked)

    async def _recv_loop(self) -> None:
        try:
            while True:
                frame = await self._ws.read_frame()
                if frame is None:
                    break
                opcode, payload = frame
                if opcode != 0x1:
                    continue
                msg = json.loads(payload)
                id_ = msg.get("id")
                fut = self._pending.pop(id_, None) if id_ is not None \
                    else None
                if fut is not None and not fut.done():
                    if msg.get("error"):
                        e = msg["error"]
                        fut.set_exception(RPCError(
                            e.get("code", -1), e.get("message", ""),
                            e.get("data", "")))
                    else:
                        fut.set_result(msg.get("result"))
                else:
                    self.events.put_nowait(msg)  # drop-oldest when full
        except (ConnectionError, asyncio.CancelledError):
            pass

    def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
        self.events.close()  # drop the overload-controller registration
        try:
            self.writer.close()
        except Exception:
            pass
