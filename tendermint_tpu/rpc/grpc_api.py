"""gRPC broadcast API (reference: rpc/grpc/grpc.go — the broadcast-only
gRPC surface external tooling expects next to the JSON-RPC server).

Service `tendermint.rpc.grpc.BroadcastAPI`:
  Ping(RequestPing) -> ResponsePing          liveness probe
  BroadcastTx(RequestBroadcastTx{tx}) -> ResponseBroadcastTx{check_tx,
      deliver_tx}                            broadcast_tx_commit semantics

Messages are JSON dicts (tx base64), matching the repo-wide choice of a
self-describing codec over generated pb stubs.
"""

from __future__ import annotations

import base64
import json

import grpc
from grpc import aio

from ..libs.service import Service

SERVICE_NAME = "tendermint.rpc.grpc.BroadcastAPI"


def _ser(d: dict) -> bytes:
    return json.dumps(d, separators=(",", ":")).encode()


def _de(b: bytes) -> dict:
    return json.loads(b)


class GRPCBroadcastServer(Service):
    def __init__(self, env, host: str = "127.0.0.1", port: int = 0):
        super().__init__(name="rpc.GRPCBroadcastServer")
        self.env = env  # rpc.core.Environment
        self.host, self.port = host, port
        self._server: aio.Server | None = None

    async def _ping(self, request: dict, context) -> dict:
        return {}

    async def _broadcast_tx(self, request: dict, context) -> dict:
        try:
            res = await self.env.broadcast_tx_commit(
                None, tx=request.get("tx", ""))
        except Exception as e:
            await context.abort(grpc.StatusCode.INTERNAL, repr(e))
        return {
            "check_tx": res.get("check_tx", {}),
            "deliver_tx": res.get("deliver_tx", {}),
        }

    async def on_start(self) -> None:
        self._server = aio.server()
        handlers = {
            "Ping": grpc.unary_unary_rpc_method_handler(
                self._ping, request_deserializer=_de,
                response_serializer=_ser),
            "BroadcastTx": grpc.unary_unary_rpc_method_handler(
                self._broadcast_tx, request_deserializer=_de,
                response_serializer=_ser),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
        )
        self.port = self._server.add_insecure_port(
            f"{self.host}:{self.port}")
        await self._server.start()
        self.logger.info("grpc broadcast api on %s:%d", self.host, self.port)

    async def on_stop(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=1.0)


class GRPCBroadcastClient:
    """reference: rpc/grpc/client_server.go StartGRPCClient."""

    def __init__(self, host: str, port: int):
        self._channel = aio.insecure_channel(f"{host}:{port}")
        self._ping = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Ping",
            request_serializer=_ser, response_deserializer=_de)
        self._btx = self._channel.unary_unary(
            f"/{SERVICE_NAME}/BroadcastTx",
            request_serializer=_ser, response_deserializer=_de)

    async def ping(self) -> dict:
        return await self._ping({})

    async def broadcast_tx(self, tx: bytes) -> dict:
        return await self._btx(
            {"tx": base64.b64encode(tx).decode()})

    async def close(self) -> None:
        await self._channel.close()
