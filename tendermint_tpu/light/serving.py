"""Light-client serving plane (ROADMAP item 4; no reference
equivalent — the reference light proxy walks the client serially, one
commit-verify per bisection pivot per request).

A skipping verify is two >1/3-power commit checks — exactly the shape
the batched verify kernel already accelerates — yet a proxy serving N
concurrent read-mostly clients used to pay N independent serial
verification walks. The ServingPlane here sits between the LightProxy
RPC surface (one or many workers — ServingPool) and the light
``Client`` and turns N concurrent requests into few wide launches:

  * **request coalescing + verified-header cache** — a singleflight
    map keyed by height makes concurrent requests for the same height
    pay ONE verification, and a trusting-period-aware in-memory LRU
    over the trusted ``LightStore`` makes the second client hitting a
    verified height cost a dict lookup, not a device launch;

  * **batched skipping verify** — a micro-batching collector (the
    ``mempool/admission.py`` flush-on-size-or-deadline shape) takes
    ``types/validator_set.py`` CommitVerifyPlans from independent
    requests AND from both checks of one bisection step (the trusted
    -overlap check and the new set's own +2/3 check run concurrently)
    and executes them as single wide ed25519 launches — breaker-aware
    with host fallback, one known-answer sentinel lane per device
    batch (a NaN-ing kernel fails the sentinel and the batch re-runs
    on host instead of failing requests on wrong verdicts);

  * **bounded pending-verify backlog** — the collector's parked +
    in-verify commit checks are the ``light.pending_verify`` entry in
    the overload QUEUES catalog: at the bound the NEWEST request is
    shed with a 429-style error, so a request flood dies at the
    plane, not in the event loop (and never behind a wedged device —
    the ``light.verify`` failpoint's `delay` shape is the proof).

The plane preserves the Client's verification semantics exactly —
same bisection pivots, same error taxonomy, same witness
cross-checking after the target verifies, same trusted-store writes —
only the signature work is pooled.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import time

import numpy as np

from ..libs.overload import CONTROLLER
from ..types.validator_set import CommitVerifyPlan, VerificationError
from .errors import (
    DivergenceError,
    LightClientError,
    NewValSetCantBeTrustedError,
    OutsideTrustingPeriodError,
    VerificationFailedError,
)
from .types import LightBlock

logger = logging.getLogger("light.serving")

PENDING_VERIFY_QUEUE = "light.pending_verify"

# Shed reasons — the closed label set of light_shed_total
# (tools/check_backpressure.py lints call sites against it).
SHED_QUEUE_FULL = "queue_full"
SHED_REASONS = (SHED_QUEUE_FULL,)


class LightServingShedError(LightClientError):
    """Pending-verify backlog full: the newest request is shed (429 at
    the proxy) — transient backpressure, NOT a verification verdict."""

    def __init__(self, depth: int, limit: int):
        super().__init__(
            f"light serving plane overloaded: {depth} commit checks "
            f"pending (limit {limit}); retry later")


# -- the process-global active plane (the /status `light` check) ------

_ACTIVE_PLANE: "ServingPlane | None" = None


def active_plane() -> "ServingPlane | None":
    """The most recently built (not yet closed) plane in this process
    — what libs/debugsrv.py's HealthMonitor reports under the `light`
    check. Several in-process test planes replace each other, same
    stance as the metric/controller singletons."""
    return _ACTIVE_PLANE


class VerifiedHeaderCache:
    """Trusting-period-aware LRU over verified LightBlocks.

    Backs the trusted LightStore with an O(1) hot path: the store
    round-trips JSON per get, this returns the live object. Entries
    whose header time has left the trusting period are evicted on
    read — a block outside its period must not be served as trusted
    (its valset may have long unbonded), even though it still sits in
    the persistent store."""

    def __init__(self, max_entries: int, period_ns: int):
        self.max_entries = max(1, max_entries)
        self.period_ns = period_ns
        self._d: collections.OrderedDict[int, LightBlock] = \
            collections.OrderedDict()

    def __len__(self) -> int:
        return len(self._d)

    def get(self, height: int, now_ns: int) -> LightBlock | None:
        lb = self._d.get(height)
        if lb is None:
            return None
        if lb.time() + self.period_ns <= now_ns:
            del self._d[height]
            return None
        self._d.move_to_end(height)
        return lb

    def put(self, lb: LightBlock, now_ns: int) -> None:
        if lb.time() + self.period_ns <= now_ns:
            return  # already expired: never cache
        self._d[lb.height()] = lb
        self._d.move_to_end(lb.height())
        while len(self._d) > self.max_entries:
            self._d.popitem(last=False)

    def clear(self) -> None:
        self._d.clear()


class _VerifyJob:
    __slots__ = ("plan", "future")

    def __init__(self, plan: CommitVerifyPlan, future: asyncio.Future):
        self.plan = plan
        self.future = future


class LightVerifyCollector:
    """Micro-batching commit-check collector (the admission-collector
    shape, but the unit of work is a CommitVerifyPlan of several
    signature lanes, not one tx).

    ``check(plan)`` parks the plan and awaits its verdict; a single
    flusher cuts batches once ``batch_max`` LANES have accumulated (or
    ``flush_ms`` after the first pending plan) and runs every plan's
    triples through ONE wide verify launch in an executor thread,
    scattering per-lane verdicts back per plan. A plan with any
    invalid lane gets the same VerificationError its inline execute()
    would raise — one request's lying provider never poisons the
    verdicts of the batchmates."""

    def __init__(self, batch_max: int = 1024, flush_ms: float = 2.0,
                 pending_max: int = 1024,
                 device_threshold: int | None = None, controller=None):
        from ..crypto import batch as cbatch

        self.batch_max = max(1, batch_max)
        self.flush_ms = flush_ms
        self.pending_max = max(1, pending_max)
        self.device_threshold = cbatch._DEVICE_THRESHOLD \
            if device_threshold is None else device_threshold
        self._controller = controller or CONTROLLER
        self._pending: collections.deque[_VerifyJob] = collections.deque()
        self._pending_lane_count = 0
        self._in_flight = 0
        self._item_evt = asyncio.Event()
        self._full_evt = asyncio.Event()
        self._flusher: asyncio.Task | None = None
        self._controller.register("light.pending_verify", self.depth,
                                  lambda: self.pending_max, owner=self)

    # -- sizes ---------------------------------------------------------

    def depth(self) -> int:
        """Backlog the bound applies to: parked + in-verify checks."""
        return len(self._pending) + self._in_flight

    def pending_lanes(self) -> int:
        # maintained incrementally: check() and the flusher consult
        # this per enqueue/wakeup, and a scan of a deep backlog here
        # would make admission quadratic exactly under load
        return self._pending_lane_count

    def saturated(self) -> bool:
        return self.depth() >= self.pending_max

    # -- lifecycle -----------------------------------------------------

    def close(self) -> None:
        if self._flusher is not None:
            self._flusher.cancel()
            self._flusher = None
        for job in self._pending:
            if not job.future.done():
                job.future.cancel()
        self._pending.clear()
        self._pending_lane_count = 0
        self._controller.unregister("light.pending_verify", owner=self)

    def _ensure_flusher(self) -> None:
        if self._flusher is None or self._flusher.done():
            self._flusher = asyncio.get_running_loop().create_task(
                self._flush_loop(), name="light-verify-flusher")

    # -- the await-a-verdict entry point -------------------------------

    async def check(self, plan: CommitVerifyPlan) -> None:
        """Queue `plan` for the next coalesced launch; returns when
        every lane verified, raises VerificationError (bad slots named
        exactly like the inline path) otherwise. Raises
        LightServingShedError (shed-newest) at the backlog bound —
        UNcounted: one shed REQUEST may park two plans (the gathered
        checks of a non-adjacent step), so the plane counts sheds once
        per request, not here per plan."""
        if self.depth() >= self.pending_max:
            raise LightServingShedError(self.depth(), self.pending_max)
        self._ensure_flusher()
        fut = asyncio.get_running_loop().create_future()
        self._pending.append(_VerifyJob(plan, fut))
        self._pending_lane_count += len(plan)
        self._item_evt.set()
        if self.pending_lanes() >= self.batch_max:
            self._full_evt.set()
        verdicts = await fut
        plan.raise_invalid(verdicts)

    # -- flusher -------------------------------------------------------

    async def _flush_loop(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            while not self._pending:
                self._item_evt.clear()
                await self._item_evt.wait()
            deadline = loop.time() + self.flush_ms / 1000.0
            while self.pending_lanes() < self.batch_max:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    break
                self._full_evt.clear()
                try:
                    await asyncio.wait_for(self._full_evt.wait(),
                                           remaining)
                except asyncio.TimeoutError:
                    break
            batch: list[_VerifyJob] = []
            lanes = 0
            while self._pending and (not batch
                                     or lanes < self.batch_max):
                job = self._pending.popleft()
                self._pending_lane_count -= len(job.plan)
                batch.append(job)
                lanes += len(job.plan)
            self._in_flight = len(batch)
            try:
                verdicts = await loop.run_in_executor(
                    None, self._verify_jobs, [j.plan for j in batch])
                for job, v in zip(batch, verdicts):
                    if not job.future.done():
                        job.future.set_result(v)
            except asyncio.CancelledError:
                for job in batch:
                    if not job.future.done():
                        job.future.cancel()
                raise
            except Exception as e:  # defensive: a verdict must land
                logger.exception("light verify batch died")
                for job in batch:
                    if not job.future.done():
                        job.future.set_exception(e)
            finally:
                self._in_flight = 0

    # -- the coalesced verify launch (executor thread) -----------------

    def _verify_jobs(self, plans: list[CommitVerifyPlan]
                     ) -> list[np.ndarray]:
        """Flatten every plan's triples into one launch, scatter the
        per-lane verdicts back per plan."""
        triples: list[tuple] = []
        spans: list[tuple[int, int]] = []
        for plan in plans:
            t = plan.triples()
            spans.append((len(triples), len(t)))
            triples.extend(t)
        verdicts = self._verify_triples(triples)
        return [verdicts[off:off + n] for off, n in spans]

    def _verify_triples(self, triples: list[tuple]) -> np.ndarray:
        # Same dispatch stance as the admission plane: one wide
        # general-kernel launch with a known-answer sentinel lane,
        # breaker-aware, host fallback — and the shared crypto/tpu
        # device-health counters move so dashboards see light-plane
        # launches next to consensus ones. Cross-plan batches mix
        # validator sets, so the general kernel (per-lane keys) is
        # the right tool, not any one set's expanded tables.
        from ..crypto import batch as cbatch
        from ..libs import failpoints
        from ..libs.metrics import (crypto_metrics, light_metrics,
                                    tpu_metrics)

        met = light_metrics()
        n = len(triples)
        met.batch_lanes.observe(n)
        t0 = time.perf_counter()
        try:
            try:
                failpoints.hit("light.verify")
            except failpoints.FailpointError:
                # injected launch failure: degrade to the host oracle,
                # exactly like a raising device launch
                met.verify_launches.inc(backend="host")
                crypto_metrics().batch_lanes.inc(n, backend="host")
                return self._host_verify(triples)
            ed = [i for i, (pk, _, _) in enumerate(triples)
                  if pk.type_name == "ed25519"]
            ed_set = set(ed)
            out = np.zeros(n, bool)
            # non-ed25519 lanes (sr25519/secp256k1 validators) verify
            # on host per key — rare in practice, never worth a
            # second kernel here
            for i in range(n):
                if i not in ed_set:
                    pk, m, s = triples[i]
                    try:
                        out[i] = pk.verify_signature(m, s)
                    except Exception:
                        out[i] = False
            if not ed:
                met.verify_launches.inc(backend="host")
                return out
            want_dev = len(ed) >= self.device_threshold
            use_dev = want_dev and cbatch.breaker("ed25519").acquire()
            if use_dev:
                try:
                    from ..crypto.tpu import verify as tpu_verify

                    failpoints.hit("device.verify")
                    # device_launches counts ATTEMPTS (the core
                    # BatchVerifier convention — a raising launch
                    # still burned a launch slot)
                    crypto_metrics().device_launches.inc()
                    # one known-answer sentinel lane rides every
                    # device batch (the breaker probe's triple): a
                    # NaN-ing kernel fails the sentinel, so wrong
                    # verdicts are detected POSITIVELY and the batch
                    # re-verifies on host instead of failing client
                    # requests on headers that are actually valid
                    spub, smsg, ssig = cbatch._ed_probe_triple()
                    from ..crypto.tpu import ledger as tpu_ledger

                    with tpu_ledger.workload("light"):
                        dv = np.asarray(tpu_verify.verify_batch(
                            [triples[i][0].bytes() for i in ed]
                            + [spub],
                            [triples[i][1] for i in ed] + [smsg],
                            [triples[i][2] for i in ed] + [ssig]),
                            bool)
                    # the launch LANDED: only now does it count as a
                    # device verify — a raising launch falls through
                    # to the host path as ONE host launch, never
                    # device+host for the same flush
                    met.verify_launches.inc(backend="device")
                    crypto_metrics().batch_lanes.inc(len(ed),
                                                     backend="tpu")
                    if dv[-1]:
                        out[np.asarray(ed)] = dv[:-1]
                        return out
                    cbatch.mark_device_failed("ed25519")
                    logger.error(
                        "light verify batch (%d lanes) failed its "
                        "known-answer sentinel; breaker open %.1fs, "
                        "re-verifying on host", len(ed),
                        cbatch.breaker("ed25519").cooldown_remaining())
                    met.verify_launches.inc(backend="host_recheck")
                    tpu_metrics().host_fallbacks.inc()
                    return self._host_verify(triples, into=out, only=ed)
                except Exception:
                    cbatch.mark_device_failed("ed25519")
                    logger.exception(
                        "light device batch failed (%d lanes); "
                        "breaker open %.1fs, degrading to host",
                        len(ed),
                        cbatch.breaker("ed25519").cooldown_remaining())
            if want_dev:
                tpu_metrics().host_fallbacks.inc()
            met.verify_launches.inc(backend="host")
            crypto_metrics().batch_lanes.inc(len(ed), backend="host")
            return self._host_verify(triples, into=out, only=ed)
        finally:
            met.verify_seconds.observe(time.perf_counter() - t0)

    @staticmethod
    def _host_verify(triples: list[tuple], into: np.ndarray | None = None,
                     only: list[int] | None = None) -> np.ndarray:
        out = np.zeros(len(triples), bool) if into is None else into
        idxs = range(len(triples)) if only is None else only
        for i in idxs:
            pk, m, s = triples[i]
            try:
                out[i] = len(s) == 64 and pk.verify_signature(m, s)
            except Exception:
                out[i] = False
        return out


class ServingPlane:
    """The shared verification plane N proxy workers run requests
    through. One plane owns one light Client (and its trusted store);
    requests enter via get_verified()."""

    def __init__(self, client, config=None, controller=None):
        from ..config import LightConfig

        cfg = config or LightConfig()
        cfg.validate_basic()
        self.client = client
        self.config = cfg
        self.cache = VerifiedHeaderCache(
            cfg.cache_size, client.trust_options.period_ns)
        self.collector = LightVerifyCollector(
            batch_max=cfg.batch_max, flush_ms=cfg.flush_ms,
            pending_max=cfg.pending_max, controller=controller)
        self._inflight: dict[int, asyncio.Task] = {}
        # running tallies for the /status `light` check (metric
        # counters mirror these with labels)
        self.requests = 0
        self.coalesced = 0
        self.cache_hits = 0
        self.cache_misses = 0
        self.sheds: dict[str, int] = {r: 0 for r in SHED_REASONS}
        global _ACTIVE_PLANE
        _ACTIVE_PLANE = self

    def close(self) -> None:
        self.collector.close()
        for task in self._inflight.values():
            task.cancel()
        self._inflight.clear()
        global _ACTIVE_PLANE
        if _ACTIVE_PLANE is self:
            _ACTIVE_PLANE = None

    # -- the request entry point ---------------------------------------

    async def get_verified(self, height: int = 0) -> LightBlock:
        """Verified LightBlock at `height` (0 = the primary's latest).
        Coalesces with any in-flight verification of the same height;
        sheds (LightServingShedError) when the pending-verify backlog
        is at its bound and this request would start a NEW
        verification."""
        from ..libs.metrics import light_metrics

        met = light_metrics()
        self.requests += 1
        now_ns = self.client.now_fn()
        if height:
            lb = self.cache.get(height, now_ns)
            if lb is not None:
                self.cache_hits += 1
                met.cache_hits.inc()
                return lb
            self.cache_misses += 1
            met.cache_misses.inc()
            # trusted-store probe BEFORE the admission gate: a height
            # already verified and still inside its period is a READ,
            # not new verification work — it serves even with the
            # plane fully saturated (LRU refilled in passing), and
            # without spawning a singleflight task
            stored = self.client.store.get(height)
            if stored is not None and stored.time() + \
                    self.client.trust_options.period_ns > now_ns:
                self.cache.put(stored, now_ns)
                return stored
        task = self._inflight.get(height)
        if task is not None and not task.done():
            # join the in-flight verification: no new device work, no
            # queue growth — the whole point of the singleflight map
            self.coalesced += 1
            met.requests_coalesced.inc()
            return await self._await_counted(task)
        if self.collector.saturated():
            # shed at ADMISSION: a flood of distinct heights must die
            # here with a cheap 429, not deep inside a bisection.
            # This gate covers backwards walks too — they never enter
            # the pending-verify queue, but each one is new work
            # (primary fetches per uncached interim), and a scrape-
            # the-history flood of distinct cold heights must not
            # amplify into unbounded concurrent walks while the
            # plane is already saturated. Store-resident heights
            # were served above, before the gate.
            self._count_shed(SHED_QUEUE_FULL)
            raise LightServingShedError(self.collector.depth(),
                                        self.collector.pending_max)
        task = asyncio.get_running_loop().create_task(
            self._verify_height(height, now_ns),
            name=f"light-verify-h{height}")
        self._inflight[height] = task

        def _done(t, h=height):
            if self._inflight.get(h) is t:
                del self._inflight[h]
            # every waiter may have been cancelled (client timeouts
            # are routine on a public proxy) while the shielded task
            # ran on — retrieve the exception so asyncio doesn't log
            # "Task exception was never retrieved" for an error that
            # simply had no one left to deliver to
            if not t.cancelled():
                t.exception()

        task.add_done_callback(_done)
        return await self._await_counted(task)

    async def _await_counted(self, task: asyncio.Task) -> LightBlock:
        """Await the shared verification (shield: a cancelled waiter
        must not cancel the task other coalesced waiters are parked
        on) and count a mid-verification shed PER AFFECTED REQUEST —
        every waiter surfaces a 429, so every waiter moves the shed
        counters, keeping 429s == light_shed_total == /status tally
        even when coalesced joiners ride a verification that sheds."""
        try:
            return await asyncio.shield(task)
        except LightServingShedError:
            self._count_shed(SHED_QUEUE_FULL)
            raise

    def _count_shed(self, reason: str) -> None:
        """ONE shed request: /status tally + metric + the controller
        tracking the pending-verify queue (the collector's, which may
        be an injected test controller — never unconditionally the
        process-global one)."""
        from ..libs.metrics import light_metrics

        self.sheds[reason] += 1
        light_metrics().shed.inc(reason=reason)
        self.collector._controller.shed(PENDING_VERIFY_QUEUE)

    # -- the singleflight body -----------------------------------------

    async def _verify_height(self, height: int,
                             now_ns: int) -> LightBlock:
        cl = self.client
        if not cl._initialized:
            await cl.initialize()
        period = cl.trust_options.period_ns
        if height:
            stored = cl.store.get(height)
            if stored is not None:
                if stored.time() + period > now_ns:
                    self.cache.put(stored, now_ns)
                    return stored
                # outside its trusting period: the old verification
                # alone no longer makes it servable (the serial
                # client returns stored blocks unconditionally — the
                # plane serves UNTRUSTED public clients and enforces
                # the cache's documented invariant on the store path
                # too). Below the trusted head the backwards walk
                # re-proves it by hash linkage from an IN-period
                # anchor; at the head there is nothing to anchor on.
                latest = cl.store.latest()
                if latest is None or height >= latest.height():
                    raise OutsideTrustingPeriodError(
                        f"stored header {height} outside trusting "
                        "period")
                return await cl._verify_backwards(height, now_ns)
            latest = cl.store.latest()
            if latest is not None and height < latest.height():
                # hash-chain walk down — no commit signatures to
                # batch; the client's walk (with its linkage cache)
                # is already the right tool
                lb = await cl._verify_backwards(height, now_ns)
                self.cache.put(lb, now_ns)
                return lb
            target = await cl._from_primary(height)
        else:
            target = await cl._from_primary(0)
            latest = cl.store.latest()
            if latest is not None and \
                    target.height() <= latest.height():
                if latest.time() + period <= now_ns:
                    raise OutsideTrustingPeriodError(
                        f"trusted head {latest.height()} outside "
                        "trusting period")
                self.cache.put(latest, now_ns)
                return latest
        # verify from the head captured BEFORE the fetch (the serial
        # client's order): a concurrent task may have advanced
        # store.latest() past `height` while _from_primary awaited,
        # and a re-read here would make _common_checks refuse a
        # perfectly servable height ("target not above trusted")
        trusted = latest
        assert trusted is not None
        try:
            await self._verify_skipping(trusted, target, now_ns)
            await cl._detect_divergence(target, now_ns)
        except DivergenceError:
            # a PROVEN fork purged the trusted store above the common
            # height — the LRU may still hold the attacker's chain;
            # drop everything rather than risk serving it
            self.cache.clear()
            raise
        # a mid-verification LightServingShedError propagates
        # UNcounted from here: _await_counted counts it once per
        # affected waiter (the collector raises uncounted too — one
        # request may park two plans and both may shed)
        self.cache.put(target, now_ns)
        return target

    # -- batched skipping verification ---------------------------------

    async def _verify_skipping(self, trusted: LightBlock,
                               target: LightBlock,
                               now_ns: int) -> None:
        """Client._verify_skipping with the commit checks routed
        through the coalescing collector: same pivots, same error
        taxonomy, same store writes."""
        cl = self.client
        pending: list[LightBlock] = [target]
        seen: set[int] = {target.height()}
        steps = 0
        while pending:
            steps += 1
            if steps > 200:  # 2^200 heights — unreachable honestly
                raise LightClientError("bisection did not converge")
            block = pending[-1]
            try:
                await self._verify_one(trusted, block, now_ns)
            except NewValSetCantBeTrustedError:
                pivot_h = (trusted.height() + block.height()) // 2
                if pivot_h in (trusted.height(), block.height()) or \
                        pivot_h in seen:
                    raise  # can't split further: genuine failure
                pivot = await cl._from_primary(pivot_h)
                seen.add(pivot_h)
                pending.append(pivot)
                continue
            cl.store.save(block)
            self.cache.put(block, now_ns)
            trusted = block
            pending.pop()

    async def _verify_one(self, trusted: LightBlock,
                          untrusted: LightBlock, now_ns: int) -> None:
        """verifier.verify with the signature work pooled: the
        non-crypto checks run inline, the commit check(s) become
        CommitVerifyPlans awaited through the collector — the two
        checks of a non-adjacent step verify CONCURRENTLY, so they
        coalesce with each other and with every other in-flight
        request's checks into the same wide launches."""
        from .verifier import _common_checks

        cl = self.client
        chain_id = cl.chain_id
        period = cl.trust_options.period_ns
        sh = untrusted.signed_header
        if untrusted.height() == trusted.height() + 1:
            _common_checks(chain_id, trusted, untrusted, period, now_ns)
            if sh.header.validators_hash != \
                    trusted.signed_header.header.next_validators_hash:
                raise VerificationFailedError(
                    "new validators_hash != trusted next_validators_hash")
            try:
                plan = untrusted.validator_set.plan_commit_light(
                    chain_id, sh.commit.block_id, sh.header.height,
                    sh.commit)
            except VerificationError as e:
                raise VerificationFailedError(
                    f"invalid commit: {e}") from e
            try:
                await self.collector.check(plan)
            except VerificationError as e:
                raise VerificationFailedError(
                    f"invalid commit: {e}") from e
            return
        _common_checks(chain_id, trusted, untrusted, period, now_ns)
        try:
            plan_trusting = trusted.validator_set.plan_commit_trusting(
                chain_id, sh.commit, cl.trust_level.numerator,
                cl.trust_level.denominator)
        except VerificationError as e:
            raise NewValSetCantBeTrustedError(str(e)) from e
        try:
            plan_light = untrusted.validator_set.plan_commit_light(
                chain_id, sh.commit.block_id, sh.header.height,
                sh.commit)
        except VerificationError as e:
            # own-commit cannot even reach 2/3 — but the reference
            # order gives the TRUSTING check its verdict first, and a
            # failed overlap drives bisection, not rejection
            try:
                await self.collector.check(plan_trusting)
            except VerificationError as e2:
                raise NewValSetCantBeTrustedError(str(e2)) from e2
            raise VerificationFailedError(f"invalid commit: {e}") from e
        # both-or-neither admission for the gathered pair: if only
        # ONE slot remains, parking the trusting check and shedding
        # its twin would delay the 429 until the admitted (possibly
        # stalled) launch completes and throw its verdict away —
        # shed promptly instead (the per-check gate in check() stays
        # the hard bound)
        coll = self.collector
        if coll.depth() + 2 > coll.pending_max:
            raise LightServingShedError(coll.depth(), coll.pending_max)
        res_t, res_l = await asyncio.gather(
            self.collector.check(plan_trusting),
            self.collector.check(plan_light),
            return_exceptions=True)
        # error taxonomy parity with verifier.verify_non_adjacent: a
        # failed TRUSTING check (insufficient overlap OR bad overlap
        # signature) drives bisection; a failed own-commit check is a
        # definitive rejection; anything else (shed, cancellation)
        # propagates untouched
        if isinstance(res_t, VerificationError):
            raise NewValSetCantBeTrustedError(str(res_t)) from res_t
        if isinstance(res_t, BaseException):
            raise res_t
        if isinstance(res_l, VerificationError):
            raise VerificationFailedError(
                f"invalid commit: {res_l}") from res_l
        if isinstance(res_l, BaseException):
            raise res_l

    # -- /status -------------------------------------------------------

    def status_check(self) -> dict:
        """The GET /status `light` check body: backlog fill, request/
        coalesce/cache tallies, shed breakdown, verify-backend split.
        Shedding is designed behavior — only a saturated pending-
        verify backlog degrades the check."""
        from ..crypto import batch as cbatch
        from ..libs.metrics import light_metrics

        met = light_metrics()
        depth = self.collector.depth()
        cap = self.collector.pending_max
        out: dict = {
            "requests": self.requests,
            "coalesced": self.coalesced,
            "cache": {"entries": len(self.cache),
                      "hits": self.cache_hits,
                      "misses": self.cache_misses},
            "queue_depth": depth,
            "queue_capacity": cap,
            "shed": {r: n for r, n in self.sheds.items() if n},
            "trusted_height": self.client.store.latest_height(),
            "verify_launches": {
                b: int(met.verify_launches.value(backend=b))
                for b in ("device", "host", "host_recheck")
                if met.verify_launches.value(backend=b)},
        }
        fill = depth / cap if cap else 0.0
        if fill >= 0.8:
            out["status"] = "degraded"
            out["detail"] = (f"pending-verify backlog at {fill:.0%}; "
                             "shedding newest requests soon")
        else:
            out["status"] = "ok"
            if not cbatch.device_available("ed25519"):
                out["detail"] = ("ed25519 breaker open: light plane "
                                 "verifying on host")
        return out


class ServingPool:
    """N LightProxy workers sharing ONE plane (one client, one trusted
    store, one verify collector, one cache) — the horizontally
    scalable serving face: more workers add RPC accept/parse
    capacity, while every verification still coalesces in the shared
    plane."""

    def __init__(self, client, workers: int | None = None, config=None,
                 forward_clients=None, proof_runtime=None):
        from ..config import LightConfig
        from .proxy import LightProxy

        cfg = config or LightConfig()
        n = cfg.workers if workers is None else workers
        if n < 1:
            raise ValueError("serving pool needs at least one worker")
        self.plane = ServingPlane(client, cfg)
        fwds = forward_clients or [None] * n
        if len(fwds) != n:
            raise ValueError(
                f"{len(fwds)} forward clients for {n} workers")
        self.proxies = [
            LightProxy(client, forward_client=fwds[i],
                       proof_runtime=proof_runtime, plane=self.plane)
            for i in range(n)
        ]
        self.ports: list[int] = []

    async def listen(self, host: str,
                     ports: list[int] | None = None) -> list[int]:
        ports = ports or [0] * len(self.proxies)
        if len(ports) != len(self.proxies):
            raise ValueError(
                f"{len(ports)} ports for {len(self.proxies)} workers")
        self.ports = [await proxy.listen(host, port)
                      for proxy, port in zip(self.proxies, ports)]
        logger.info("light serving pool: %d workers on %s:%s",
                    len(self.proxies), host, self.ports)
        return self.ports

    def close(self) -> None:
        for proxy in self.proxies:
            proxy.close()
        self.plane.close()
