"""Trusted light-block store (reference: light/store/db/db.go).

Persists verified LightBlocks keyed by height; the client resumes from
the highest trusted block after restart."""

from __future__ import annotations

import json

from ..state.store import _valset_from_json, _valset_to_json
from ..types.block import Commit, Header
from .types import LightBlock, SignedHeader

_PREFIX = b"lb/"


def _key(height: int) -> bytes:
    return _PREFIX + height.to_bytes(8, "big")


class LightStore:
    def __init__(self, db):
        self.db = db
        # Highest saved height, maintained incrementally after the
        # first scan. latest_height() used to walk the WHOLE prefix on
        # every call — and the light client calls it (via latest()) on
        # every single verify request, so a proxy serving a long chain
        # paid an O(stored-heights) scan per request. None = unknown
        # (not yet scanned, or invalidated by a delete/prune that may
        # have removed the maximum).
        self._latest: int | None = None

    def save(self, lb: LightBlock) -> None:
        payload = json.dumps({
            "header": lb.signed_header.header.to_proto().finish().hex(),
            "commit": lb.signed_header.commit.to_bytes().hex(),
            "validators": _valset_to_json(lb.validator_set),
        }).encode()
        self.db.set(_key(lb.height()), payload)
        if self._latest is not None:
            self._latest = max(self._latest, lb.height())

    def get(self, height: int) -> LightBlock | None:
        raw = self.db.get(_key(height))
        if raw is None:
            return None
        d = json.loads(raw)
        return LightBlock(
            SignedHeader(Header.from_bytes(bytes.fromhex(d["header"])),
                         Commit.from_bytes(bytes.fromhex(d["commit"]))),
            _valset_from_json(d["validators"]),
        )

    def latest(self) -> LightBlock | None:
        latest_h = self.latest_height()
        return self.get(latest_h) if latest_h else None

    def latest_height(self) -> int:
        if self._latest is None:
            best = 0
            for k, _ in self.db.iterate_prefix(_PREFIX):
                h = int.from_bytes(k[len(_PREFIX):], "big")
                best = max(best, h)
            self._latest = best
        return self._latest

    def lowest_height(self) -> int:
        for k, _ in self.db.iterate_prefix(_PREFIX):
            return int.from_bytes(k[len(_PREFIX):], "big")
        return 0

    def heights(self) -> list[int]:
        return [int.from_bytes(k[len(_PREFIX):], "big")
                for k, _ in self.db.iterate_prefix(_PREFIX)]

    def delete(self, height: int) -> None:
        self.db.delete(_key(height))
        if self._latest is not None and height >= self._latest:
            # the cached maximum may be gone; rescan on next read
            self._latest = None

    def prune(self, keep: int) -> None:
        hs = self.heights()
        for h in hs[:-keep] if keep else hs:
            self.db.delete(_key(h))
        # pruning keeps the TOP `keep` heights, so the maximum
        # survives when keep > 0 — but a full prune empties the store
        if not keep:
            self._latest = None
