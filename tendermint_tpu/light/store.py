"""Trusted light-block store (reference: light/store/db/db.go).

Persists verified LightBlocks keyed by height; the client resumes from
the highest trusted block after restart."""

from __future__ import annotations

import json

from ..state.store import _valset_from_json, _valset_to_json
from ..types.block import Commit, Header
from .types import LightBlock, SignedHeader

_PREFIX = b"lb/"


def _key(height: int) -> bytes:
    return _PREFIX + height.to_bytes(8, "big")


class LightStore:
    def __init__(self, db):
        self.db = db

    def save(self, lb: LightBlock) -> None:
        payload = json.dumps({
            "header": lb.signed_header.header.to_proto().finish().hex(),
            "commit": lb.signed_header.commit.to_bytes().hex(),
            "validators": _valset_to_json(lb.validator_set),
        }).encode()
        self.db.set(_key(lb.height()), payload)

    def get(self, height: int) -> LightBlock | None:
        raw = self.db.get(_key(height))
        if raw is None:
            return None
        d = json.loads(raw)
        return LightBlock(
            SignedHeader(Header.from_bytes(bytes.fromhex(d["header"])),
                         Commit.from_bytes(bytes.fromhex(d["commit"]))),
            _valset_from_json(d["validators"]),
        )

    def latest(self) -> LightBlock | None:
        latest_h = self.latest_height()
        return self.get(latest_h) if latest_h else None

    def latest_height(self) -> int:
        best = 0
        for k, _ in self.db.iterate_prefix(_PREFIX):
            h = int.from_bytes(k[len(_PREFIX):], "big")
            best = max(best, h)
        return best

    def lowest_height(self) -> int:
        for k, _ in self.db.iterate_prefix(_PREFIX):
            return int.from_bytes(k[len(_PREFIX):], "big")
        return 0

    def heights(self) -> list[int]:
        return [int.from_bytes(k[len(_PREFIX):], "big")
                for k, _ in self.db.iterate_prefix(_PREFIX)]

    def delete(self, height: int) -> None:
        self.db.delete(_key(height))

    def prune(self, keep: int) -> None:
        hs = self.heights()
        for h in hs[:-keep] if keep else hs:
            self.db.delete(_key(h))
