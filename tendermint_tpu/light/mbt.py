"""Model-based-test fixture driver for the light-client verifier
(reference: light/mbt/driver_test.go, which replays JSON fixtures
generated from the TLA+ light-client spec via tendermint-rs testgen).

Fixture schema (JSON):

    {
      "description": "...",
      "chain_id": "mbt-chain",
      "trust_level": [1, 3],
      "initial": {
        "block": "<hex of LightBlock proto bytes>",
        "trusting_period_ns": 3600000000000,
        "now_ns": 1700000001000000000
      },
      "input": [
        {"block": "<hex>", "now_ns": ..., "verdict": "SUCCESS"},
        {"block": "<hex>", "now_ns": ..., "verdict": "INVALID"},
        ...
      ]
    }

Driver semantics (same as the reference's): each input step runs ONE
`verify` of the step's block against the current trusted block at the
step's `now`; SUCCESS advances the trusted block, NOT_ENOUGH_TRUST
(insufficient trusted-valset overlap — the signal that drives
bisection) and INVALID leave it unchanged. The corpus lives in
tests/light_fixtures/ (generated in-repo by tests/gen_light_fixtures.py
— own generation, covering the trust-expiry x adjacency x
valset-rotation x attack lattice).
"""

from __future__ import annotations

import json
from fractions import Fraction

from .errors import (
    LightClientError,
    NewValSetCantBeTrustedError,
)
from .types import LightBlock
from .verifier import verify

SUCCESS = "SUCCESS"
NOT_ENOUGH_TRUST = "NOT_ENOUGH_TRUST"
INVALID = "INVALID"


def classify(chain_id: str, trusted: LightBlock, untrusted: LightBlock,
             trusting_period_ns: int, now_ns: int,
             trust_level: Fraction) -> str:
    """One verification attempt -> its fixture verdict."""
    try:
        verify(chain_id, trusted, untrusted, trusting_period_ns, now_ns,
               trust_level)
        return SUCCESS
    except NewValSetCantBeTrustedError:
        return NOT_ENOUGH_TRUST
    except (LightClientError, ValueError):
        # ValueError: validate_basic structural failures
        return INVALID


def run_fixture(doc: dict) -> list[str]:
    """Replay one fixture; returns the verdicts produced (for
    reporting). Raises AssertionError on the first divergence."""
    chain_id = doc["chain_id"]
    tl = doc.get("trust_level", [1, 3])
    trust_level = Fraction(tl[0], tl[1])
    init = doc["initial"]
    trusted = LightBlock.from_bytes(bytes.fromhex(init["block"]))
    period = int(init["trusting_period_ns"])
    verdicts = []
    for i, step in enumerate(doc["input"]):
        block = LightBlock.from_bytes(bytes.fromhex(step["block"]))
        got = classify(chain_id, trusted, block, period,
                       int(step["now_ns"]), trust_level)
        verdicts.append(got)
        want = step["verdict"]
        assert got == want, (
            f"{doc.get('description', '?')}: step {i} (height "
            f"{block.height()}): got {got}, want {want}")
        if got == SUCCESS:
            trusted = block
    return verdicts


def run_fixture_file(path: str) -> list[str]:
    with open(path) as f:
        return run_fixture(json.load(f))
