"""Light-client data types (reference: types/light.go) and
LightClientAttackEvidence (reference: types/evidence.go:215).

A LightBlock is the minimum a light client needs per height: the
signed header (header + commit) and the validator set that signed it.
LightClientAttackEvidence proves a set of validators signed a
conflicting light block: the detector builds it on witness/primary
divergence (light/client.py) and full nodes verify it against their
own chain (evidence/verify.py), punishing the signers via ABCI."""

from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto import tmhash
from ..encoding.proto import Reader, Writer
from ..types.block import Commit, Header
from ..types.evidence import Evidence
from ..types.validator import Validator
from ..types.validator_set import ValidatorSet


@dataclass
class SignedHeader:
    header: Header
    commit: Commit

    def validate_basic(self, chain_id: str) -> None:
        if self.header is None or self.commit is None:
            raise ValueError("signed header missing header or commit")
        self.header.validate_basic()
        self.commit.validate_basic()
        if self.header.chain_id != chain_id:
            raise ValueError(
                f"header chain id {self.header.chain_id!r} != {chain_id!r}")
        if self.commit.height != self.header.height:
            raise ValueError("commit height != header height")
        if self.commit.block_id.hash != self.header.hash():
            raise ValueError("commit is for a different block")


@dataclass
class LightBlock:
    signed_header: SignedHeader
    validator_set: ValidatorSet

    def height(self) -> int:
        return self.signed_header.header.height

    def time(self) -> int:
        return self.signed_header.header.time

    def hash(self) -> bytes:
        return self.signed_header.header.hash()

    def validate_basic(self, chain_id: str) -> None:
        self.signed_header.validate_basic(chain_id)
        self.validator_set.validate_basic()
        if self.signed_header.header.validators_hash != \
                self.validator_set.hash():
            raise ValueError(
                "validator set does not match header validators_hash")

    def to_proto(self) -> Writer:
        """Wire layout mirrors the reference proto exactly
        (proto/tendermint/types/types.proto:140 LightBlock:
        signed_header=1, validator_set=2; validator.proto:9
        ValidatorSet: validators=1, proposer=2, total_voting_power=3)
        so evidence bytes and hashes interop with reference-format
        peers."""
        sh = Writer()
        sh.bytes(1, self.signed_header.header.to_proto().finish(),
                 skip_empty=False)
        sh.bytes(2, self.signed_header.commit.to_bytes(),
                 skip_empty=False)
        vs = Writer()
        for v in self.validator_set.validators:
            vs.bytes(1, v.to_proto().finish(), skip_empty=False)
        if self.validator_set.proposer is not None:
            vs.bytes(2, self.validator_set.proposer.to_proto().finish(),
                     skip_empty=False)
        vs.varint(3, self.validator_set.total_voting_power())
        w = Writer()
        w.message(1, sh)
        w.message(2, vs)
        return w

    @classmethod
    def from_bytes(cls, data: bytes) -> "LightBlock":
        r = Reader(data)
        header = commit = None
        proposer: Validator | None = None
        vals: list[Validator] = []
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                sr = Reader(r.bytes())
                while not sr.at_end():
                    sf, swt = sr.field()
                    if sf == 1:
                        header = Header.from_bytes(sr.bytes())
                    elif sf == 2:
                        commit = Commit.from_bytes(sr.bytes())
                    else:
                        sr.skip(swt)
            elif f == 2:
                vr = Reader(r.bytes())
                while not vr.at_end():
                    vf, vwt = vr.field()
                    if vf == 1:
                        vals.append(Validator.from_bytes(vr.bytes()))
                    elif vf == 2:
                        proposer = Validator.from_bytes(vr.bytes())
                    elif vf == 3:
                        vr.varint()  # total_voting_power: recomputed
                    else:
                        vr.skip(vwt)
            else:
                r.skip(wt)
        if header is None or commit is None:
            raise ValueError("light block missing header or commit")
        # Restore the set EXACTLY (order, priorities, proposer): the
        # ValidatorSet constructor re-runs proposer-priority rotation,
        # which would change the wire bytes and thus the evidence hash.
        vs = ValidatorSet([])
        vs.validators = vals
        if proposer is not None:
            _, vp = vs.get_by_address(proposer.address)
            vs.proposer = vp if vp is not None else proposer
        return cls(SignedHeader(header, commit), vs)


def conflicting_header_is_invalid(conflicting: Header, trusted: Header) -> bool:
    """True when the conflicting header could not have been produced by
    the chain the trusted header is on — a LUNATIC attack: any of the
    deterministically-derived fields differ (reference:
    types/evidence.go ConflictingHeaderIsInvalid)."""
    return (
        conflicting.validators_hash != trusted.validators_hash
        or conflicting.next_validators_hash != trusted.next_validators_hash
        or conflicting.consensus_hash != trusted.consensus_hash
        or conflicting.app_hash != trusted.app_hash
        or conflicting.last_results_hash != trusted.last_results_hash
    )


def compute_byzantine_validators(common_vals: ValidatorSet,
                                 trusted: "SignedHeader",
                                 conflicting_block: "LightBlock"
                                 ) -> list[Validator]:
    """The punishable signer set for an attack, deterministically
    derived so the detector and every verifying full node agree
    (reference: types/evidence.go:253-280 GetByzantineValidators):

    - LUNATIC (conflicting header is invalid w.r.t. the trusted one):
      validators of the COMMON valset that signed the conflicting
      commit — they signed off a header the chain could never produce.
    - EQUIVOCATION (commit ROUNDS equal, header otherwise valid):
      validators that voted in BOTH commits — only signing both is
      double-signing; a validator that precommitted only the
      conflicting block may have done so legitimately. The valsets
      are identical (validators_hash matches), so the commits are
      index-aligned and one indexed pass suffices.
    - AMNESIA (rounds differ, header valid): indeterminable from the
      evidence alone; empty list.

    Ordered by voting power (desc, address tiebreak), matching the
    reference's ValidatorsByVotingPower sort.
    """
    commit = conflicting_block.signed_header.commit
    ch = conflicting_block.signed_header.header
    out: list[Validator] = []
    if conflicting_header_is_invalid(ch, trusted.header):
        for cs in commit.signatures:
            if not cs.for_block():
                continue
            _, val = common_vals.get_by_address(cs.validator_address)
            if val is not None:
                out.append(val.copy())
    elif trusted.commit.round == commit.round:
        trusted_sigs = trusted.commit.signatures
        for i, sig_a in enumerate(commit.signatures):
            if sig_a.is_absent() or i >= len(trusted_sigs):
                continue
            if trusted_sigs[i].is_absent():
                continue
            _, val = conflicting_block.validator_set.get_by_address(
                sig_a.validator_address)
            if val is not None:
                out.append(val.copy())
    else:
        return []
    out.sort(key=lambda v: (-v.voting_power, v.address))
    return out


@dataclass
class LightClientAttackEvidence(Evidence):
    """Proof that validators signed a conflicting light block
    (reference: types/evidence.go:215). Field semantics:

    - conflicting_block: the forged/conflicting block (with the valset
      whose hash its header claims).
    - common_height: the latest height the attacked client and this
      chain agree on; the valset at this height anchors verification.
    - byzantine_validators: computed via compute_byzantine_validators;
      re-derived and cross-checked by every verifier.
    - total_voting_power / timestamp: of/at the common height, pinned
      so ABCI punishment data is deterministic.
    """

    conflicting_block: LightBlock
    common_height: int
    byzantine_validators: list[Validator] = field(default_factory=list)
    total_voting_power: int = 0
    timestamp: int = 0

    def height(self) -> int:
        return self.common_height

    def conflicting_height(self) -> int:
        return self.conflicting_block.height()

    def hash(self) -> bytes:
        return tmhash.sum256(self.to_bytes())

    def validate_basic(self) -> None:
        if self.conflicting_block is None:
            raise ValueError("missing conflicting block")
        if self.common_height <= 0:
            raise ValueError("non-positive common height")
        sh = self.conflicting_block.signed_header
        if sh.header is None or sh.commit is None:
            raise ValueError("conflicting block missing header or commit")
        if self.common_height > sh.header.height:
            raise ValueError(
                f"common height {self.common_height} is after the "
                f"conflicting block height {sh.header.height}")
        sh.header.validate_basic()
        sh.commit.validate_basic()

    def to_abci(self) -> list:
        from ..abci.types import Misbehavior

        return [
            Misbehavior(
                type="LIGHT_CLIENT_ATTACK",
                validator_address=v.address,
                validator_power=v.voting_power,
                height=self.common_height,
                time=self.timestamp,
                total_voting_power=self.total_voting_power,
            )
            for v in self.byzantine_validators
        ]

    def to_proto(self) -> Writer:
        w = Writer()
        w.message(1, self.conflicting_block.to_proto())
        w.varint(2, self.common_height)
        for v in self.byzantine_validators:
            w.bytes(3, v.to_proto().finish(), skip_empty=False)
        w.varint(4, self.total_voting_power)
        w.varint(5, self.timestamp)
        return w

    def to_bytes(self) -> bytes:
        # Field 2 of the Evidence oneof (see types/evidence.py
        # evidence_from_bytes; field 1 is DuplicateVoteEvidence).
        return Writer().message(2, self.to_proto()).finish()

    @classmethod
    def _from_inner(cls, data: bytes) -> "LightClientAttackEvidence":
        r = Reader(data)
        cb = None
        common = tvp = ts = 0
        byz: list[Validator] = []
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                cb = LightBlock.from_bytes(r.bytes())
            elif f == 2:
                common = r.varint()
            elif f == 3:
                byz.append(Validator.from_bytes(r.bytes()))
            elif f == 4:
                tvp = r.varint()
            elif f == 5:
                ts = r.varint()
            else:
                r.skip(wt)
        if cb is None:
            raise ValueError("light-client-attack evidence missing block")
        return cls(cb, common, byz, tvp, ts)
