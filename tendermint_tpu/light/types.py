"""Light-client data types (reference: types/light.go).

A LightBlock is the minimum a light client needs per height: the
signed header (header + commit) and the validator set that signed it."""

from __future__ import annotations

from dataclasses import dataclass

from ..types.block import Commit, Header
from ..types.validator_set import ValidatorSet


@dataclass
class SignedHeader:
    header: Header
    commit: Commit

    def validate_basic(self, chain_id: str) -> None:
        if self.header is None or self.commit is None:
            raise ValueError("signed header missing header or commit")
        self.header.validate_basic()
        self.commit.validate_basic()
        if self.header.chain_id != chain_id:
            raise ValueError(
                f"header chain id {self.header.chain_id!r} != {chain_id!r}")
        if self.commit.height != self.header.height:
            raise ValueError("commit height != header height")
        if self.commit.block_id.hash != self.header.hash():
            raise ValueError("commit is for a different block")


@dataclass
class LightBlock:
    signed_header: SignedHeader
    validator_set: ValidatorSet

    def height(self) -> int:
        return self.signed_header.header.height

    def time(self) -> int:
        return self.signed_header.header.time

    def hash(self) -> bytes:
        return self.signed_header.header.hash()

    def validate_basic(self, chain_id: str) -> None:
        self.signed_header.validate_basic(chain_id)
        self.validator_set.validate_basic()
        if self.signed_header.header.validators_hash != \
                self.validator_set.hash():
            raise ValueError(
                "validator set does not match header validators_hash")
