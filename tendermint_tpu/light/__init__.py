"""Light client: verify chain headers without executing blocks
(reference: light/).

A light client tracks a chain by verifying SignedHeaders against
validator sets it already trusts — adjacent headers by valset-hash
continuity, distant headers by the +1/3-trust overlap rule with
bisection (reference light/client.go:114, verifier.go:33,102).
All commit verification rides the batched BatchVerifier surfaces on
ValidatorSet, so a bisection over thousands of heights is a handful
of device batches instead of thousands of sequential CPU verifies."""

from .client import Client, TrustOptions
from .errors import (
    DivergenceError,
    LightClientError,
    NewValSetCantBeTrustedError,
    VerificationFailedError,
)
from .provider import BlockStoreProvider, Provider
from .serving import (
    LightServingShedError,
    LightVerifyCollector,
    ServingPlane,
    ServingPool,
    VerifiedHeaderCache,
)
from .store import LightStore
from .types import LightBlock, SignedHeader
from .verifier import (
    DEFAULT_TRUST_LEVEL,
    verify_adjacent,
    verify_non_adjacent,
)

__all__ = [
    "Client", "TrustOptions", "LightBlock", "SignedHeader",
    "LightStore", "Provider", "BlockStoreProvider",
    "ServingPlane", "ServingPool", "VerifiedHeaderCache",
    "LightVerifyCollector", "LightServingShedError",
    "verify_adjacent", "verify_non_adjacent", "DEFAULT_TRUST_LEVEL",
    "LightClientError", "VerificationFailedError",
    "NewValSetCantBeTrustedError", "DivergenceError",
]
