"""Light block providers (reference: light/provider/provider.go).

A provider serves LightBlocks for heights (0 = latest). The HTTP/RPC
provider arrives with the RPC layer; BlockStoreProvider serves from a
node's local stores (used by tests, the light proxy, and statesync's
state provider when a local full node is available)."""

from __future__ import annotations

from .errors import LightClientError
from .types import LightBlock, SignedHeader


class ProviderError(LightClientError):
    pass


class BlockNotFoundError(ProviderError):
    pass


class Provider:
    async def light_block(self, height: int) -> LightBlock:
        """height 0 → latest. Raises BlockNotFoundError."""
        raise NotImplementedError

    async def report_evidence(self, ev) -> None:
        """Submit LightClientAttackEvidence to the node behind this
        provider (reference: light/provider ReportEvidence). Default:
        nowhere to send it."""

    def provider_id(self) -> str:
        return repr(self)


class RPCProvider(Provider):
    """Fetches light blocks from a node's JSON-RPC `commit` +
    `validators` routes (reference: light/provider/http)."""

    def __init__(self, host: str, port: int, name: str = ""):
        from ..rpc.jsonrpc import HTTPClient

        self.client = HTTPClient(host, port)
        self.name = name or f"{host}:{port}"

    def provider_id(self) -> str:
        return self.name

    async def light_block(self, height: int) -> LightBlock:
        from ..rpc.core import (
            commit_from_json, header_from_json, validator_set_from_json,
        )
        from ..rpc.jsonrpc import RPCError

        try:
            params = {} if height == 0 else {"height": height}
            cm = await self.client.call("commit", **params)
            header = header_from_json(cm["signed_header"]["header"])
            commit = commit_from_json(cm["signed_header"]["commit"])
            vals_pages = []
            page = 1
            # Bound pagination against a malicious provider: an
            # inflated `total` with empty pages must not spin forever
            # (reference http provider caps pages); a truncated set is
            # caught downstream by the valset-hash check.
            max_pages = 1 + (10_000 // 100)  # MaxVotesCount / per_page
            while page <= max_pages:
                v = await self.client.call("validators",
                                           height=header.height,
                                           page=page, per_page=100)
                if not v["validators"]:
                    break  # provider returned an empty page: stop
                vals_pages.extend(v["validators"])
                if len(vals_pages) >= int(v["total"]):
                    break
                page += 1
            vals = validator_set_from_json(vals_pages)
        except RPCError as e:
            # Only height-not-there responses are "not found" (the
            # normal not-committed-yet signal, which must NOT trigger
            # primary failover); any other JSON-RPC error — internal
            # errors, broken handlers — is a provider failure.
            msg = str(e)
            if "not available" in msg or "not found" in msg:
                raise BlockNotFoundError(msg) from e
            raise ProviderError(msg) from e
        except (ValueError, KeyError, TypeError) as e:
            # malformed/truncated responses (HTML 502 pages, bad JSON,
            # missing fields) are transport-class provider failures
            raise ProviderError(f"malformed response: {e}") from e
        return LightBlock(SignedHeader(header, commit), vals)

    async def report_evidence(self, ev) -> None:
        import base64

        from ..rpc.jsonrpc import RPCError

        try:
            await self.client.call(
                "broadcast_evidence",
                evidence=base64.b64encode(ev.to_bytes()).decode())
        except RPCError as e:
            raise ProviderError(str(e)) from e


class BlockStoreProvider(Provider):
    """Serves from a full node's block store + state store
    (reference: the local rpc core behaviour light clients hit)."""

    def __init__(self, block_store, state_store, name: str = "local",
                 evidence_pool=None):
        self.block_store = block_store
        self.state_store = state_store
        self.name = name
        self.evidence_pool = evidence_pool

    async def report_evidence(self, ev) -> None:
        if self.evidence_pool is not None:
            self.evidence_pool.add_evidence(ev)

    def provider_id(self) -> str:
        return self.name

    async def light_block(self, height: int) -> LightBlock:
        if height == 0:
            height = self.block_store.height
        meta = self.block_store.load_block_meta(height)
        commit = self.block_store.load_block_commit(height)
        if commit is None:
            # head height: only the seen-commit exists so far
            commit = self.block_store.load_seen_commit(height)
        vals = self.state_store.load_validators(height)
        if meta is None or commit is None or vals is None:
            raise BlockNotFoundError(f"no light block at height {height}")
        return LightBlock(SignedHeader(meta.header, commit), vals)
