"""Light block providers (reference: light/provider/provider.go).

A provider serves LightBlocks for heights (0 = latest). The HTTP/RPC
provider arrives with the RPC layer; BlockStoreProvider serves from a
node's local stores (used by tests, the light proxy, and statesync's
state provider when a local full node is available)."""

from __future__ import annotations

from .errors import LightClientError
from .types import LightBlock, SignedHeader


class ProviderError(LightClientError):
    pass


class BlockNotFoundError(ProviderError):
    pass


class Provider:
    async def light_block(self, height: int) -> LightBlock:
        """height 0 → latest. Raises BlockNotFoundError."""
        raise NotImplementedError

    def provider_id(self) -> str:
        return repr(self)


class BlockStoreProvider(Provider):
    """Serves from a full node's block store + state store
    (reference: the local rpc core behaviour light clients hit)."""

    def __init__(self, block_store, state_store, name: str = "local"):
        self.block_store = block_store
        self.state_store = state_store
        self.name = name

    def provider_id(self) -> str:
        return self.name

    async def light_block(self, height: int) -> LightBlock:
        if height == 0:
            height = self.block_store.height
        meta = self.block_store.load_block_meta(height)
        commit = self.block_store.load_block_commit(height)
        if commit is None:
            # head height: only the seen-commit exists so far
            commit = self.block_store.load_seen_commit(height)
        vals = self.state_store.load_validators(height)
        if meta is None or commit is None or vals is None:
            raise BlockNotFoundError(f"no light block at height {height}")
        return LightBlock(SignedHeader(meta.header, commit), vals)
