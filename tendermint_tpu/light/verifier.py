"""Header verification rules (reference: light/verifier.go).

verify_adjacent  (:102): heights differ by 1 — the trusted header's
next_validators_hash must equal the new header's validators_hash, then
the new valset's commit is checked (+2/3, batched).

verify_non_adjacent (:33): any height gap — the TRUSTED valset must
have signed the new commit with ≥ trust-level (default 1/3) of its
power (batched, address-matched), then the new valset's own commit is
checked (+2/3, batched). Raises NewValSetCantBeTrustedError when the
overlap is insufficient, which drives the client's bisection."""

from __future__ import annotations

from fractions import Fraction

from ..types.validator_set import VerificationError
from .errors import (
    NewValSetCantBeTrustedError,
    OutsideTrustingPeriodError,
    VerificationFailedError,
)
from .types import LightBlock

DEFAULT_TRUST_LEVEL = Fraction(1, 3)
MAX_CLOCK_DRIFT_NS = 10 * 1_000_000_000  # reference defaultMaxClockDrift


def _common_checks(chain_id: str, trusted: LightBlock,
                   untrusted: LightBlock, trusting_period_ns: int,
                   now_ns: int,
                   max_clock_drift_ns: int = MAX_CLOCK_DRIFT_NS) -> None:
    untrusted.validate_basic(chain_id)
    if untrusted.height() <= trusted.height():
        raise VerificationFailedError(
            f"target height {untrusted.height()} not above trusted "
            f"{trusted.height()}")
    # the trusted header must still be inside its trusting period,
    # else its valset may have long unbonded (reference HeaderExpired)
    if trusted.time() + trusting_period_ns <= now_ns:
        raise OutsideTrustingPeriodError(
            f"trusted header from {trusted.time()} expired")
    if untrusted.time() <= trusted.time():
        raise VerificationFailedError(
            "untrusted header time not after trusted header time")
    if untrusted.time() >= now_ns + max_clock_drift_ns:
        raise VerificationFailedError(
            "untrusted header is from the future (clock drift exceeded)")


def verify_adjacent(chain_id: str, trusted: LightBlock,
                    untrusted: LightBlock, trusting_period_ns: int,
                    now_ns: int,
                    max_clock_drift_ns: int = MAX_CLOCK_DRIFT_NS) -> None:
    if untrusted.height() != trusted.height() + 1:
        raise VerificationFailedError("headers must be adjacent")
    _common_checks(chain_id, trusted, untrusted, trusting_period_ns,
                   now_ns, max_clock_drift_ns)
    if untrusted.signed_header.header.validators_hash != \
            trusted.signed_header.header.next_validators_hash:
        raise VerificationFailedError(
            "new validators_hash != trusted next_validators_hash")
    sh = untrusted.signed_header
    try:
        untrusted.validator_set.verify_commit_light(
            chain_id, sh.commit.block_id, sh.header.height, sh.commit)
    except VerificationError as e:
        raise VerificationFailedError(f"invalid commit: {e}") from e


def verify_non_adjacent(chain_id: str, trusted: LightBlock,
                        untrusted: LightBlock, trusting_period_ns: int,
                        now_ns: int,
                        trust_level: Fraction = DEFAULT_TRUST_LEVEL,
                        max_clock_drift_ns: int = MAX_CLOCK_DRIFT_NS) -> None:
    if untrusted.height() == trusted.height() + 1:
        return verify_adjacent(chain_id, trusted, untrusted,
                               trusting_period_ns, now_ns,
                               max_clock_drift_ns)
    _common_checks(chain_id, trusted, untrusted, trusting_period_ns,
                   now_ns, max_clock_drift_ns)
    sh = untrusted.signed_header
    # ≥ trust-level of the TRUSTED valset must have signed the new block
    try:
        trusted.validator_set.verify_commit_light_trusting(
            chain_id, sh.commit,
            trust_level.numerator, trust_level.denominator)
    except VerificationError as e:
        raise NewValSetCantBeTrustedError(str(e)) from e
    # and the new valset itself must have +2/3 committed it
    try:
        untrusted.validator_set.verify_commit_light(
            chain_id, sh.commit.block_id, sh.header.height, sh.commit)
    except VerificationError as e:
        raise VerificationFailedError(f"invalid commit: {e}") from e


def verify_backwards(untrusted_header, trusted_header) -> None:
    """Hash-chain verification of an OLDER header against a newer
    trusted one (reference: light/verifier.go:196 VerifyBackwards):
    the trusted header's last_block_id must be the hash of the older
    header — no signatures needed, the chain linkage is the proof."""
    untrusted_header.validate_basic()
    if untrusted_header.chain_id != trusted_header.chain_id:
        raise VerificationFailedError(
            f"older header from a different chain "
            f"({untrusted_header.chain_id!r} != "
            f"{trusted_header.chain_id!r})")
    if untrusted_header.time >= trusted_header.time:
        raise VerificationFailedError(
            "older header time not before trusted header time")
    if trusted_header.last_block_id is None or \
            untrusted_header.hash() != trusted_header.last_block_id.hash:
        raise VerificationFailedError(
            "older header hash does not match trusted header's "
            "last_block_id")


def verify(chain_id: str, trusted: LightBlock, untrusted: LightBlock,
           trusting_period_ns: int, now_ns: int,
           trust_level: Fraction = DEFAULT_TRUST_LEVEL,
           max_clock_drift_ns: int = MAX_CLOCK_DRIFT_NS) -> None:
    """reference: light/verifier.go:150 Verify — dispatch on adjacency."""
    if untrusted.height() == trusted.height() + 1:
        verify_adjacent(chain_id, trusted, untrusted, trusting_period_ns,
                        now_ns, max_clock_drift_ns)
    else:
        verify_non_adjacent(chain_id, trusted, untrusted,
                            trusting_period_ns, now_ns, trust_level,
                            max_clock_drift_ns)
