"""Light client core (reference: light/client.go:114).

Tracks one primary provider and N witnesses. Headers from the primary
are verified sequentially (adjacent, height by height) or by skipping
with bisection (reference verifySkipping :683): try the target
directly against the latest trusted block; when the trusted valset's
overlap is below the trust level, pivot to the midpoint and recurse.
Each verified header is cross-checked against every witness
(reference detector.go:28); a conflicting witness raises
DivergenceError carrying both blocks so the caller can submit
LightClientAttackEvidence."""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from fractions import Fraction

from .errors import (
    DivergenceError,
    LightClientError,
    NewValSetCantBeTrustedError,
)
from .provider import Provider
from .store import LightStore
from .types import LightBlock
from .verifier import DEFAULT_TRUST_LEVEL, verify, verify_adjacent

logger = logging.getLogger("light")


@dataclass
class TrustOptions:
    """Social-consensus root of trust (reference: light/base.go
    TrustOptions): a height+hash the operator got out of band."""

    period_ns: int
    height: int
    hash: bytes

    def validate(self) -> None:
        if self.period_ns <= 0:
            raise ValueError("trusting period must be positive")
        if self.height < 1:
            raise ValueError("trusted height must be >= 1")
        if len(self.hash) != 32:
            raise ValueError("trusted hash must be 32 bytes")


class Client:
    def __init__(self, chain_id: str, trust_options: TrustOptions,
                 primary: Provider, witnesses: list[Provider],
                 store: LightStore,
                 trust_level: Fraction = DEFAULT_TRUST_LEVEL,
                 now_fn=time.time_ns):
        trust_options.validate()
        self.chain_id = chain_id
        self.trust_options = trust_options
        self.primary = primary
        self.witnesses = list(witnesses)
        self.store = store
        self.trust_level = trust_level
        self.now_fn = now_fn
        self._initialized = False

    # -- bootstrap --

    async def initialize(self) -> LightBlock:
        """Fetch + pin the trusted block (reference client.go
        initializeWithTrustOptions)."""
        existing = self.store.get(self.trust_options.height)
        if existing is not None:
            self._initialized = True
            return existing
        lb = await self.primary.light_block(self.trust_options.height)
        lb.validate_basic(self.chain_id)
        if lb.hash() != self.trust_options.hash:
            raise LightClientError(
                f"trusted header hash mismatch at height "
                f"{self.trust_options.height}: got {lb.hash().hex()}, "
                f"want {self.trust_options.hash.hex()}")
        # +2/3 of ITS OWN valset must have signed it (self-consistency)
        lb.validator_set.verify_commit_light(
            self.chain_id, lb.signed_header.commit.block_id,
            lb.height(), lb.signed_header.commit)
        self.store.save(lb)
        self._initialized = True
        return lb

    # -- public verification API --

    async def verify_light_block_at_height(self, height: int,
                                           now_ns: int | None = None
                                           ) -> LightBlock:
        """reference client.go:445 VerifyLightBlockAtHeight."""
        if not self._initialized:
            await self.initialize()
        now_ns = self.now_fn() if now_ns is None else now_ns
        cached = self.store.get(height)
        if cached is not None:
            return cached
        latest_trusted = self.store.latest()
        assert latest_trusted is not None
        if height <= latest_trusted.height():
            raise LightClientError(
                f"height {height} below latest trusted "
                f"{latest_trusted.height()}; backwards verification "
                "unsupported for now")
        target = await self.primary.light_block(height)
        await self._verify_skipping(latest_trusted, target, now_ns)
        await self._detect_divergence(target, now_ns)
        return target

    async def update(self, now_ns: int | None = None) -> LightBlock | None:
        """Verify the primary's latest header
        (reference client.go Update)."""
        if not self._initialized:
            await self.initialize()
        now_ns = self.now_fn() if now_ns is None else now_ns
        latest = await self.primary.light_block(0)
        trusted = self.store.latest()
        if trusted is not None and latest.height() <= trusted.height():
            return None
        await self._verify_skipping(self.store.latest(), latest, now_ns)
        await self._detect_divergence(latest, now_ns)
        return latest

    def trusted_light_block(self, height: int = 0) -> LightBlock | None:
        return self.store.latest() if height == 0 else \
            self.store.get(height)

    # -- skipping verification with bisection --

    async def _verify_skipping(self, trusted: LightBlock,
                               target: LightBlock, now_ns: int) -> None:
        """reference client.go:683 verifySkipping. Iterative pivoting:
        keep a stack of unverified blocks; verify what we can against
        the current trusted head, bisect when trust is insufficient."""
        pending: list[LightBlock] = [target]
        cache: dict[int, LightBlock] = {target.height(): target}
        steps = 0
        while pending:
            steps += 1
            if steps > 200:  # 2^200 heights — unreachable honestly
                raise LightClientError("bisection did not converge")
            block = pending[-1]
            try:
                verify(self.chain_id, trusted, block,
                       self.trust_options.period_ns, now_ns,
                       self.trust_level)
            except NewValSetCantBeTrustedError:
                pivot_h = (trusted.height() + block.height()) // 2
                if pivot_h in (trusted.height(), block.height()) or \
                        pivot_h in cache:
                    raise  # can't split further: genuine failure
                pivot = await self.primary.light_block(pivot_h)
                cache[pivot_h] = pivot
                pending.append(pivot)
                continue
            self.store.save(block)
            trusted = block
            pending.pop()

    # -- witness cross-checking --

    async def _detect_divergence(self, verified: LightBlock,
                                 now_ns: int) -> None:
        """reference light/detector.go:28 detectDivergence."""
        if not self.witnesses:
            return
        results = await asyncio.gather(
            *(self._compare_with_witness(i, w, verified)
              for i, w in enumerate(self.witnesses)),
            return_exceptions=True)
        for i, res in enumerate(results):
            if isinstance(res, DivergenceError):
                raise res
            if isinstance(res, BaseException):
                logger.warning("witness %d unreachable: %r", i, res)

    async def _compare_with_witness(self, idx: int, witness: Provider,
                                    verified: LightBlock) -> None:
        wb = await witness.light_block(verified.height())
        if wb.hash() != verified.hash():
            raise DivergenceError(idx, wb, verified)
