"""Light client core (reference: light/client.go:114).

Tracks one primary provider and N witnesses. Headers from the primary
are verified sequentially (adjacent, height by height) or by skipping
with bisection (reference verifySkipping :683): try the target
directly against the latest trusted block; when the trusted valset's
overlap is below the trust level, pivot to the midpoint and recurse.
Each verified header is cross-checked against every witness
(reference detector.go:28); a conflicting witness raises
DivergenceError carrying both blocks so the caller can submit
LightClientAttackEvidence."""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from fractions import Fraction

from .errors import (
    DivergenceError,
    LightClientError,
    NewValSetCantBeTrustedError,
)
from .provider import BlockNotFoundError, Provider, ProviderError
from .store import LightStore
from .types import LightBlock
from .verifier import DEFAULT_TRUST_LEVEL, verify, verify_adjacent

logger = logging.getLogger("light")


@dataclass
class TrustOptions:
    """Social-consensus root of trust (reference: light/base.go
    TrustOptions): a height+hash the operator got out of band."""

    period_ns: int
    height: int
    hash: bytes

    def validate(self) -> None:
        if self.period_ns <= 0:
            raise ValueError("trusting period must be positive")
        if self.height < 1:
            raise ValueError("trusted height must be >= 1")
        if len(self.hash) != 32:
            raise ValueError("trusted hash must be 32 bytes")


class Client:
    def __init__(self, chain_id: str, trust_options: TrustOptions,
                 primary: Provider, witnesses: list[Provider],
                 store: LightStore,
                 trust_level: Fraction = DEFAULT_TRUST_LEVEL,
                 now_fn=time.time_ns):
        trust_options.validate()
        self.chain_id = chain_id
        self.trust_options = trust_options
        self.primary = primary
        self.witnesses = list(witnesses)
        self.store = store
        self.trust_level = trust_level
        self.now_fn = now_fn
        self._initialized = False
        # In-memory linkage-verified headers from backwards walks
        # (NOT the trusted store — their commits are unverified).
        # Bounds request amplification: without it, every old-height
        # query re-walks the hash chain from the trusted head — one
        # cheap blockchain(1..20) RPC against a deep chain meant
        # ~depth x 20 sequential primary fetches.
        self._interim_cache: dict[int, LightBlock] = {}
        self._interim_cache_max = 4096

    # -- bootstrap --

    async def initialize(self) -> LightBlock:
        """Fetch + pin the trusted block (reference client.go
        initializeWithTrustOptions)."""
        existing = self.store.get(self.trust_options.height)
        if existing is not None:
            self._initialized = True
            return existing
        lb = await self._from_primary(self.trust_options.height)
        lb.validate_basic(self.chain_id)
        if lb.hash() != self.trust_options.hash:
            raise LightClientError(
                f"trusted header hash mismatch at height "
                f"{self.trust_options.height}: got {lb.hash().hex()}, "
                f"want {self.trust_options.hash.hex()}")
        # +2/3 of ITS OWN valset must have signed it (self-consistency)
        lb.validator_set.verify_commit_light(
            self.chain_id, lb.signed_header.commit.block_id,
            lb.height(), lb.signed_header.commit)
        self.store.save(lb)
        self._initialized = True
        return lb

    # -- public verification API --

    async def verify_light_block_at_height(self, height: int,
                                           now_ns: int | None = None
                                           ) -> LightBlock:
        """reference client.go:445 VerifyLightBlockAtHeight."""
        if not self._initialized:
            await self.initialize()
        now_ns = self.now_fn() if now_ns is None else now_ns
        cached = self.store.get(height)
        if cached is not None:
            return cached
        latest_trusted = self.store.latest()
        assert latest_trusted is not None
        if height < latest_trusted.height():
            return await self._verify_backwards(height, now_ns)
        target = await self._from_primary(height)
        await self._verify_skipping(latest_trusted, target, now_ns)
        await self._detect_divergence(target, now_ns)
        return target

    async def _from_primary(self, height: int) -> LightBlock:
        """Fetch from the primary; on a TRANSPORT failure promote the
        first witness to primary and retry (reference client.go:975
        lightBlockFromPrimary + replacePrimaryProvider) — a dead or
        unreachable primary must not strand the client while healthy
        witnesses exist. BlockNotFoundError propagates unchanged: a
        height that simply doesn't exist yet (the proxy's h+1 retry
        window) is not grounds to burn a witness."""
        tries = 0
        while True:
            try:
                return await self.primary.light_block(height)
            except BlockNotFoundError:
                raise
            except (ProviderError, OSError) as e:
                tries += 1
                if not self.witnesses or tries > len(self.witnesses) + 1:
                    raise
                # ROTATE, don't consume: the failed primary goes to
                # the END of the witness list instead of being
                # discarded — transient blips must not permanently
                # shrink the witness set until fork detection is
                # silently disabled (the divergence check already
                # tolerates unreachable witnesses). The tries bound
                # stops an all-dead provider set from cycling forever.
                old, self.primary = self.primary, self.witnesses.pop(0)
                self.witnesses.append(old)
                logger.warning(
                    "primary %r failed (%s); promoting witness %r "
                    "(failed primary demoted to witness)",
                    old, e, self.primary)

    async def _verify_backwards(self, height: int,
                                now_ns: int) -> LightBlock:
        """Hash-chain walk DOWN from the nearest trusted block above
        `height` (reference client.go:905 backwards + verifier.go:196):
        each interim header must be the one the (already verified)
        header above links to via last_block_id. No signature checks —
        the linkage is the proof; the anchor must still be inside its
        trusting period."""
        from .verifier import verify_backwards

        # Anchor on the nearest TRUSTED block — the trusting-period
        # check applies to it, never to a cached interim (an interim's
        # older timestamp could fail the check while a perfectly valid
        # trusted anchor exists above). The walk loop below consults
        # the linkage cache per step, so a cached chain still costs
        # zero fetches.
        anchor_h = min(h for h in self.store.heights() if h > height)
        cur = self.store.get(anchor_h)
        if cur.time() + self.trust_options.period_ns <= now_ns:
            raise LightClientError(
                f"anchor header {anchor_h} outside trusting period")
        while cur.height() > height:
            cached = self._interim_cache.get(cur.height() - 1)
            if cached is not None and cached.hash() == \
                    cur.signed_header.header.last_block_id.hash:
                cur = cached
                continue
            interim = await self._from_primary(cur.height() - 1)
            try:
                interim.validate_basic(self.chain_id)
                verify_backwards(interim.signed_header.header,
                                 cur.signed_header.header)
            except (LightClientError, ValueError) as e:
                raise LightClientError(
                    f"backwards verification failed at height "
                    f"{cur.height() - 1}: {e}") from e
            # Interim blocks are NOT persisted to the TRUSTED store
            # (reference client.go: "Intermediate headers are not
            # saved to database"): the hash-chain walk proves linkage
            # only — the interim commits' signatures were never
            # verified, and a stored block would later read as fully
            # trusted. They do go into the bounded in-memory linkage
            # cache so repeated old-height walks don't re-fetch the
            # whole chain. Only the requested target is saved, below.
            if len(self._interim_cache) >= self._interim_cache_max:
                # evict oldest-inserted so cold ranges still cache
                self._interim_cache.pop(next(iter(self._interim_cache)))
            self._interim_cache[interim.height()] = interim
            cur = interim
        self.store.save(cur)
        return cur

    async def update(self, now_ns: int | None = None) -> LightBlock | None:
        """Verify the primary's latest header
        (reference client.go Update)."""
        if not self._initialized:
            await self.initialize()
        now_ns = self.now_fn() if now_ns is None else now_ns
        latest = await self._from_primary(0)
        trusted = self.store.latest()
        if trusted is not None and latest.height() <= trusted.height():
            return None
        await self._verify_skipping(self.store.latest(), latest, now_ns)
        await self._detect_divergence(latest, now_ns)
        return latest

    def trusted_light_block(self, height: int = 0) -> LightBlock | None:
        return self.store.latest() if height == 0 else \
            self.store.get(height)

    # -- skipping verification with bisection --

    async def _verify_skipping(self, trusted: LightBlock,
                               target: LightBlock, now_ns: int,
                               provider: Provider | None = None,
                               persist: bool = True) -> None:
        """reference client.go:683 verifySkipping. Iterative pivoting:
        keep a stack of unverified blocks; verify what we can against
        the current trusted head, bisect when trust is insufficient.

        `provider` supplies pivot blocks (default: the primary WITH
        failover — a primary dying mid-bisection must not strand the
        client, reference verifySkipping routes pivots through
        lightBlockFromPrimary); an EXPLICIT provider (divergence
        examination of a specific witness) is used as-is and must not
        trigger failover. `persist=False` verifies without touching
        the trusted store — used to examine a witness's conflicting
        header, which must never pollute the store."""
        fetch = provider.light_block if provider is not None \
            else self._from_primary
        pending: list[LightBlock] = [target]
        cache: dict[int, LightBlock] = {target.height(): target}
        steps = 0
        while pending:
            steps += 1
            if steps > 200:  # 2^200 heights — unreachable honestly
                raise LightClientError("bisection did not converge")
            block = pending[-1]
            try:
                verify(self.chain_id, trusted, block,
                       self.trust_options.period_ns, now_ns,
                       self.trust_level)
            except NewValSetCantBeTrustedError:
                pivot_h = (trusted.height() + block.height()) // 2
                if pivot_h in (trusted.height(), block.height()) or \
                        pivot_h in cache:
                    raise  # can't split further: genuine failure
                pivot = await fetch(pivot_h)
                cache[pivot_h] = pivot
                pending.append(pivot)
                continue
            if persist:
                self.store.save(block)
            trusted = block
            pending.pop()

    # -- witness cross-checking --

    async def _detect_divergence(self, verified: LightBlock,
                                 now_ns: int) -> None:
        """reference light/detector.go:28 detectDivergence.

        A witness that merely DISAGREES is not yet an attack: it must
        PROVE its conflicting header from a block we both trust
        (reference detector.go:120 examineConflictingHeaderAgainstTrace).
        Witnesses that cannot prove their header are dropped and the
        loop continues (one bad witness must not DoS the client); a
        witness that proves a conflict means a real fork — evidence is
        built against both sides, submitted to the opposing providers,
        and DivergenceError (carrying the evidence) is raised."""
        if not self.witnesses:
            return
        results = await asyncio.gather(
            *(self._compare_with_witness(i, w, verified)
              for i, w in enumerate(self.witnesses)),
            return_exceptions=True)
        faulty: list = []
        try:
            for i, res in enumerate(results):
                if isinstance(res, DivergenceError):
                    outcome = await self._examine_divergence(res, now_ns)
                    if outcome == "proven":
                        raise res
                    if outcome == "unreachable":
                        # A transient transport blip is NOT proof the
                        # witness forged its header — keep it and let a
                        # later cross-check retry (dropping it here
                        # would suppress genuine attack evidence).
                        logger.warning(
                            "witness %d diverged but became unreachable"
                            " during examination; keeping it", i)
                        continue
                    logger.warning(
                        "witness %d could not prove its conflicting "
                        "header; removing it", i)
                    faulty.append(self.witnesses[i])
                elif isinstance(res, BaseException):
                    logger.warning("witness %d unreachable: %r", i, res)
        finally:
            if faulty:
                self.witnesses = [w for w in self.witnesses
                                  if w not in faulty]

    async def _compare_with_witness(self, idx: int, witness: Provider,
                                    verified: LightBlock) -> None:
        wb = await witness.light_block(verified.height())
        if wb.hash() != verified.hash():
            raise DivergenceError(idx, wb, verified)

    async def _examine_divergence(self, div: DivergenceError,
                                  now_ns: int) -> str:
        """Try to verify the witness's conflicting block from the last
        height the witness and our (primary-derived) store agree on.
        Returns "proven" — after building + submitting attack
        evidence — when the witness proves a genuine fork;
        "unprovable" when the witness fails to prove its header
        (caller drops it); "unreachable" when transport failures made
        examination impossible (caller keeps the witness — a network
        blip must not be classified as an unprovable forgery)."""
        witness = self.witnesses[div.witness_index]
        target_h = div.primary_block.height()
        common, reachable = await self._find_common_block(witness, target_h)
        if common is None:
            return "unprovable" if reachable else "unreachable"
        try:
            await self._verify_skipping(
                common, div.witness_block, now_ns,
                provider=witness, persist=False)
        except ProviderError:
            return "unreachable"  # pivot fetch failed, not a bad proof
        except (LightClientError, ValueError):
            # ValueError: structural validate_basic failures — the
            # witness's block is not even well-formed.
            return "unprovable"
        except (OSError, asyncio.TimeoutError):
            return "unreachable"
        await self._report_attack(common, div, witness)
        # The fork is PROVEN: every primary-derived block above the
        # common height may be the attacker's — including the target
        # already saved by _verify_skipping. Purge them so later calls
        # cannot silently serve the forged chain from the store cache
        # (reference: the detector returns ErrLightClientAttack and the
        # client stops trusting the primary's trace).
        for h in self.store.heights():
            if h > common.height():
                self.store.delete(h)
        return "proven"

    async def _find_common_block(self, witness: Provider, below: int
                                 ) -> tuple[LightBlock | None, bool]:
        """Latest stored (trusted) block strictly below `below` whose
        hash the witness also reports (reference detector.go walks the
        primary trace backwards the same way). Second element is False
        when EVERY witness fetch failed — total unreachability, which
        the caller must not confuse with "no common block exists"."""
        any_response = False
        for h in sorted(self.store.heights(), reverse=True):
            if h >= below:
                continue
            ours = self.store.get(h)
            if ours is None:
                continue
            try:
                theirs = await witness.light_block(h)
            except Exception:
                # Transient provider failure at ONE height must not
                # make a genuine fork look "unprovable" (which would
                # drop an honest witness and suppress the evidence);
                # keep walking down.
                continue
            any_response = True
            if theirs.hash() == ours.hash():
                return ours, True
        return None, any_response

    async def _report_attack(self, common: LightBlock,
                             div: DivergenceError,
                             witness: Provider) -> None:
        """Build LightClientAttackEvidence for BOTH sides of the fork
        and hand each to the opposing provider (reference
        detector.go:234 handleConflictingHeaders): we cannot know which
        chain is canonical, but each full node can — it verifies the
        evidence against its own chain and discards the half that
        matches it."""
        from .types import (
            LightClientAttackEvidence, compute_byzantine_validators,
        )

        def build(conflicting: LightBlock, trusted: LightBlock):
            return LightClientAttackEvidence(
                conflicting_block=conflicting,
                common_height=common.height(),
                byzantine_validators=compute_byzantine_validators(
                    common.validator_set,
                    trusted.signed_header,
                    conflicting,
                ),
                total_voting_power=common.validator_set.total_voting_power(),
                timestamp=common.time(),
            )

        ev_vs_witness = build(div.witness_block, div.primary_block)
        ev_vs_primary = build(div.primary_block, div.witness_block)
        div.evidence = [ev_vs_witness, ev_vs_primary]
        for provider, ev in ((self.primary, ev_vs_witness),
                             (witness, ev_vs_primary)):
            try:
                await provider.report_evidence(ev)
            except Exception as e:  # best-effort: the fork is already fatal
                logger.warning("could not report evidence to %s: %r",
                               provider.provider_id(), e)
