"""Adapter for the reference's TLA+-generated light-client MBT corpus
(reference: light/mbt/doc.go:1-20, light/mbt/json/*.json,
driver_test.go).

The corpus is the only externally-derived test oracle available to
this repo: its fixtures carry REAL signed headers and validator sets
produced over the reference implementation's canonical sign-bytes and
hashing (generated from the TLA+ light-client spec via tendermint-rs
testgen). Replaying them through this package's verifier therefore
cross-validates, in one sweep:

  * canonical vote sign-bytes (types/canonical.py field layout),
  * header hashing (types/block.py Header.hash: cdcEncode field
    merkle),
  * validator-set hashing (SimpleValidator encoding + ordering),
  * ed25519 signature verification,
  * the verifier's trust/adjacency/expiry/drift verdict logic
    (reference: light/verifier.go Verify),

because a commit only verifies if every byte of the recomputed
sign-bytes and every recomputed hash matches what the reference
signed. Any divergence is a real encoding bug or must be documented.

Fixture schema (reference tmjson encoding): string-encoded int64s,
base64 keys/signatures, hex hashes/addresses, RFC3339 times with
nanoseconds. Driver semantics mirror driver_test.go exactly: the
trusted state carries the *next* validator set of the latest trusted
header (tendermint-rs convention — driver_test.go:104-118), each step
runs one verify at the step's `now` with maxClockDrift=1s, SUCCESS
advances the trusted state, NOT_ENOUGH_TRUST and INVALID leave it.
"""

from __future__ import annotations

import base64
import json
from fractions import Fraction

from ..crypto import ed25519
from ..libs.timeenc import rfc3339_to_ns as _time_ns
from ..types.block import (
    BlockID,
    BlockIDFlag,
    Commit,
    CommitSig,
    Header,
    PartSetHeader,
)
from ..types.validator import Validator
from ..types.validator_set import ValidatorSet
from .errors import LightClientError, NewValSetCantBeTrustedError
from .types import LightBlock, SignedHeader
from .verifier import verify

SUCCESS = "SUCCESS"
NOT_ENOUGH_TRUST = "NOT_ENOUGH_TRUST"
INVALID = "INVALID"

# driver_test.go passes 1 * time.Second
MAX_CLOCK_DRIFT_NS = 1_000_000_000




def _hex(s: str | None) -> bytes:
    return bytes.fromhex(s) if s else b""


def _block_id(d: dict | None) -> BlockID | None:
    if d is None:
        return None
    psh = d.get("part_set_header") or d.get("parts")
    return BlockID(
        _hex(d.get("hash")),
        PartSetHeader(int(psh["total"]), _hex(psh.get("hash")))
        if psh else None,
    )


def _header(d: dict) -> Header:
    ver = d.get("version") or {}
    return Header(
        version_block=int(ver.get("block") or 0),
        version_app=int(ver.get("app") or 0),
        chain_id=d["chain_id"],
        height=int(d["height"]),
        time=_time_ns(d["time"]),
        last_block_id=_block_id(d.get("last_block_id")),
        last_commit_hash=_hex(d.get("last_commit_hash")),
        data_hash=_hex(d.get("data_hash")),
        validators_hash=_hex(d.get("validators_hash")),
        next_validators_hash=_hex(d.get("next_validators_hash")),
        consensus_hash=_hex(d.get("consensus_hash")),
        app_hash=_hex(d.get("app_hash")),
        last_results_hash=_hex(d.get("last_results_hash")),
        evidence_hash=_hex(d.get("evidence_hash")),
        proposer_address=_hex(d.get("proposer_address")),
    )


def _commit(d: dict) -> Commit:
    sigs = []
    for s in d.get("signatures") or []:
        flag = int(s["block_id_flag"])
        if flag == BlockIDFlag.ABSENT:
            sigs.append(CommitSig.absent())
            continue
        sigs.append(CommitSig(
            flag,
            _hex(s.get("validator_address")),
            _time_ns(s["timestamp"]) if s.get("timestamp") else 0,
            base64.b64decode(s["signature"]) if s.get("signature")
            else b"",
        ))
    return Commit(
        height=int(d["height"]),
        round=int(d.get("round") or 0),
        block_id=_block_id(d["block_id"]),
        signatures=sigs,
    )


def _valset(d: dict | None) -> ValidatorSet:
    vals = []
    for v in (d or {}).get("validators") or []:
        pk = v["pub_key"]
        if "ed25519" not in pk["type"].lower():
            raise ValueError(f"unsupported key type {pk['type']!r}")
        pub = ed25519.Ed25519PubKey(base64.b64decode(pk["value"]))
        vals.append(Validator(
            address=_hex(v["address"]),
            pub_key=pub,
            voting_power=int(v["voting_power"]),
            proposer_priority=int(v["proposer_priority"] or 0)
            if v.get("proposer_priority") is not None else 0,
        ))
    return ValidatorSet(vals)


def _signed_header(d: dict) -> SignedHeader:
    return SignedHeader(_header(d["header"]), _commit(d["commit"]))


def classify(chain_id: str, trusted: LightBlock, untrusted: LightBlock,
             trusting_period_ns: int, now_ns: int,
             trust_level: Fraction) -> str:
    try:
        verify(chain_id, trusted, untrusted, trusting_period_ns, now_ns,
               trust_level, max_clock_drift_ns=MAX_CLOCK_DRIFT_NS)
        return SUCCESS
    except NewValSetCantBeTrustedError:
        return NOT_ENOUGH_TRUST
    except (LightClientError, ValueError):
        return INVALID


def run_case(doc: dict) -> list[str]:
    """Replay one reference corpus case; returns the verdict list.
    Raises AssertionError on the first divergence from the fixture's
    expected verdicts."""
    init = doc["initial"]
    trusted_sh = _signed_header(init["signed_header"])
    chain_id = trusted_sh.header.chain_id
    # tendermint-rs convention: the verifier state carries the NEXT
    # valset of the trusted header (driver_test.go trustedNextVals)
    trusted = LightBlock(trusted_sh, _valset(init["next_validator_set"]))
    period = int(init["trusting_period"])
    verdicts = []
    for i, step in enumerate(doc["input"]):
        blk = step["block"]
        untrusted = LightBlock(_signed_header(blk["signed_header"]),
                               _valset(blk.get("validator_set")))
        got = classify(chain_id, trusted, untrusted, period,
                       _time_ns(step["now"]), Fraction(1, 3))
        verdicts.append(got)
        want = step["verdict"]
        assert got == want, (
            f"{doc.get('description', '?')}: step {i} (height "
            f"{untrusted.height()}): got {got}, want {want}")
        if got == SUCCESS:
            trusted = LightBlock(
                untrusted.signed_header,
                _valset(blk.get("next_validator_set")))
    return verdicts


def run_case_file(path: str) -> list[str]:
    with open(path) as f:
        return run_case(json.load(f))
