"""Light proxy: a JSON-RPC server whose block-bearing responses are
LIGHT-VERIFIED before they leave the process (reference:
light/proxy/proxy.go:16, light/proxy/routes.go).

A wallet or indexer points at this proxy exactly as it would at a full
node; the proxy forwards transaction submission and queries to the
primary, but every header/commit/validator-set it returns has passed
the light client's verification (sequential or skipping + witness
cross-check), and every full block fetched from the primary is checked
against the corresponding verified header hash. A lying primary
cannot feed this proxy's clients a forged chain — the request fails
instead.
"""

from __future__ import annotations

import logging

from ..rpc.jsonrpc import JSONRPCServer, RPCError
from .client import Client
from .errors import LightClientError
from .provider import BlockNotFoundError
from .serving import LightServingShedError

logger = logging.getLogger("light.proxy")


class LightProxy:
    """Serves verified RPC routes from a light `Client`.

    forward_client: an ``HTTPClient`` to the primary's RPC, used for
    pass-through routes (tx broadcast, abci queries, full blocks);
    None disables those routes (verified-only mode, e.g. tests over a
    BlockStoreProvider primary).
    """

    def __init__(self, client: Client, forward_client=None,
                 proof_runtime=None, plane=None):
        self.client = client
        self.forward = forward_client
        # Shared verification plane (light/serving.py ServingPlane):
        # when set, every verified route resolves heights through it —
        # request coalescing, the verified-header cache and batched
        # commit verification — instead of walking the client
        # serially. Several proxy workers (ServingPool) share one.
        self.plane = plane
        # app-defined proof formats decode through this registry
        # (reference: lrpc.KeyPathFn/prt options); default knows the
        # kvstore ops, apps with their own formats inject a runtime
        self._prt = proof_runtime
        self.server = JSONRPCServer(self._routes(),
                                    ws_routes=self._ws_routes())
        self.server._on_ws_close = self._on_ws_close
        self.port: int | None = None

    async def listen(self, host: str, port: int) -> int:
        self.port = await self.server.listen(host, port)
        logger.info("light proxy serving verified RPC on %s:%d",
                    host, self.port)
        return self.port

    def close(self) -> None:
        self.server.close()

    def _routes(self) -> dict:
        routes = {
            "status": self.status,
            "commit": self.commit,
            "validators": self.validators,
            "block": self.block,
            "header": self.header,
            "health": self.health,
        }
        if self.forward is not None:
            # verified pass-throughs (reference light/rpc/client.go):
            # the answer is checked against light-verified state
            routes["abci_query"] = self.abci_query
            routes["block_by_hash"] = self.block_by_hash
            routes["block_results"] = self.block_results
            routes["tx"] = self.tx
            routes["blockchain"] = self.blockchain
            routes["consensus_params"] = self.consensus_params
            # plain pass-throughs — the set the reference also relays
            # without light verification (lrpc client delegates these
            # straight to `next`)
            for name in ("broadcast_tx_sync", "broadcast_tx_async",
                         "broadcast_tx_commit", "abci_info",
                         "tx_search", "net_info",
                         "genesis", "genesis_chunked", "block_search",
                         "consensus_state", "dump_consensus_state",
                         "unconfirmed_txs",
                         "num_unconfirmed_txs", "check_tx",
                         "broadcast_evidence"):
                routes[name] = self._forwarder(name)
        return routes

    # -- verified routes --

    async def _verified_block_at(self, height) -> "object":
        from ..rpc.jsonrpc import CODE_BUSY

        h = int(height) if height else 0
        try:
            if self.plane is not None:
                lb = await self.plane.get_verified(h)
            elif h == 0:
                lb = await self.client.update()
                if lb is None:
                    lb = self.client.trusted_light_block()
            else:
                lb = await self.client.verify_light_block_at_height(h)
        except LightServingShedError as e:
            # backpressure, not a verdict: same 429 vocabulary as the
            # RPC overload limiter and the mempool admission sheds
            raise RPCError(CODE_BUSY, str(e), "queue_full")
        except (LightClientError, BlockNotFoundError) as e:
            raise RPCError(-32603, f"light verification failed: {e}")
        if lb is None:
            raise RPCError(-32603, "no trusted block yet")
        return lb

    async def health(self, ctx) -> dict:
        return {}

    async def status(self, ctx) -> dict:
        lb = self.client.trusted_light_block()
        if lb is None:
            raise RPCError(-32603, "light client not initialized")
        h = lb.signed_header.header
        return {
            "node_info": {
                "network": h.chain_id,
                "moniker": "light-proxy",
                "version": "tendermint-tpu/light",
            },
            "sync_info": {
                "latest_block_height": str(h.height),
                "latest_block_hash": lb.hash().hex().upper(),
                "latest_app_hash": h.app_hash.hex().upper(),
                "latest_block_time": str(h.time),
                "catching_up": False,
            },
        }

    async def commit(self, ctx, height=None) -> dict:
        from ..rpc.core import _commit_json, _header_json

        lb = await self._verified_block_at(height)
        return {
            "signed_header": {
                "header": _header_json(lb.signed_header.header),
                "commit": _commit_json(lb.signed_header.commit),
            },
            "canonical": True,
        }

    async def header(self, ctx, height=None) -> dict:
        from ..rpc.core import _header_json

        lb = await self._verified_block_at(height)
        return {"header": _header_json(lb.signed_header.header)}

    async def validators(self, ctx, height=None, page=1,
                         per_page=30) -> dict:
        from ..rpc.core import _validator_json

        lb = await self._verified_block_at(height)
        vals = lb.validator_set
        page, per_page = max(int(page), 1), min(max(int(per_page), 1), 100)
        start = (page - 1) * per_page
        sel = vals.validators[start:start + per_page]
        return {"block_height": str(lb.height()),
                "validators": [_validator_json(v) for v in sel],
                "count": str(len(sel)), "total": str(len(vals))}

    async def block(self, ctx, height=None) -> dict:
        """Full block from the primary, checked hash-for-hash against
        the light-verified header (reference routes.go BlockFn →
        proxy verification)."""
        if self.forward is None:
            raise RPCError(-32601, "block pass-through not configured")
        lb = await self._verified_block_at(height)
        res = await self.forward.call("block", height=lb.height())
        got = bytes.fromhex(res["block_id"]["hash"])
        want = lb.hash()
        if got != want:
            raise RPCError(
                -32603,
                f"primary served block {got.hex()[:16]}… but the "
                f"verified header at height {lb.height()} is "
                f"{want.hex()[:16]}… — refusing to relay a forged block")
        # and the BODY must actually hash to that id (a forged body
        # under a truthful block_id must not pass)
        self._check_block_body(res, want)
        return res

    def _check_block_body(self, res: dict, want: bytes) -> None:
        """The served BODY must hash to `want`: recompute the header
        hash from the response (not the primary's claimed block_id)
        and bind the tx payload to header.data_hash — a primary
        cannot attach a forged body under a real verified hash
        (reference client.go BlockByHash res.Block.ValidateBasic +
        Hash comparison)."""
        import base64

        from ..crypto import merkle
        from ..rpc.core import header_from_json

        hdr = header_from_json(res["block"]["header"])
        if hdr.hash() != want:
            raise RPCError(
                -32603,
                f"served block body hashes to {hdr.hash().hex()[:16]}… "
                f"not the verified {want.hex()[:16]}…")
        txs = [base64.b64decode(t)
               for t in res["block"]["data"].get("txs") or []]
        if merkle.hash_from_byte_slices(txs) != hdr.data_hash:
            raise RPCError(
                -32603, "served txs do not match the header's data_hash")

    async def block_by_hash(self, ctx, hash="") -> dict:
        """reference light/rpc/client.go:314 BlockByHash: the answer
        must be the block WE asked for (requested hash), its body must
        hash to that id, and the id must equal the light-verified
        header at that height."""
        if self.forward is None:
            raise RPCError(-32601, "pass-through not configured")
        from ..rpc.core import coerce_hex_param

        hash = coerce_hex_param(hash)
        want = bytes.fromhex(hash)
        res = await self.forward.call("block_by_hash", hash=hash)
        h = int(res["block"]["header"]["height"])
        self._check_block_body(res, want)
        # the relayed block_id must be the verified id too — clients
        # record it as the canonical hash
        if bytes.fromhex(res["block_id"]["hash"]) != want:
            raise RPCError(
                -32603, "block_id does not match the requested hash")
        lb = await self._verified_block_at(h)
        if want != lb.hash():
            raise RPCError(
                -32603,
                f"block {want.hex()[:16]}… at height {h} does not "
                f"match the verified header {lb.hash().hex()[:16]}…")
        return res

    async def block_results(self, ctx, height=None) -> dict:
        """reference light/rpc/client.go:349 BlockResults: recompute
        the deliver-tx results hash from the response and check it
        against header(h+1).last_results_hash — tampered tx results
        (codes/data) are rejected."""
        import base64
        from types import SimpleNamespace

        if self.forward is None:
            raise RPCError(-32601, "pass-through not configured")
        if height in (None, 0, "0", ""):
            # latest results aren't provable yet (their hash lands in
            # the NEXT header) — serve the previous block's instead,
            # as the reference does (client.go:352-358)
            st = await self.forward.call("status")
            height = int(st["sync_info"]["latest_block_height"]) - 1
        res = await self.forward.call("block_results", height=height)
        h = int(height)
        if h <= 0:
            raise RPCError(-32603, "zero or negative results height")
        if int(res.get("height") or 0) != h:
            # verification is against the REQUESTED height; an answer
            # for some other height must not slip through
            raise RPCError(
                -32603,
                f"primary answered for height {res.get('height')} but "
                f"{h} was requested")
        lb = await self._verified_block_at(h + 1)
        from ..state import abci_results_hash

        rs = [SimpleNamespace(
            code=int(t.get("code", 0)),
            data=base64.b64decode(t.get("data") or ""))
            for t in res.get("txs_results") or []]
        want = lb.signed_header.header.last_results_hash
        if abci_results_hash(rs) != want:
            raise RPCError(
                -32603,
                f"results hash mismatch for height {h} — refusing to "
                "relay tampered block results")
        return res

    async def tx(self, ctx, hash="", prove=True) -> dict:
        """reference light/rpc/client.go:425 Tx: prove is forced on
        and the tx merkle proof is validated against the verified
        header's data_hash."""
        import base64

        if self.forward is None:
            raise RPCError(-32601, "pass-through not configured")
        from ..crypto import tmhash
        from ..rpc.core import coerce_hex_param

        hash = coerce_hex_param(hash)
        res = await self.forward.call("tx", hash=hash, prove=True)
        h = int(res["height"])
        if h <= 0:
            raise RPCError(-32603, "zero or negative tx height")
        proof = res.get("proof")
        if not proof:
            raise RPCError(-32603, "no proof in tx response")
        txb = base64.b64decode(res.get("tx") or "")
        # the proven tx must BE the one we asked for — an honest
        # inclusion proof for a different committed tx must not pass
        if tmhash.sum256(txb) != bytes.fromhex(hash):
            raise RPCError(
                -32603,
                f"primary answered with a tx hashing to "
                f"{tmhash.sum256(txb).hex()[:16]}… but {hash[:16]}… "
                "was queried")
        lb = await self._verified_block_at(h)
        from ..crypto import merkle

        pj = proof["proof"]
        p = merkle.Proof(
            total=int(pj["total"]), index=int(pj["index"]),
            leaf_hash=base64.b64decode(pj["leaf_hash"]),
            aunts=[base64.b64decode(a) for a in pj.get("aunts", [])])
        if not p.verify(lb.signed_header.header.data_hash, txb):
            raise RPCError(
                -32603,
                f"tx proof failed against data_hash of verified "
                f"header {h} — refusing to relay")
        return res

    async def blockchain(self, ctx, min_height=None,
                         max_height=None) -> dict:
        """reference lrpc client BlockchainInfo: every returned
        BlockMeta's header must recompute to its claimed block id and
        match the light-verified header at that height."""
        if self.forward is None:
            raise RPCError(-32601, "pass-through not configured")
        from ..rpc.core import header_from_json

        res = await self.forward.call(
            "blockchain", min_height=min_height, max_height=max_height)
        lo = int(min_height) if min_height not in (None, "", "0", 0) \
            else None
        hi = int(max_height) if max_height not in (None, "", "0", 0) \
            else None
        for meta in res.get("block_metas") or []:
            hdr = header_from_json(meta["header"])
            # answers must stay inside the requested range — a
            # different (individually valid) range must not pass
            if (lo is not None and hdr.height < lo) or \
                    (hi is not None and hdr.height > hi):
                raise RPCError(
                    -32603,
                    f"block meta height {hdr.height} outside the "
                    f"requested range [{min_height}, {max_height}]")
            want = bytes.fromhex(meta["block_id"]["hash"])
            if hdr.hash() != want:
                raise RPCError(
                    -32603,
                    f"block meta at height {hdr.height}: header does "
                    "not hash to its claimed block id")
            lb = await self._verified_block_at(hdr.height)
            if lb.hash() != want:
                raise RPCError(
                    -32603,
                    f"block meta at height {hdr.height} does not match "
                    "the verified header")
        return res

    async def consensus_params(self, ctx, height=None) -> dict:
        """reference lrpc client ConsensusParams: the returned params
        must hash to the verified header's consensus_hash."""
        if self.forward is None:
            raise RPCError(-32601, "pass-through not configured")
        from ..types.params import (BlockParams, ConsensusParams,
                                    EvidenceParams, ValidatorParams,
                                    VersionParams)

        res = await self.forward.call("consensus_params", height=height)
        h = int(res["block_height"])
        if height not in (None, 0, "0", "") and h != int(height):
            raise RPCError(
                -32603,
                f"primary answered params for height {h} but "
                f"{height} was requested")
        cp = res["consensus_params"]
        params = ConsensusParams(
            block=BlockParams(
                max_bytes=int(cp["block"]["max_bytes"]),
                max_gas=int(cp["block"]["max_gas"])),
            evidence=EvidenceParams(
                max_age_num_blocks=int(
                    cp["evidence"]["max_age_num_blocks"]),
                max_age_duration_ns=int(
                    cp["evidence"]["max_age_duration"]),
                max_bytes=int(cp["evidence"]["max_bytes"])),
            validator=ValidatorParams(
                pub_key_types=list(cp["validator"]["pub_key_types"])),
            version=VersionParams(app_version=int(
                (cp.get("version") or {}).get("app_version", 0))),
        )
        lb = await self._verified_block_at(h)
        if params.hash() != lb.signed_header.header.consensus_hash:
            raise RPCError(
                -32603,
                f"consensus params do not hash to the verified "
                f"header {h}'s consensus_hash — refusing to relay")
        return res

    async def abci_query(self, ctx, path="", data="", height=0,
                         prove=True) -> dict:
        """Query the primary and PROVE the answer against the
        light-verified app hash (reference light/rpc/client.go:104-151
        ABCIQueryWithOptions): prove is forced on, the response must
        carry proof ops, and the value (or its absence) is verified
        via the ProofRuntime against header(resp.height+1).app_hash —
        the app hash for height H lives in header H+1. A tampered
        value, forged proof, or proof against the wrong state fails
        here instead of reaching the caller."""
        import base64

        from ..rpc.core import hexbytes_param

        # Decode once (hex / 0x-hex / URI-quoted raw) and forward as
        # plain hex so the primary sees one canonical form.
        want = hexbytes_param(data)
        res = await self._forwarder("abci_query")(
            ctx, path=path, data=want.hex(), height=height, prove=True)
        resp = res.get("response", {})
        if int(resp.get("code", 0)) != 0:
            raise RPCError(-32603,
                           f"err response code: {resp.get('code')}")
        key = base64.b64decode(resp.get("key") or "")
        if not key:
            raise RPCError(-32603, "empty key in query response")
        # The proof must be about the key WE asked for — a primary
        # that answers with a different key (and a perfectly valid
        # proof for it) must not pass.
        if key != want:
            raise RPCError(
                -32603,
                f"primary answered for key {key.hex()[:16]}… but "
                f"{want.hex()[:16]}… was queried")
        ops_json = (resp.get("proof_ops") or {}).get("ops") or []
        if not ops_json:
            raise RPCError(
                -32603, "no proof ops in query response (the app must "
                "support Prove=true for verified queries)")
        h = int(resp.get("height") or 0)
        if h <= 0:
            raise RPCError(-32603, "zero or negative query height")
        # The app hash for state h is committed in header h+1, which
        # may be one block-time away when the query hits the app's
        # live head — absorb only THAT race (block-not-found) with a
        # bounded wait; verification failures are deterministic and
        # surface immediately.
        import asyncio

        deadline = asyncio.get_running_loop().time() + 5.0
        while True:
            try:
                if self.plane is not None:
                    lb = await self.plane.get_verified(h + 1)
                else:
                    lb = await self.client.verify_light_block_at_height(
                        h + 1)
                break
            except BlockNotFoundError as e:
                if asyncio.get_running_loop().time() >= deadline:
                    raise RPCError(
                        -32603, f"header {h + 1} (carrying the app "
                        f"hash for query height {h}) not available: {e}")
                await asyncio.sleep(0.2)
            except LightServingShedError as e:
                # same shed-to-429 mapping as _verified_block_at:
                # backpressure, not a verdict (clause order matters —
                # the shed error IS a LightClientError)
                from ..rpc.jsonrpc import CODE_BUSY

                raise RPCError(CODE_BUSY, str(e), "queue_full")
            except LightClientError as e:
                raise RPCError(-32603, f"light verification failed: {e}")
        app_hash = lb.signed_header.header.app_hash
        from ..crypto.merkle import ProofOp

        ops = [ProofOp(o["type"], base64.b64decode(o.get("key") or ""),
                       base64.b64decode(o.get("data") or ""))
               for o in ops_json]
        value = base64.b64decode(resp.get("value") or "")
        rt = self._proof_runtime()
        if value:
            ok = rt.verify_value(ops, app_hash, [key], value)
        else:
            # An empty value is EITHER a proven absence OR a key
            # legitimately stored with an empty value — b64 JSON
            # cannot carry the reference's nil-vs-empty distinction,
            # so accept whichever proof the app sent; both pin the
            # relayed (empty) answer to the trusted root.
            ok = rt.verify_absence(ops, app_hash, [key]) or \
                rt.verify_value(ops, app_hash, [key], b"")
        if not ok:
            raise RPCError(
                -32603,
                f"proof verification failed for key {key.hex()[:16]}… "
                f"against app_hash of verified header {h + 1} — "
                "refusing to relay an unproven query result")
        return res

    def _proof_runtime(self):
        if getattr(self, "_prt", None) is None:
            from ..abci.kv_proofs import kv_proof_runtime

            self._prt = kv_proof_runtime()
        return self._prt

    # -- websocket subscriptions (reference light/proxy/routes.go
    #    subscribe/unsubscribe: relayed through the primary's event
    #    stream; events are inherently unverifiable live data, same
    #    trust level as the reference's passthrough) --

    def _ws_routes(self) -> dict:
        if self.forward is None or not hasattr(self.forward, "host"):
            return {}
        return {"subscribe": self.subscribe,
                "unsubscribe": self.unsubscribe,
                "unsubscribe_all": self.unsubscribe_all}

    MAX_SUBSCRIPTIONS_PER_CLIENT = 5  # same bound as RPCConfig

    async def subscribe(self, ctx, query="") -> dict:
        import asyncio

        from ..rpc.jsonrpc import WSClient, relay_events

        ws = ctx.ws
        if ws is None:
            raise RPCError(-32603, "subscribe requires a websocket")
        subs = getattr(ws, "_lp_subs", None)
        if subs is None:
            subs = ws._lp_subs = {}
        if query in subs:
            raise RPCError(-32603, f"already subscribed to {query!r}")
        if len(subs) >= self.MAX_SUBSCRIPTIONS_PER_CLIENT:
            # each subscription costs an upstream TCP+WS connection;
            # an unbounded loop over distinct queries must not
            # exhaust fds on proxy or primary
            raise RPCError(-32603, "too many subscriptions")
        up = WSClient(self.forward.host, self.forward.port)
        try:
            # bounded: the handler runs inline in the ws read loop, so
            # a blackholed primary must not wedge this client's socket
            await asyncio.wait_for(up.connect(), 10)
            await up.call("subscribe", query=query)
        except BaseException:
            up.close()
            raise
        task = asyncio.get_running_loop().create_task(
            relay_events(ws, up.events.get), name=f"lp-ws-sub-{id(ws)}")
        subs[query] = (up, task)
        return {}

    async def unsubscribe(self, ctx, query="") -> dict:
        ws = ctx.ws
        subs = getattr(ws, "_lp_subs", {}) if ws else {}
        ent = subs.pop(query, None)
        if ent is None:
            raise RPCError(-32603, f"not subscribed to {query!r}")
        up, task = ent
        task.cancel()
        up.close()
        return {}

    async def unsubscribe_all(self, ctx) -> dict:
        ws = ctx.ws
        for up, task in getattr(ws, "_lp_subs", {}).values():
            task.cancel()
            up.close()
        if ws is not None:
            ws._lp_subs = {}
        return {}

    def _on_ws_close(self, ws) -> None:
        for up, task in getattr(ws, "_lp_subs", {}).values():
            task.cancel()
            up.close()

    # -- pass-through routes --

    def _forwarder(self, name: str):
        async def fwd(ctx, **params):
            from ..rpc.jsonrpc import RPCError as ClientRPCError

            try:
                return await self.forward.call(name, **params)
            except ClientRPCError as e:
                raise RPCError(e.code, e.message, e.data)
            except OSError as e:
                raise RPCError(-32603, f"primary unreachable: {e}")

        return fwd
