"""Light proxy: a JSON-RPC server whose block-bearing responses are
LIGHT-VERIFIED before they leave the process (reference:
light/proxy/proxy.go:16, light/proxy/routes.go).

A wallet or indexer points at this proxy exactly as it would at a full
node; the proxy forwards transaction submission and queries to the
primary, but every header/commit/validator-set it returns has passed
the light client's verification (sequential or skipping + witness
cross-check), and every full block fetched from the primary is checked
against the corresponding verified header hash. A lying primary
cannot feed this proxy's clients a forged chain — the request fails
instead.
"""

from __future__ import annotations

import logging

from ..rpc.jsonrpc import JSONRPCServer, RPCError
from .client import Client
from .errors import LightClientError
from .provider import BlockNotFoundError

logger = logging.getLogger("light.proxy")


class LightProxy:
    """Serves verified RPC routes from a light `Client`.

    forward_client: an ``HTTPClient`` to the primary's RPC, used for
    pass-through routes (tx broadcast, abci queries, full blocks);
    None disables those routes (verified-only mode, e.g. tests over a
    BlockStoreProvider primary).
    """

    def __init__(self, client: Client, forward_client=None):
        self.client = client
        self.forward = forward_client
        self.server = JSONRPCServer(self._routes())
        self.port: int | None = None

    async def listen(self, host: str, port: int) -> int:
        self.port = await self.server.listen(host, port)
        logger.info("light proxy serving verified RPC on %s:%d",
                    host, self.port)
        return self.port

    def close(self) -> None:
        self.server.close()

    def _routes(self) -> dict:
        routes = {
            "status": self.status,
            "commit": self.commit,
            "validators": self.validators,
            "block": self.block,
            "header": self.header,
            "health": self.health,
        }
        if self.forward is not None:
            for name in ("broadcast_tx_sync", "broadcast_tx_async",
                         "broadcast_tx_commit", "abci_query", "abci_info",
                         "tx", "tx_search", "net_info",
                         "broadcast_evidence"):
                routes[name] = self._forwarder(name)
        return routes

    # -- verified routes --

    async def _verified_block_at(self, height) -> "object":
        h = int(height) if height else 0
        try:
            if h == 0:
                lb = await self.client.update()
                if lb is None:
                    lb = self.client.trusted_light_block()
            else:
                lb = await self.client.verify_light_block_at_height(h)
        except (LightClientError, BlockNotFoundError) as e:
            raise RPCError(-32603, f"light verification failed: {e}")
        if lb is None:
            raise RPCError(-32603, "no trusted block yet")
        return lb

    async def health(self, ctx) -> dict:
        return {}

    async def status(self, ctx) -> dict:
        lb = self.client.trusted_light_block()
        if lb is None:
            raise RPCError(-32603, "light client not initialized")
        h = lb.signed_header.header
        return {
            "node_info": {
                "network": h.chain_id,
                "moniker": "light-proxy",
                "version": "tendermint-tpu/light",
            },
            "sync_info": {
                "latest_block_height": str(h.height),
                "latest_block_hash": lb.hash().hex().upper(),
                "latest_app_hash": h.app_hash.hex().upper(),
                "latest_block_time": str(h.time),
                "catching_up": False,
            },
        }

    async def commit(self, ctx, height=None) -> dict:
        from ..rpc.core import _commit_json, _header_json

        lb = await self._verified_block_at(height)
        return {
            "signed_header": {
                "header": _header_json(lb.signed_header.header),
                "commit": _commit_json(lb.signed_header.commit),
            },
            "canonical": True,
        }

    async def header(self, ctx, height=None) -> dict:
        from ..rpc.core import _header_json

        lb = await self._verified_block_at(height)
        return {"header": _header_json(lb.signed_header.header)}

    async def validators(self, ctx, height=None, page=1,
                         per_page=30) -> dict:
        from ..rpc.core import _validator_json

        lb = await self._verified_block_at(height)
        vals = lb.validator_set
        page, per_page = max(int(page), 1), min(max(int(per_page), 1), 100)
        start = (page - 1) * per_page
        sel = vals.validators[start:start + per_page]
        return {"block_height": str(lb.height()),
                "validators": [_validator_json(v) for v in sel],
                "count": str(len(sel)), "total": str(len(vals))}

    async def block(self, ctx, height=None) -> dict:
        """Full block from the primary, checked hash-for-hash against
        the light-verified header (reference routes.go BlockFn →
        proxy verification)."""
        if self.forward is None:
            raise RPCError(-32601, "block pass-through not configured")
        lb = await self._verified_block_at(height)
        res = await self.forward.call("block", height=lb.height())
        got = bytes.fromhex(res["block_id"]["hash"])
        want = lb.hash()
        if got != want:
            raise RPCError(
                -32603,
                f"primary served block {got.hex()[:16]}… but the "
                f"verified header at height {lb.height()} is "
                f"{want.hex()[:16]}… — refusing to relay a forged block")
        return res

    # -- pass-through routes --

    def _forwarder(self, name: str):
        async def fwd(ctx, **params):
            from ..rpc.jsonrpc import RPCError as ClientRPCError

            try:
                return await self.forward.call(name, **params)
            except ClientRPCError as e:
                raise RPCError(e.code, e.message, e.data)
            except OSError as e:
                raise RPCError(-32603, f"primary unreachable: {e}")

        return fwd
