"""Light proxy: a JSON-RPC server whose block-bearing responses are
LIGHT-VERIFIED before they leave the process (reference:
light/proxy/proxy.go:16, light/proxy/routes.go).

A wallet or indexer points at this proxy exactly as it would at a full
node; the proxy forwards transaction submission and queries to the
primary, but every header/commit/validator-set it returns has passed
the light client's verification (sequential or skipping + witness
cross-check), and every full block fetched from the primary is checked
against the corresponding verified header hash. A lying primary
cannot feed this proxy's clients a forged chain — the request fails
instead.
"""

from __future__ import annotations

import logging

from ..rpc.jsonrpc import JSONRPCServer, RPCError
from .client import Client
from .errors import LightClientError
from .provider import BlockNotFoundError

logger = logging.getLogger("light.proxy")


class LightProxy:
    """Serves verified RPC routes from a light `Client`.

    forward_client: an ``HTTPClient`` to the primary's RPC, used for
    pass-through routes (tx broadcast, abci queries, full blocks);
    None disables those routes (verified-only mode, e.g. tests over a
    BlockStoreProvider primary).
    """

    def __init__(self, client: Client, forward_client=None,
                 proof_runtime=None):
        self.client = client
        self.forward = forward_client
        # app-defined proof formats decode through this registry
        # (reference: lrpc.KeyPathFn/prt options); default knows the
        # kvstore ops, apps with their own formats inject a runtime
        self._prt = proof_runtime
        self.server = JSONRPCServer(self._routes())
        self.port: int | None = None

    async def listen(self, host: str, port: int) -> int:
        self.port = await self.server.listen(host, port)
        logger.info("light proxy serving verified RPC on %s:%d",
                    host, self.port)
        return self.port

    def close(self) -> None:
        self.server.close()

    def _routes(self) -> dict:
        routes = {
            "status": self.status,
            "commit": self.commit,
            "validators": self.validators,
            "block": self.block,
            "header": self.header,
            "health": self.health,
        }
        if self.forward is not None:
            routes["abci_query"] = self.abci_query
            for name in ("broadcast_tx_sync", "broadcast_tx_async",
                         "broadcast_tx_commit", "abci_info",
                         "tx", "tx_search", "net_info",
                         "broadcast_evidence"):
                routes[name] = self._forwarder(name)
        return routes

    # -- verified routes --

    async def _verified_block_at(self, height) -> "object":
        h = int(height) if height else 0
        try:
            if h == 0:
                lb = await self.client.update()
                if lb is None:
                    lb = self.client.trusted_light_block()
            else:
                lb = await self.client.verify_light_block_at_height(h)
        except (LightClientError, BlockNotFoundError) as e:
            raise RPCError(-32603, f"light verification failed: {e}")
        if lb is None:
            raise RPCError(-32603, "no trusted block yet")
        return lb

    async def health(self, ctx) -> dict:
        return {}

    async def status(self, ctx) -> dict:
        lb = self.client.trusted_light_block()
        if lb is None:
            raise RPCError(-32603, "light client not initialized")
        h = lb.signed_header.header
        return {
            "node_info": {
                "network": h.chain_id,
                "moniker": "light-proxy",
                "version": "tendermint-tpu/light",
            },
            "sync_info": {
                "latest_block_height": str(h.height),
                "latest_block_hash": lb.hash().hex().upper(),
                "latest_app_hash": h.app_hash.hex().upper(),
                "latest_block_time": str(h.time),
                "catching_up": False,
            },
        }

    async def commit(self, ctx, height=None) -> dict:
        from ..rpc.core import _commit_json, _header_json

        lb = await self._verified_block_at(height)
        return {
            "signed_header": {
                "header": _header_json(lb.signed_header.header),
                "commit": _commit_json(lb.signed_header.commit),
            },
            "canonical": True,
        }

    async def header(self, ctx, height=None) -> dict:
        from ..rpc.core import _header_json

        lb = await self._verified_block_at(height)
        return {"header": _header_json(lb.signed_header.header)}

    async def validators(self, ctx, height=None, page=1,
                         per_page=30) -> dict:
        from ..rpc.core import _validator_json

        lb = await self._verified_block_at(height)
        vals = lb.validator_set
        page, per_page = max(int(page), 1), min(max(int(per_page), 1), 100)
        start = (page - 1) * per_page
        sel = vals.validators[start:start + per_page]
        return {"block_height": str(lb.height()),
                "validators": [_validator_json(v) for v in sel],
                "count": str(len(sel)), "total": str(len(vals))}

    async def block(self, ctx, height=None) -> dict:
        """Full block from the primary, checked hash-for-hash against
        the light-verified header (reference routes.go BlockFn →
        proxy verification)."""
        if self.forward is None:
            raise RPCError(-32601, "block pass-through not configured")
        lb = await self._verified_block_at(height)
        res = await self.forward.call("block", height=lb.height())
        got = bytes.fromhex(res["block_id"]["hash"])
        want = lb.hash()
        if got != want:
            raise RPCError(
                -32603,
                f"primary served block {got.hex()[:16]}… but the "
                f"verified header at height {lb.height()} is "
                f"{want.hex()[:16]}… — refusing to relay a forged block")
        return res

    async def abci_query(self, ctx, path="", data="", height=0,
                         prove=True) -> dict:
        """Query the primary and PROVE the answer against the
        light-verified app hash (reference light/rpc/client.go:104-151
        ABCIQueryWithOptions): prove is forced on, the response must
        carry proof ops, and the value (or its absence) is verified
        via the ProofRuntime against header(resp.height+1).app_hash —
        the app hash for height H lives in header H+1. A tampered
        value, forged proof, or proof against the wrong state fails
        here instead of reaching the caller."""
        import base64

        res = await self._forwarder("abci_query")(
            ctx, path=path, data=data, height=height, prove=True)
        resp = res.get("response", {})
        if int(resp.get("code", 0)) != 0:
            raise RPCError(-32603,
                           f"err response code: {resp.get('code')}")
        key = base64.b64decode(resp.get("key") or "")
        if not key:
            raise RPCError(-32603, "empty key in query response")
        # The proof must be about the key WE asked for — a primary
        # that answers with a different key (and a perfectly valid
        # proof for it) must not pass.
        from ..rpc.core import coerce_hex_param

        data = coerce_hex_param(data)
        want = bytes.fromhex(data) if data else b""
        if key != want:
            raise RPCError(
                -32603,
                f"primary answered for key {key.hex()[:16]}… but "
                f"{want.hex()[:16]}… was queried")
        ops_json = (resp.get("proof_ops") or {}).get("ops") or []
        if not ops_json:
            raise RPCError(
                -32603, "no proof ops in query response (the app must "
                "support Prove=true for verified queries)")
        h = int(resp.get("height") or 0)
        if h <= 0:
            raise RPCError(-32603, "zero or negative query height")
        # The app hash for state h is committed in header h+1, which
        # may be one block-time away when the query hits the app's
        # live head — absorb only THAT race (block-not-found) with a
        # bounded wait; verification failures are deterministic and
        # surface immediately.
        import asyncio

        deadline = asyncio.get_running_loop().time() + 5.0
        while True:
            try:
                lb = await self.client.verify_light_block_at_height(h + 1)
                break
            except BlockNotFoundError as e:
                if asyncio.get_running_loop().time() >= deadline:
                    raise RPCError(
                        -32603, f"header {h + 1} (carrying the app "
                        f"hash for query height {h}) not available: {e}")
                await asyncio.sleep(0.2)
            except LightClientError as e:
                raise RPCError(-32603, f"light verification failed: {e}")
        app_hash = lb.signed_header.header.app_hash
        from ..crypto.merkle import ProofOp

        ops = [ProofOp(o["type"], base64.b64decode(o.get("key") or ""),
                       base64.b64decode(o.get("data") or ""))
               for o in ops_json]
        value = base64.b64decode(resp.get("value") or "")
        rt = self._proof_runtime()
        if value:
            ok = rt.verify_value(ops, app_hash, [key], value)
        else:
            # An empty value is EITHER a proven absence OR a key
            # legitimately stored with an empty value — b64 JSON
            # cannot carry the reference's nil-vs-empty distinction,
            # so accept whichever proof the app sent; both pin the
            # relayed (empty) answer to the trusted root.
            ok = rt.verify_absence(ops, app_hash, [key]) or \
                rt.verify_value(ops, app_hash, [key], b"")
        if not ok:
            raise RPCError(
                -32603,
                f"proof verification failed for key {key.hex()[:16]}… "
                f"against app_hash of verified header {h + 1} — "
                "refusing to relay an unproven query result")
        return res

    def _proof_runtime(self):
        if getattr(self, "_prt", None) is None:
            from ..abci.kv_proofs import kv_proof_runtime

            self._prt = kv_proof_runtime()
        return self._prt

    # -- pass-through routes --

    def _forwarder(self, name: str):
        async def fwd(ctx, **params):
            from ..rpc.jsonrpc import RPCError as ClientRPCError

            try:
                return await self.forward.call(name, **params)
            except ClientRPCError as e:
                raise RPCError(e.code, e.message, e.data)
            except OSError as e:
                raise RPCError(-32603, f"primary unreachable: {e}")

        return fwd
