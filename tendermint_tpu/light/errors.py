"""Light client error taxonomy (reference: light/errors.go)."""

from __future__ import annotations


class LightClientError(Exception):
    pass


class VerificationFailedError(LightClientError):
    """Header failed verification — definitive rejection."""


class NewValSetCantBeTrustedError(LightClientError):
    """<1/3 trusted overlap at this distance: bisect closer
    (reference: types.ErrNotEnoughVotingPowerSigned → bisection)."""


class OutsideTrustingPeriodError(LightClientError):
    pass


class DivergenceError(LightClientError):
    """A witness disagrees with the primary — possible attack
    (reference: light/detector.go ErrConflictingHeaders)."""

    def __init__(self, witness_index: int, witness_block, primary_block):
        self.witness_index = witness_index
        self.witness_block = witness_block
        self.primary_block = primary_block
        # Filled by the detector once the fork is proven: the two
        # LightClientAttackEvidence objects submitted to each side.
        self.evidence: list = []
        super().__init__(
            f"witness {witness_index} header conflicts with primary at "
            f"height {primary_block.height()}")
