"""`tendermint-tpu debug kill|dump` — diagnostics bundles from a
running node (reference: cmd/tendermint/commands/debug/kill.go,
dump.go, util.go).

Both commands aggregate, into a .tar.gz archive:

  status.json           RPC `status`
  net_info.json         RPC `net_info`
  consensus_state.json  RPC `dump_consensus_state`
  goroutine.txt         debug server /debug/pprof/goroutine
                        (asyncio-task + thread stacks)
  heap.txt              debug server /debug/pprof/heap
  trace.json            debug server /debug/trace (span timeline,
                        Chrome trace-event JSON for Perfetto)
  trace_rollup.json     per-span-kind p50/p95/p99 rollup
  metrics.txt           debug server /metrics (Prometheus exposition)
  node_health.json      debug server /status (liveness verdict)
  config.toml           the node's config file
  cs.wal/               copy of the consensus WAL directory

`kill` additionally SIGABRTs the process afterwards (the reference
sends SIGABRT to force a Go runtime dump; here it still produces a
core-style termination and a crash log). `dump` polls, producing one
timestamped bundle per interval, optionally including a CPU profile
from /debug/pprof/profile.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import tarfile
import tempfile
import time
import urllib.error
import urllib.request


def _rpc_call(rpc_addr: str, method: str) -> dict:
    req = urllib.request.Request(
        f"http://{rpc_addr}/",
        data=json.dumps({
            "jsonrpc": "2.0", "method": method, "params": {}, "id": 1,
        }).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        body = json.loads(resp.read())
    if "error" in body and body["error"]:
        raise RuntimeError(f"{method}: {body['error']}")
    return body.get("result", {})


def _pprof_get(pprof_addr: str, path: str,
               timeout: float = 30.0) -> bytes:
    with urllib.request.urlopen(
            f"http://{pprof_addr}{path}", timeout=timeout) as resp:
        return resp.read()


def _collect(tmp: str, rpc_addr: str, pprof_addr: str, home: str,
             profile_seconds: float = 0.0) -> list[str]:
    """Gather every artifact into `tmp`; returns notes about pieces
    that could not be collected (best-effort, like the reference)."""
    notes = []
    for method, fname in (
        ("status", "status.json"),
        ("net_info", "net_info.json"),
        ("dump_consensus_state", "consensus_state.json"),
    ):
        try:
            result = _rpc_call(rpc_addr, method)
            with open(os.path.join(tmp, fname), "w") as f:
                json.dump(result, f, indent=2, default=str)
        except Exception as e:
            notes.append(f"{fname}: {e!r}")

    for path, fname in (
        ("/debug/pprof/goroutine", "goroutine.txt"),
        ("/debug/pprof/heap", "heap.txt"),
        ("/debug/trace", "trace.json"),
        ("/debug/trace/rollup", "trace_rollup.json"),
        ("/metrics", "metrics.txt"),
        ("/status", "node_health.json"),
    ):
        try:
            data = _pprof_get(pprof_addr, path)
            with open(os.path.join(tmp, fname), "wb") as f:
                f.write(data)
        except Exception as e:
            notes.append(f"{fname}: {e!r}")
    if profile_seconds > 0:
        try:
            data = _pprof_get(
                pprof_addr,
                f"/debug/pprof/profile?seconds={profile_seconds}",
                timeout=profile_seconds + 30.0)
            with open(os.path.join(tmp, "profile.txt"), "wb") as f:
                f.write(data)
        except Exception as e:
            notes.append(f"profile.txt: {e!r}")

    # Filesystem copies stay best-effort too: the node is live, so the
    # WAL directory can rotate/truncate mid-copy.
    try:
        cfg_file = os.path.join(home, "config", "config.toml")
        if os.path.exists(cfg_file):
            shutil.copy(cfg_file, os.path.join(tmp, "config.toml"))
        else:
            notes.append(f"config.toml: not found at {cfg_file}")
    except OSError as e:
        notes.append(f"config.toml: {e!r}")
    try:
        wal_dir = os.path.join(home, "data", "cs.wal")
        if os.path.isdir(wal_dir):
            shutil.copytree(wal_dir, os.path.join(tmp, "cs.wal"))
        else:
            notes.append(f"cs.wal: not found at {wal_dir}")
    except OSError as e:
        notes.append(f"cs.wal: {e!r}")

    if notes:
        with open(os.path.join(tmp, "INCOMPLETE.txt"), "w") as f:
            f.write("\n".join(notes) + "\n")
    return notes


def _bundle(tmp: str, out_file: str) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(out_file)), exist_ok=True)
    with tarfile.open(out_file, "w:gz") as tar:
        for name in sorted(os.listdir(tmp)):
            tar.add(os.path.join(tmp, name), arcname=name)


def cmd_debug_kill(args) -> int:
    """reference: cmd/tendermint/commands/debug/kill.go."""
    pid = args.pid
    with tempfile.TemporaryDirectory(prefix="tm_debug_") as tmp:
        notes = _collect(tmp, args.rpc_laddr, args.pprof_laddr, args.home)
        _bundle(tmp, args.output_file)
    for n in notes:
        print(f"warning: {n}")
    print(f"wrote debug bundle: {args.output_file}")
    try:
        os.kill(pid, signal.SIGABRT)
        print(f"sent SIGABRT to pid {pid}")
    except ProcessLookupError:
        print(f"warning: no such process {pid}")
        return 1
    return 0


def cmd_debug_trace(args) -> int:
    """Capture the node's span-tracer ring as a Perfetto-loadable
    Chrome trace-event JSON file (plus the per-stage rollup on
    stdout). The lightweight sibling of kill/dump for the question
    'where did the last N seconds actually go'."""
    try:
        raw = _pprof_get(
            args.pprof_laddr,
            f"/debug/trace?seconds={args.seconds}"
            if args.seconds else "/debug/trace")
        trace = json.loads(raw)
        events = trace.get("traceEvents")
        if not isinstance(events, list):
            raise ValueError("response is not Chrome trace-event JSON")
    except Exception as e:
        print(f"error: trace capture failed: {e!r}")
        return 1
    try:
        out_dir = os.path.dirname(os.path.abspath(args.output_file))
        os.makedirs(out_dir, exist_ok=True)
        with open(args.output_file, "wb") as f:
            f.write(raw)
    except OSError as e:
        print(f"error: cannot write {args.output_file}: {e!r}")
        return 1
    print(f"wrote {len(events)} spans: {args.output_file} "
          "(open in https://ui.perfetto.dev or chrome://tracing)")
    try:
        rollup = json.loads(_pprof_get(
            args.pprof_laddr,
            f"/debug/trace/rollup?seconds={args.seconds}"
            if args.seconds else "/debug/trace/rollup"))
        for kind, row in rollup.get("stages", {}).items():
            print(f"  {kind:<24} n={row['count']:<6} "
                  f"p50={row['p50_ms']}ms p95={row['p95_ms']}ms "
                  f"p99={row['p99_ms']}ms")
        dropped = rollup.get("spans_dropped", 0)
        if dropped:
            print(f"  WARNING: {dropped} spans evicted from the ring "
                  f"(capacity {rollup.get('capacity')}) — the timeline "
                  "above is a suffix, not the whole story")
    except Exception as e:
        print(f"warning: rollup unavailable: {e!r}")
    return 0


def cmd_debug_dump(args) -> int:
    """reference: cmd/tendermint/commands/debug/dump.go — poll forever
    (or --count times), one timestamped bundle per interval."""
    os.makedirs(args.output_dir, exist_ok=True)
    remaining = args.count
    while True:
        start = time.time()
        stamp = time.strftime("%Y-%m-%d_%H-%M-%S", time.gmtime())
        out_file = os.path.join(args.output_dir, f"{stamp}.tar.gz")
        with tempfile.TemporaryDirectory(prefix="tm_debug_") as tmp:
            notes = _collect(tmp, args.rpc_laddr, args.pprof_laddr,
                             args.home,
                             profile_seconds=args.profile_seconds)
            _bundle(tmp, out_file)
        for n in notes:
            print(f"warning: {n}")
        print(f"wrote debug bundle: {out_file}")
        if remaining is not None:
            remaining -= 1
            if remaining <= 0:
                return 0
        delay = args.interval - (time.time() - start)
        if delay > 0:
            time.sleep(delay)


def register(sub) -> None:
    """Attach the `debug` command group to the CLI parser."""
    import argparse

    sp = sub.add_parser("debug", help="debug a running node")
    dsub = sp.add_subparsers(dest="debug_command", required=True)
    # --home: SUPPRESS so these subparsers don't clobber the top-level
    # `tendermint-tpu --home ...` value (argparse subparser defaults
    # overwrite the parent namespace); the top-level flag provides the
    # actual default.
    common = {
        "--home": dict(default=argparse.SUPPRESS,
                       help="node home directory"),
        "--rpc-laddr": dict(default="127.0.0.1:26657",
                            help="node RPC address host:port"),
        "--pprof-laddr": dict(default="127.0.0.1:6060",
                              help="node debug/pprof address host:port"),
    }

    kp = dsub.add_parser(
        "kill", help="capture a debug bundle, then SIGABRT the node")
    kp.add_argument("pid", type=int, help="node process id")
    kp.add_argument("output_file", help="output .tar.gz path")
    for flag, kw in common.items():
        kp.add_argument(flag, **kw)
    kp.set_defaults(fn=cmd_debug_kill)

    tp = dsub.add_parser(
        "trace", help="capture a span trace (Perfetto/Chrome JSON)")
    tp.add_argument("output_file", help="output trace.json path")
    tp.add_argument("--seconds", type=float, default=0.0,
                    help="window to the trailing N seconds "
                         "(default: the whole span ring)")
    for flag, kw in common.items():
        tp.add_argument(flag, **kw)
    tp.set_defaults(fn=cmd_debug_trace)

    dp = dsub.add_parser(
        "dump", help="periodically capture debug bundles")
    dp.add_argument("output_dir", help="directory for .tar.gz bundles")
    dp.add_argument("--interval", type=float, default=30.0,
                    help="seconds between bundles")
    dp.add_argument("--count", type=int, default=None,
                    help="stop after N bundles (default: forever)")
    dp.add_argument("--profile-seconds", type=float, default=0.0,
                    help="include a CPU profile of this length")
    for flag, kw in common.items():
        dp.add_argument(flag, **kw)
    dp.set_defaults(fn=cmd_debug_dump)
