"""Command-line interface (reference: cmd/tendermint/main.go:15-45).

Subcommands: init, start, testnet, light, replay, replay-console,
unsafe-reset-all, unsafe-reset-priv-validator, debug kill|dump,
gen-validator, show-validator, gen-node-key, show-node-id, probe-upnp,
version. argparse instead of cobra; same behaviors."""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import shutil
import sys
import time

VERSION = "tendermint-tpu/0.1.0"


def _load_config(home: str):
    from ..config import Config

    path = os.path.join(home, "config", "config.toml")
    if os.path.exists(path):
        cfg = Config.load(path)
        # Reject typo'd values loudly (e.g. tx_index.indexer =
        # "nulll" silently meaning "kv") instead of running with a
        # config the operator didn't ask for — reference
        # config.ValidateBasic on the CLI load path. Clean one-line
        # CLI error, not a traceback.
        try:
            cfg.validate_basic()
        except ValueError as e:
            raise SystemExit(f"invalid config {path}: {e}")
    else:
        cfg = Config()
    cfg.base.home = home
    return cfg


def cmd_init(args) -> int:
    """reference: cmd/tendermint/commands/init.go."""
    from ..config import Config
    from ..p2p.key import NodeKey
    from ..privval import FilePV
    from ..types.genesis import GenesisDoc, GenesisValidator

    home = args.home
    os.makedirs(os.path.join(home, "config"), exist_ok=True)
    os.makedirs(os.path.join(home, "data"), exist_ok=True)
    cfg = Config()
    cfg.base.home = home

    key_file = cfg.base.resolve(cfg.base.priv_validator_key_file)
    state_file = cfg.base.resolve(cfg.base.priv_validator_state_file)
    if os.path.exists(key_file):
        pv = FilePV.load(key_file, state_file)
        print(f"Found private validator: {key_file}")
    else:
        pv = FilePV.generate(key_file, state_file)
        print(f"Generated private validator: {key_file}")

    nk_file = cfg.base.resolve(cfg.base.node_key_file)
    NodeKey.load_or_gen(nk_file)
    print(f"Node key: {nk_file}")

    gen_file = cfg.base.resolve(cfg.base.genesis_file)
    if not os.path.exists(gen_file):
        gdoc = GenesisDoc(
            chain_id=args.chain_id or f"test-chain-{os.urandom(3).hex()}",
            genesis_time=time.time_ns(),
            validators=[GenesisValidator(pv.get_pub_key(), 10)],
        )
        gdoc.validate_and_complete()
        gdoc.save(gen_file)
        print(f"Generated genesis file: {gen_file}")
    else:
        print(f"Found genesis file: {gen_file}")

    cfg_file = os.path.join(home, "config", "config.toml")
    if not os.path.exists(cfg_file):
        cfg.save(cfg_file)
        print(f"Generated config: {cfg_file}")
    return 0


def cmd_start(args) -> int:
    """reference: cmd/tendermint/commands/run_node.go:100."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # Honor the operator's platform choice even on machines whose
        # sitecustomize force-registers an accelerator plugin: without
        # the config-level override, the first device-path signature
        # batch tries the accelerator backend, and a wedged relay
        # freezes the whole node mid-consensus (observed: a restarted
        # node hanging forever on catch-up vote batches).
        from ..libs.cpuforce import force_cpu_backend

        force_cpu_backend()

    from ..node import Node

    logging.basicConfig(
        level=logging.DEBUG if args.log_level == "debug" else logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s")
    cfg = _load_config(args.home)
    if args.proxy_app:
        cfg.base.proxy_app = args.proxy_app
    if args.p2p_laddr:
        cfg.p2p.laddr = args.p2p_laddr
    if args.rpc_laddr:
        cfg.rpc.laddr = args.rpc_laddr
    if args.persistent_peers:
        cfg.p2p.persistent_peers = args.persistent_peers
    if args.fast_sync is not None:
        cfg.base.fast_sync = args.fast_sync == "true"

    async def run():
        node = Node.default_new_node(cfg)
        # Maverick mode (reference: test/maverick — a SEPARATE node
        # binary with pluggable misbehaviors): --misbehavior
        # double-prevote@H. Equivocation bypasses the PrivValidator
        # double-sign guard and gets a production validator slashed,
        # so the flag is inert unless TM_TPU_ENABLE_MAVERICK=1 marks
        # the process as a test node.
        if args.misbehavior:
            if os.environ.get("TM_TPU_ENABLE_MAVERICK") != "1":
                raise SystemExit(
                    "--misbehavior deliberately equivocates (slashable);"
                    " refusing without TM_TPU_ENABLE_MAVERICK=1")
            logging.getLogger("node").warning(
                "MAVERICK MODE: this node will misbehave: %s",
                args.misbehavior)
            from ..consensus.misbehavior import MISBEHAVIORS

            for spec in args.misbehavior.split(","):
                name, _, h = spec.partition("@")
                node.misbehaviors[int(h)] = MISBEHAVIORS[name]()
        await node.start()
        logging.getLogger("node").info(
            "node %s started: p2p %s rpc port %s",
            cfg.base.moniker, node.p2p_addr,
            getattr(node, "rpc_port", "off"))
        stop = asyncio.Event()
        import signal

        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:  # pragma: no cover
                pass
        await stop.wait()
        await node.stop()

    asyncio.run(run())
    return 0


def cmd_testnet(args) -> int:
    """Generate N validator home dirs wired as a full mesh
    (reference: cmd/tendermint/commands/testnet.go)."""
    from ..config import Config
    from ..p2p.key import NodeKey
    from ..privval import FilePV
    from ..types.genesis import GenesisDoc, GenesisValidator

    n = args.v
    out = args.o
    pvs, node_keys, cfgs = [], [], []
    for i in range(n):
        home = os.path.join(out, f"node{i}")
        os.makedirs(os.path.join(home, "config"), exist_ok=True)
        os.makedirs(os.path.join(home, "data"), exist_ok=True)
        cfg = Config()
        cfg.base.home = home
        cfg.base.moniker = f"node{i}"
        pv = FilePV.generate(
            cfg.base.resolve(cfg.base.priv_validator_key_file),
            cfg.base.resolve(cfg.base.priv_validator_state_file))
        nk = NodeKey.load_or_gen(cfg.base.resolve(cfg.base.node_key_file))
        pvs.append(pv)
        node_keys.append(nk)
        cfgs.append(cfg)

    gdoc = GenesisDoc(
        chain_id=args.chain_id or f"testnet-{os.urandom(3).hex()}",
        genesis_time=time.time_ns(),
        validators=[GenesisValidator(pv.get_pub_key(), 10) for pv in pvs],
    )
    gdoc.validate_and_complete()

    base_p2p = args.starting_port
    base_rpc = args.starting_port + 1000
    for i, cfg in enumerate(cfgs):
        gdoc.save(cfg.base.resolve(cfg.base.genesis_file))
        cfg.p2p.laddr = f"tcp://127.0.0.1:{base_p2p + i}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{base_rpc + i}"
        cfg.p2p.persistent_peers = ",".join(
            f"{node_keys[j].id}@127.0.0.1:{base_p2p + j}"
            for j in range(n) if j != i)
        cfg.save(os.path.join(cfg.base.home, "config", "config.toml"))
    print(f"Successfully initialized {n} node directories in {out}")
    return 0


def cmd_light(args) -> int:
    """Light client daemon: follow a chain through an RPC primary,
    verifying every header (reference: cmd/tendermint/commands/light.go
    + light/proxy)."""
    from ..libs.db import FileDB, MemDB
    from ..light import Client, LightStore, TrustOptions
    from ..light.provider import RPCProvider

    host, _, port = args.primary.rpartition(":")
    primary = RPCProvider(host or "127.0.0.1", int(port))
    witnesses = []
    for w in (args.witnesses or "").split(","):
        if w:
            wh, _, wp = w.rpartition(":")
            witnesses.append(RPCProvider(wh or "127.0.0.1", int(wp)))
    store = LightStore(FileDB(args.store) if args.store else MemDB())

    async def run():
        cl = Client(
            args.chain_id,
            TrustOptions(period_ns=args.trust_period * 10**9,
                         height=args.trust_height,
                         hash=bytes.fromhex(args.trust_hash)),
            primary, witnesses, store)
        lb = await cl.initialize()
        print(f"trusted root at height {lb.height()}: "
              f"{lb.hash().hex()[:16]}…")
        proxy = None
        if args.laddr:
            from ..light.proxy import LightProxy
            from ..rpc.jsonrpc import HTTPClient

            lh, _, lp = args.laddr.rpartition(":")
            proxy = LightProxy(
                cl, forward_client=HTTPClient(host or "127.0.0.1",
                                              int(port)))
            p = await proxy.listen(lh or "127.0.0.1", int(lp))
            print(f"light proxy: verified RPC on {lh or '127.0.0.1'}:{p}")
        try:
            while True:
                new = await cl.update()
                if new is not None:
                    print(f"verified height {new.height()}: "
                          f"{new.hash().hex()[:16]}…")
                if args.once:
                    return
                await asyncio.sleep(args.interval)
        finally:
            if proxy is not None:
                proxy.close()

    asyncio.run(run())
    return 0


def cmd_replay(args) -> int:
    """Replay the consensus WAL through the app (reference:
    cmd/tendermint/commands/replay.go → consensus.RunReplayFile)."""
    from ..node import Node

    cfg = _load_config(args.home)

    async def run():
        node = Node.default_new_node(cfg)
        await node._build()
        # handshake already replayed blocks into the app; starting
        # consensus replays the WAL tail for the current height
        await node.consensus_state.start()
        h = node.consensus_state.rs.height
        print(f"replay complete; consensus at height {h}")
        await node.stop()

    asyncio.run(run())
    return 0


def cmd_replay_console(args) -> int:
    """Interactive WAL replay (reference: replay.go ReplayConsoleCmd →
    RunReplayFile(console=true)): step through the consensus WAL
    message by message — Enter advances one message, a number advances
    that many, 'q' quits. Read-only: decodes the WAL without mutating
    any store, so it is safe on a live node's data directory copy."""
    from ..consensus import wal as walmod

    cfg = _load_config(args.home)
    wal_path = cfg.base.resolve(cfg.consensus.wal_file)
    if not os.path.exists(wal_path):
        print(f"no WAL at {wal_path}")
        return 1
    # Strictly read-only (works on a read-only mount) and streamed one
    # segment at a time — a full WAL group is up to 1 GiB on disk, far
    # more as decoded Python objects.
    segs = [p for p in walmod.segment_paths(wal_path) if os.path.exists(p)]
    print(f"WAL group: {len(segs)} segment(s) at {wal_path}")
    i = 0
    step = 0
    for seg in segs:
        for tm in walmod.WAL.decode_iter(seg):
            if step <= 0:
                try:
                    line = input(f"[{i}] Enter=next, N=skip N, "
                                 "q=quit > ").strip()
                except EOFError:
                    line = "q"
                if line == "q":
                    return 0
                step = int(line) if line.isdigit() else 1
            step -= 1
            print(f"  #{i} t={tm.time_ns} "
                  f"{type(tm.msg).__name__}: {tm.msg}")
            i += 1
    print(f"end of WAL ({i} messages)")
    return 0


def cmd_unsafe_reset_all(args) -> int:
    """reference: cmd/tendermint/commands/reset_priv_validator.go
    ResetAll — remove data + WAL (+ addrbook unless --keep-addr-book),
    reset the validator's last-sign state."""
    cfg = _load_config(args.home)
    data = cfg.base.resolve(cfg.base.db_dir)
    if os.path.isdir(data):
        shutil.rmtree(data)
        os.makedirs(data)
        print(f"Removed all data in {data}")
    book = cfg.base.resolve("config/addrbook.json")
    if getattr(args, "keep_addr_book", False):
        print("The address book remains intact")
    elif os.path.exists(book):
        os.remove(book)
        print(f"Removed existing address book {book}")
    state_file = cfg.base.resolve(cfg.base.priv_validator_state_file)
    if os.path.exists(state_file):
        os.remove(state_file)
    print("Reset private validator state")
    return 0


def cmd_unsafe_reset_priv_validator(args) -> int:
    """reference: reset_priv_validator.go ResetPrivValidatorCmd —
    reset ONLY this node's validator to genesis state: regenerate the
    key file if missing and wipe the last-sign state (the double-sign
    guard's HRS record). Data/WAL/addrbook stay intact."""
    from ..privval import FilePV

    cfg = _load_config(args.home)
    key_file = cfg.base.resolve(cfg.base.priv_validator_key_file)
    state_file = cfg.base.resolve(cfg.base.priv_validator_state_file)
    if os.path.exists(state_file):
        os.remove(state_file)
        print(f"Reset private validator state {state_file}")
    if os.path.exists(key_file):
        print(f"Private validator key intact at {key_file}")
    else:
        FilePV.generate(key_file, state_file)
        print(f"Generated private validator key {key_file}")
    return 0


def cmd_signer(args) -> int:
    """Remote-signer sidecar (the tmkms role; reference privval/
    signer_server.go + SignerDialerEndpoint): load this home's file
    key and DIAL the validator node's priv_validator_laddr, answering
    sign requests. Reconnects forever — the signer outliving node
    restarts is the point of running it out of process."""
    import asyncio as _asyncio

    from ..libs.net import split_laddr
    from ..p2p.key import NodeKey
    from ..privval import FilePV
    from ..privval.signer import SignerServer
    from ..types.genesis import GenesisDoc

    cfg = _load_config(args.home)
    pv = FilePV.load_or_generate(
        cfg.base.resolve(cfg.base.priv_validator_key_file),
        cfg.base.resolve(cfg.base.priv_validator_state_file))
    chain_id = args.chain_id
    if not chain_id:
        chain_id = GenesisDoc.load(
            cfg.base.resolve(cfg.base.genesis_file)).chain_id
    host, port = split_laddr(args.connect, default_host="127.0.0.1")
    # SecretConnection identity for the link (matches the node side,
    # which keys the handshake on ITS node key): never plaintext TCP.
    conn_key = NodeKey.load_or_gen(
        cfg.base.resolve(cfg.base.node_key_file)).priv_key
    server = SignerServer(pv, chain_id, conn_key=conn_key)
    print(f"signer for validator "
          f"{pv.get_pub_key().address().hex()[:12]}… dialing "
          f"{host}:{port}", flush=True)
    # operators copy this into the node's priv_validator_signer_id to
    # pin the link (required when the laddr is not loopback-only)
    print(f"signer link id: "
          f"{conn_key.pub_key().address().hex()}", flush=True)
    try:
        _asyncio.run(server.dial_and_serve(
            host, port, retries=None, retry_delay=1.0,
            on_event=lambda msg: print(msg, flush=True)))
    except KeyboardInterrupt:
        pass
    return 0


def cmd_gen_validator(args) -> int:
    from ..privval import FilePV

    pv = FilePV.generate()
    print(json.dumps({
        "priv_key": pv.priv_key.bytes().hex(),
        "pub_key": pv.get_pub_key().bytes().hex(),
        "address": pv.get_pub_key().address().hex().upper(),
    }, indent=2))
    return 0


def cmd_show_validator(args) -> int:
    cfg = _load_config(args.home)
    from ..privval import FilePV

    pv = FilePV.load(cfg.base.resolve(cfg.base.priv_validator_key_file),
                     cfg.base.resolve(cfg.base.priv_validator_state_file))
    print(json.dumps({"type": "ed25519",
                      "value": pv.get_pub_key().bytes().hex()}))
    return 0


def cmd_gen_node_key(args) -> int:
    from ..p2p.key import NodeKey

    cfg = _load_config(args.home)
    path = cfg.base.resolve(cfg.base.node_key_file)
    if os.path.exists(path):
        print(f"node key already exists at {path}", file=sys.stderr)
        return 1
    nk = NodeKey.generate()
    nk.save(path)
    print(nk.id)
    return 0


def cmd_show_node_id(args) -> int:
    from ..p2p.key import NodeKey

    cfg = _load_config(args.home)
    nk = NodeKey.load(cfg.base.resolve(cfg.base.node_key_file))
    print(nk.id)
    return 0


def cmd_probe_upnp(args) -> int:
    """reference: cmd/tendermint/commands/probe_upnp.go."""
    from ..p2p.upnp import UPnPError, discover

    async def go() -> int:
        try:
            igd = await discover(timeout=args.timeout)
        except UPnPError as e:
            print(json.dumps({"success": False, "error": str(e)}))
            return 1
        out = {"success": True, "control_url": igd.control_url,
               "local_ip": igd.local_ip}
        try:
            out["external_ip"] = igd.external_ip()
        except UPnPError as e:
            out["external_ip_error"] = str(e)
        print(json.dumps(out))
        return 0

    return asyncio.run(go())


def cmd_version(args) -> int:
    print(VERSION)
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tendermint-tpu",
                                description=__doc__)
    p.add_argument("--home", default=os.path.expanduser("~/.tendermint_tpu"))
    _sub = p.add_subparsers(dest="command")

    # --home works in BOTH positions (`--home H start` and
    # `start --home H`), like cobra persistent flags: every subparser
    # inherits it via a parent with SUPPRESS so an omitted
    # subcommand-level flag never clobbers the top-level value.
    _home_parent = argparse.ArgumentParser(add_help=False)
    _home_parent.add_argument("--home", default=argparse.SUPPRESS)

    class _Sub:
        def add_parser(self, name, **kw):
            # fresh list: never mutate a caller-shared parents list
            kw["parents"] = [*kw.get("parents", []), _home_parent]
            return _sub.add_parser(name, **kw)

    sub = _Sub()

    sp = sub.add_parser("init", help="initialize a home directory")
    sp.add_argument("--chain-id", default="")
    sp.set_defaults(fn=cmd_init)

    sp = sub.add_parser("start", help="run a node")
    sp.add_argument("--proxy_app", default="")
    sp.add_argument("--p2p.laddr", dest="p2p_laddr", default="")
    sp.add_argument("--rpc.laddr", dest="rpc_laddr", default="")
    sp.add_argument("--p2p.persistent_peers", dest="persistent_peers",
                    default="")
    sp.add_argument("--fast_sync", choices=("true", "false"), default=None)
    sp.add_argument("--log_level", default="info")
    sp.add_argument("--misbehavior", default="",
                    help="maverick mode: NAME@HEIGHT[,NAME@HEIGHT...] "
                         "(e.g. double-prevote@3)")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("testnet", help="generate a local testnet")
    sp.add_argument("--v", type=int, default=4)
    sp.add_argument("--o", default="./mytestnet")
    sp.add_argument("--chain-id", default="")
    sp.add_argument("--starting-port", type=int, default=26656)
    sp.set_defaults(fn=cmd_testnet)

    sp = sub.add_parser("light", help="run a verifying light client")
    sp.add_argument("chain_id")
    sp.add_argument("--primary", required=True, help="host:rpc-port")
    sp.add_argument("--witnesses", default="")
    sp.add_argument("--trust-height", type=int, required=True)
    sp.add_argument("--trust-hash", required=True)
    sp.add_argument("--trust-period", type=int, default=168 * 3600)
    sp.add_argument("--store", default="")
    sp.add_argument("--interval", type=float, default=1.0)
    sp.add_argument("--once", action="store_true")
    sp.add_argument("--laddr", default="",
                    help="host:port to serve verified RPC (light proxy)")
    sp.set_defaults(fn=cmd_light)

    sp = sub.add_parser("replay", help="replay the consensus WAL")
    sp.set_defaults(fn=cmd_replay)

    sp = sub.add_parser("replay-console",
                        help="step through the consensus WAL "
                             "interactively (read-only)")
    sp.set_defaults(fn=cmd_replay_console)

    sp = sub.add_parser("unsafe-reset-all",
                        help="wipe data and addrbook, keep keys "
                             "and config")
    sp.add_argument("--keep-addr-book", action="store_true",
                    help="keep the address book intact")
    sp.set_defaults(fn=cmd_unsafe_reset_all)

    sp = sub.add_parser("unsafe-reset-priv-validator",
                        help="reset only this node's validator to "
                             "genesis state (wipes last-sign state)")
    sp.set_defaults(fn=cmd_unsafe_reset_priv_validator)

    sp = sub.add_parser("signer",
                        help="remote-signer sidecar: dial a "
                             "validator's priv_validator_laddr and "
                             "answer sign requests with this home's "
                             "file key")
    sp.add_argument("--connect", required=True,
                    help="validator's priv_validator_laddr, e.g. "
                         "tcp://127.0.0.1:26659")
    sp.add_argument("--chain-id", default="",
                    help="chain id (default: from this home's genesis)")
    sp.set_defaults(fn=cmd_signer)

    from .debug import register as register_debug

    register_debug(sub)

    sp = sub.add_parser("probe-upnp",
                        help="probe for a UPnP internet gateway")
    sp.add_argument("--timeout", type=float, default=3.0)
    sp.set_defaults(fn=cmd_probe_upnp)

    sub.add_parser("gen-validator").set_defaults(fn=cmd_gen_validator)
    sub.add_parser("show-validator").set_defaults(fn=cmd_show_validator)
    sub.add_parser("gen-node-key").set_defaults(fn=cmd_gen_node_key)
    sub.add_parser("show-node-id").set_defaults(fn=cmd_show_node_id)
    sub.add_parser("version").set_defaults(fn=cmd_version)
    return p


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if not getattr(args, "fn", None):
        parser.print_help()
        return 1
    return args.fn(args)
