"""Height forensics: cross-node timeline reconstruction + per-height
critical-path attribution from span-tracer rings.

The span tracer (libs/tracing.py) answers "where did time go in THIS
process"; this module answers the fleet question — for a committed
height, how did the wall time split across

    propose   proposer's block build (height start → propose-step end
              on the node whose propose span carries proposer=True)
    gossip    block-part dissemination (build done → a quorum of
              validators holds the full part set)
    verify    vote pipeline (quorum part-complete → the precommit
              quorum landing on a quorum of validators)
    commit    finalize (precommit quorum → a quorum done with the
              COMMIT step)

and which node each boundary waited on. Stage boundaries come from
attrs the consensus state machine stamps on its height/step spans
(consensus/state.py: proposer, parts_complete_ns, precommit_quorum_ns)
— no span joins, no new hot-path span sites.

Inputs are per-node span views. In-process nets (tests, sim) read the
shared TRACER ring directly via `from_ring`; subprocess nets go through
tools/height_forensics.py, which pulls GET /debug/trace?height=H per
node and maps each node's perf_counter clock onto a shared wall axis
using the /debug/trace/anchor offset (`from_chrome` + `offset_ns`).

Quorum semantics follow the consensus rule: q = 2n//3 + 1 of the
height's participating nodes. Boundaries take the q-th smallest
timestamp — the node supplying it is the straggler that gated the
quorum, and it gets the blame for the stage.

Output is the TIMELINE dict (one JSON line per height when serialized):

    {"height": H, "round": R, "wall_ms": ..., "proposer": "val0",
     "quorum": 3, "nodes": ["val0", ...],
     "stages": {"propose": {"ms": ..., "node": "val0"}, ...},
     "coverage": 0.97,
     "blame": {"stage": "gossip", "node": "val2", "ms": ...}}

coverage = sum(stage ms)/wall: < 0.9 means an anchor was missing
(node restarted mid-height, ring overflowed...) and the line must not
be read as a complete attribution.
"""

from __future__ import annotations

STAGES = ("propose", "gossip", "verify", "commit")


class NodeView:
    """One node's spans for one height, on a common clock: boundary
    timestamps in ns (None when the anchor is missing)."""

    __slots__ = ("node", "height", "round", "height_t0", "proposer",
                 "propose_end", "parts_complete", "precommit_quorum",
                 "commit_end", "origin_nodes")

    def __init__(self, node: str, height: int):
        self.node = node
        self.height = height
        self.round = 0
        self.height_t0 = None
        self.proposer = False
        self.propose_end = None
        self.parts_complete = None
        self.precommit_quorum = None
        self.commit_end = None
        self.origin_nodes: set[str] = set()


def from_ring(records, height: int,
              node: str | None = None) -> dict[str, NodeView]:
    """Build per-node views for `height` from tracer snapshot()
    tuples (kind, span_id, parent_id, tid, t0_ns, dur_ns, attrs).
    In-process nets interleave every node's spans in ONE ring; the
    node= attr (ConsensusState.trace_node) demultiplexes them. `node`
    overrides attribution for single-node rings without the attr."""
    views: dict[str, NodeView] = {}

    def view(label: str) -> NodeView:
        if label not in views:
            views[label] = NodeView(label, height)
        return views[label]

    for kind, _sid, _pid, _tid, t0, dur, attrs in records:
        a = attrs or {}
        if a.get("height") != height:
            continue
        label = node or a.get("node")
        if not label:
            continue
        v = view(label)
        if kind == "consensus.height":
            v.height_t0 = t0
            if "parts_complete_ns" in a:
                v.parts_complete = a["parts_complete_ns"]
            if "precommit_quorum_ns" in a:
                v.precommit_quorum = a["precommit_quorum_ns"]
        elif kind == "consensus.propose":
            if a.get("proposer"):
                v.proposer = True
                v.propose_end = t0 + dur
                v.round = max(v.round, a.get("round", 0))
        elif kind == "consensus.commit":
            end = t0 + dur
            if v.commit_end is None or end > v.commit_end:
                v.commit_end = end
            v.round = max(v.round, a.get("round", 0))
        if "origin_node" in a:
            v.origin_nodes.add(a["origin_node"])
    return views


def from_chrome(doc: dict, height: int, node: str,
                offset_ns: int = 0) -> dict[str, NodeView]:
    """Build views from a /debug/trace?height=H chrome_trace export of
    ONE node's ring. `offset_ns` (wall_ns - mono_ns from the node's
    /debug/trace/anchor) shifts its perf_counter timestamps onto the
    shared wall axis; ts/dur are µs in the export."""
    records = []
    for ev in doc.get("traceEvents", []):
        args = dict(ev.get("args") or {})
        sid = args.pop("span_id", 0)
        pid = args.pop("parent_id", 0)
        records.append((
            ev["name"], sid, pid, ev.get("tid", 0),
            int(ev["ts"] * 1e3) + offset_ns, int(ev["dur"] * 1e3),
            args,
        ))
    # anchor attrs are perf_counter ns too: shift them the same way
    views = from_ring(records, height, node=node)
    if offset_ns:
        for v in views.values():
            if v.parts_complete is not None:
                v.parts_complete += offset_ns
            if v.precommit_quorum is not None:
                v.precommit_quorum += offset_ns
    return views


def _quorum_nth(pairs, q):
    """(timestamp, node) of the q-th smallest defined timestamp, or
    (None, None) when fewer than q nodes have it."""
    have = sorted((t, n) for n, t in pairs if t is not None)
    if len(have) < q:
        return None, None
    return have[q - 1]


def build_timeline(views: dict[str, NodeView],
                   height: int) -> dict | None:
    """The TIMELINE dict for one height, or None when the views cannot
    support one (no proposer span, no quorum of commit ends)."""
    if not views:
        return None
    nodes = sorted(views)
    n = len(nodes)
    q = (2 * n) // 3 + 1

    proposers = [v for v in views.values() if v.proposer]
    if not proposers:
        return None
    # re-proposals: the last round's proposer owns the commit path
    prop = max(proposers, key=lambda v: v.round)
    t_start = prop.height_t0
    t_build = prop.propose_end

    t_gossip, n_gossip = _quorum_nth(
        ((v.node, v.parts_complete) for v in views.values()), q)
    t_verify, n_verify = _quorum_nth(
        ((v.node, v.precommit_quorum) for v in views.values()), q)
    t_commit, n_commit = _quorum_nth(
        ((v.node, v.commit_end) for v in views.values()), q)
    if t_start is None or t_commit is None:
        return None

    # Clamp each boundary monotonic (running max): an anchor can land
    # marginally before the previous boundary on a racing net; a
    # negative stage would be nonsense, 0 ms is the honest reading.
    bounds = [t_start]
    stage_nodes = [prop.node, n_gossip, n_verify, n_commit]
    for t in (t_build, t_gossip, t_verify, t_commit):
        bounds.append(max(bounds[-1], t) if t is not None else None)

    wall_ms = (t_commit - t_start) / 1e6
    stages = {}
    prev = bounds[0]
    covered = 0.0
    for name, bound, who in zip(STAGES, bounds[1:], stage_nodes):
        if bound is None or prev is None:
            stages[name] = {"ms": None, "node": who}
            prev = bound if bound is not None else prev
            continue
        ms = (bound - prev) / 1e6
        stages[name] = {"ms": round(ms, 3), "node": who}
        covered += ms
        prev = bound

    blame = None
    attributed = [(s, d) for s, d in stages.items() if d["ms"] is not None]
    if attributed:
        bs, bd = max(attributed, key=lambda kv: kv[1]["ms"])
        blame = {"stage": bs, "node": bd["node"], "ms": bd["ms"]}

    return {
        "height": height,
        "round": prop.round,
        "wall_ms": round(wall_ms, 3),
        "proposer": prop.node,
        "quorum": q,
        "nodes": nodes,
        "stages": stages,
        "coverage": round(covered / wall_ms, 4) if wall_ms > 0 else 0.0,
        "blame": blame,
    }


def timeline_from_ring(records, height: int) -> dict | None:
    """One-call form for in-process nets: snapshot() tuples in, the
    TIMELINE dict out."""
    return build_timeline(from_ring(records, height), height)


def committed_heights(records) -> list[int]:
    """Heights with at least one finished consensus.commit span in the
    records — the candidates timeline_from_ring can attribute."""
    hs = {r[6]["height"] for r in records
          if r[0] == "consensus.commit" and r[6] and "height" in r[6]}
    return sorted(hs)


def orphan_origins(records, known_nodes) -> list[str]:
    """origin_node values rehydrated into recv spans that name a node
    outside `known_nodes` — non-empty means a stamp/label mismatch
    (the cross-node link would dangle). The tier-1 4-net test pins
    this empty."""
    known = set(known_nodes)
    bad = []
    for r in records:
        a = r[6] or {}
        o = a.get("origin_node")
        if o and o not in known:
            bad.append(o)
    return sorted(set(bad))


def timeline_summary(timelines) -> dict:
    """Run-level rollup over TIMELINE dicts: per-stage p50/p99 ms,
    wall p50/p99, and a blame histogram — the payload bench.py / the
    e2e runner embed in their reports."""
    tls = [t for t in timelines if t]
    if not tls:
        return {"heights": 0}

    def pcts(vals):
        vals = sorted(vals)
        n = len(vals)

        def pct(p):
            return round(vals[min(n - 1, int(p * n))], 3)

        return {"p50_ms": pct(0.50), "p99_ms": pct(0.99)}

    out = {"heights": len(tls),
           "wall": pcts([t["wall_ms"] for t in tls]),
           "coverage_min": min(t["coverage"] for t in tls),
           "stages": {}, "blame": {}}
    for s in STAGES:
        vals = [t["stages"][s]["ms"] for t in tls
                if t["stages"][s]["ms"] is not None]
        if vals:
            out["stages"][s] = pcts(vals)
    for t in tls:
        if t["blame"]:
            key = t["blame"]["stage"]
            out["blame"][key] = out["blame"].get(key, 0) + 1
    return out


def timeline_fingerprint(timelines) -> list[tuple]:
    """The deterministic projection of a timeline run: stage DURATIONS
    are wall-clock (perf_counter) and vary run to run even under the
    sim's virtual clock, but WHICH heights committed, who proposed
    them, and which stages got attributed are seed-determined. The
    sim determinism pin compares this."""
    fp = []
    for t in timelines:
        if not t:
            continue
        fp.append((t["height"], t["round"], t["proposer"],
                   tuple(s for s in STAGES
                         if t["stages"][s]["ms"] is not None)))
    return fp
