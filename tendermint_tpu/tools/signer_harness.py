"""Remote-signer conformance harness (reference:
tools/tm-signer-harness/internal/test_harness.go).

Acts as the NODE side of the privval socket protocol: listens for a
remote signer to dial in, then runs the conformance suite —

  1. TestPublicKey    signer's key matches the expected one (from a
                      priv_validator_key.json or genesis doc)
  2. TestSignProposal signs a proposal; the signature verifies against
                      the advertised key over canonical sign bytes
  3. TestSignVote     prevote + precommit at increasing HRS, each
                      verifying; then a conflicting re-sign at the SAME
                      HRS with a different block MUST be refused
                      (double-sign protection — the harness's whole
                      point: a signer that resigns conflicting votes is
                      unsafe to deploy)

Exit codes mirror the reference: 0 ok; 1 setup/connect failure;
2 public-key mismatch; 3 proposal failure; 4 vote failure;
5 double-sign accepted.

Usage:
    python -m tendermint_tpu.tools.signer_harness \
        --laddr 127.0.0.1:28859 --chain-id my-chain \
        [--expected-key <hex pubkey | path to priv_validator_key.json>]

then point the signer at it, e.g.:
    python -c "... serve SignerServer dialing 127.0.0.1:28859 ..."
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

from ..privval.signer import RemoteSignError, SignerClient
from ..types.block import BlockID, PartSetHeader
from ..types.proposal import Proposal
from ..types.vote import Vote, VoteType


class HarnessFailure(Exception):
    def __init__(self, code: int, msg: str):
        super().__init__(msg)
        self.code = code


def _load_expected_key(spec: str) -> bytes | None:
    if not spec:
        return None
    if os.path.exists(spec):
        d = json.load(open(spec))
        return bytes.fromhex(d["pub_key"])
    return bytes.fromhex(spec)


async def run_harness(laddr: str, chain_id: str,
                      expected_key: bytes | None = None,
                      timeout: float = 30.0, log=print) -> int:
    host, _, port = laddr.partition(":")
    client = SignerClient(chain_id, timeout=timeout)
    try:
        actual_port = await client.listen(host or "127.0.0.1",
                                          int(port or 0))
        log(f"harness listening on {host}:{actual_port}; waiting for "
            f"the signer to dial in...")
        await client.wait_connected()
    except Exception as e:
        raise HarnessFailure(1, f"signer never connected: {e!r}") from e

    try:
        # 1. TestPublicKey
        pub = client.get_pub_key()
        log(f"signer public key: {pub.bytes().hex()}")
        if expected_key is not None and pub.bytes() != expected_key:
            raise HarnessFailure(
                2, f"public key mismatch: signer has "
                   f"{pub.bytes().hex()}, expected {expected_key.hex()}")
        log("TestPublicKey: OK")

        now = time.time_ns()
        bid = BlockID(b"\xab" * 32, PartSetHeader(1, b"\xcd" * 32))

        # 2. TestSignProposal
        prop = Proposal(height=1, round=0, pol_round=-1, block_id=bid,
                        timestamp=now)
        await client.sign_proposal(chain_id, prop)
        if not pub.verify_signature(prop.sign_bytes(chain_id),
                                    prop.signature):
            raise HarnessFailure(3, "proposal signature does not verify")
        log("TestSignProposal: OK")

        # 3. TestSignVote — prevote then precommit, then double-sign.
        addr = pub.address()
        for vt, name in ((VoteType.PREVOTE, "prevote"),
                         (VoteType.PRECOMMIT, "precommit")):
            vote = Vote(type=vt, height=2, round=0, block_id=bid,
                        timestamp=now, validator_address=addr,
                        validator_index=0)
            await client.sign_vote(chain_id, vote)
            if not pub.verify_signature(vote.sign_bytes(chain_id),
                                        vote.signature):
                raise HarnessFailure(
                    4, f"{name} signature does not verify")
            log(f"TestSignVote({name}): OK")

        # conflicting precommit at the SAME h/r for a DIFFERENT block
        evil_bid = BlockID(b"\xee" * 32, PartSetHeader(1, b"\xcd" * 32))
        evil = Vote(type=VoteType.PRECOMMIT, height=2, round=0,
                    block_id=evil_bid, timestamp=now + 1,
                    validator_address=addr, validator_index=0)
        try:
            await client.sign_vote(chain_id, evil)
        except RemoteSignError:
            log("TestDoubleSignRefused: OK")
        else:
            raise HarnessFailure(
                5, "signer RE-SIGNED a conflicting precommit at the "
                   "same height/round — double-sign protection absent")
        log("all conformance tests passed")
        return 0
    finally:
        client.close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tm-signer-harness",
                                description=__doc__)
    p.add_argument("--laddr", default="127.0.0.1:28859")
    p.add_argument("--chain-id", required=True)
    p.add_argument("--expected-key", default="",
                   help="hex pubkey or priv_validator_key.json path")
    p.add_argument("--timeout", type=float, default=30.0)
    args = p.parse_args(argv)
    try:
        return asyncio.run(run_harness(
            args.laddr, args.chain_id,
            _load_expected_key(args.expected_key),
            timeout=args.timeout))
    except HarnessFailure as e:
        print(f"FAILED ({e.code}): {e}", file=sys.stderr)
        return e.code


if __name__ == "__main__":
    sys.exit(main())
