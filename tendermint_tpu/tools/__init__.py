"""Operator tools (reference: tools/ — tm-signer-harness etc.)."""
