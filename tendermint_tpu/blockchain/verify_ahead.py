"""Fast-sync window verification + the verify-ahead pipeline.

The cross-block batch verification the reactor's hot loop runs
(`_batch_verify_window`: up to BATCH_WINDOW commits in one device
launch, SURVEY §3.5) plus the overlap engine that takes it off the
apply path: while window W's blocks execute through `apply_block`,
window W+1's signature batch — its verdicts fully determined by the
already-buffered blocks — runs concurrently in an executor thread
(`WindowPipeline`). Steady-state catch-up then pays
max(verify, apply) per window instead of their sum.

Deliberately p2p-free (the reactor imports this module, not the other
way around): the pipeline is pure verification scheduling over
buffered blocks, so it unit-tests — and benches — without a Switch,
sockets, or the cryptography package the secret-connection layer
needs. Correctness does not move: verdicts are computed by the same
`_batch_verify_window` either way, the consumer awaits them before
applying, and a prefetched window is keyed on (valset hash, heights,
commit identities) so a validator-set change or a re-fetched block
discards the stale verdicts instead of trusting them.
"""

from __future__ import annotations

import asyncio
import logging

from ..libs import tracing
from ..types.block import BlockID
from ..types.validator_set import VerificationError

logger = logging.getLogger("blockchain")

BATCH_WINDOW = 16                 # blocks per device verification batch


def _batch_verify_window(vals, chain_id: str, items):
    """Verify the commits of several consecutive blocks — all signed by
    the SAME validator set — in one device batch. `items` is a list of
    (block_id, height, commit). Returns a list of per-block Exception
    or None, mirroring VerifyCommitLight's accept/reject per block
    (reference types/validator_set.go:720, batched across blocks).

    Large all-ed25519 sets go through the expanded comb tables with
    STRUCTURED sign bytes (one template group per block's commit,
    types/sign_batch.py MergedSignBatch) — the same valset verifies
    every block of the window AND every window of the catch-up, which
    is exactly the workload the device-resident tables exist for.
    Everything else (or any structural/device failure) falls back to
    the general BatchVerifier with full bytes."""
    spans: list = []
    results: list = [None] * len(items)
    lanes_all: list[int] = []
    sigs_all: list[bytes] = []
    per_commit: list[tuple] = []  # (commit, slots) per verifiable block
    for i, (bid, height, commit) in enumerate(items):
        start = len(lanes_all)
        try:
            vals._check_commit_basics(bid, height, commit)
            need = 2 * vals.total_voting_power()
            tallied = 0
            slots: list[int] = []
            for idx, cs in enumerate(commit.signatures):
                if not cs.for_block():
                    continue
                val = vals.validators[idx]
                lanes_all.append(idx)
                slots.append(idx)
                sigs_all.append(cs.signature)
                tallied += val.voting_power
                if 3 * tallied > need:
                    break
            if 3 * tallied <= need:
                raise VerificationError(
                    f"insufficient voting power at height {height}")
            spans.append((i, start, len(lanes_all)))
            per_commit.append((commit, slots))
        except Exception as e:
            results[i] = e
            # roll back this block's lanes
            del lanes_all[start:]
            del sigs_all[start:]
    if not lanes_all:
        return results

    verdicts = _window_lane_verdicts(
        vals, chain_id, lanes_all, sigs_all, per_commit)
    for i, start, end in spans:
        if not bool(verdicts[start:end].all()):
            results[i] = VerificationError(
                f"invalid commit signature(s) for height "
                f"{items[i][1]}")
    return results


def _window_lane_verdicts(vals, chain_id, lanes_all, sigs_all, per_commit):
    """Per-lane verdicts for a window's collected lanes.

    Builds the merged structured batch (one template group per
    block's commit) when the expanded device path will consume it and
    the commits' values fit the vectorized layout — hostile values
    (e.g. a timestamp past int64) get full bytes instead, WITHOUT
    tripping the device-failure cooldown, mirroring
    ValidatorSet._commit_msgs. The verify ladder itself (structured →
    bytes → host, device-failure degradation, logging) is owned by
    ValidatorSet._batch_verify_lanes — one copy for every call site."""
    from ..types.sign_batch import CommitSignBatch, MergedSignBatch

    msgs = vals.structured_or_bytes(
        lanes_all,
        lambda: MergedSignBatch([
            CommitSignBatch(chain_id, c, slots)
            for c, slots in per_commit
        ]),
        lambda: [c.vote_sign_bytes(chain_id, s)
                 for c, slots in per_commit for s in slots],
    )
    from ..crypto.tpu import ledger as tpu_ledger

    with tpu_ledger.workload("fastsync"):
        _, verdicts = vals._batch_verify_lanes(lanes_all, msgs,
                                               sigs_all)
    return verdicts


def window_items(blocks) -> tuple[list[tuple], list]:
    """((block_id, height, commit) per verifiable block, the built
    PartSet per block) of a peeked window: block i is verified with
    block i+1's LastCommit. The part sets ride along so the apply loop
    reuses them for save_block — make_part_set is a full-block
    serialization and must run ONCE per block, in the executor."""
    items, parts_list = [], []
    for i in range(len(blocks) - 1):
        first, second = blocks[i], blocks[i + 1]
        parts = first.make_part_set()
        bid = BlockID(first.hash(), parts.header())
        items.append((bid, first.header.height, second.last_commit))
        parts_list.append(parts)
    return items, parts_list


class WindowPipeline:
    """The verify-ahead engine one fast-sync reactor owns: hands out a
    window's verdicts (from a matching in-flight prefetch when one
    exists) and launches the NEXT window's verification concurrently
    with whatever the caller does next (executing the current window's
    blocks). Persistence order is untouched — this schedules the same
    verification earlier, nothing else."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._prefetch: tuple | None = None  # (key, future, blocks)
        self.prefetch_hits = 0

    @staticmethod
    def window_key(vals, blocks) -> tuple:
        """Identity of a verification window, computed from the RAW
        blocks (never via window_items — that serializes every block
        into a part set, far too heavy for an on-loop key probe): the
        valset it verified against plus the exact block/commit objects
        consumed. Object identity is safe because the prefetch entry
        itself holds the blocks, so ids cannot be recycled while it is
        alive."""
        return (vals.hash(),
                tuple(b.header.height for b in blocks[:-1]),
                tuple(id(b) for b in blocks[:-1]),
                tuple(id(b.last_commit) for b in blocks[1:]))

    def reset(self) -> None:
        """Pool replaced (statesync handoff etc.): any in-flight
        prefetch is over stale blocks."""
        self._prefetch = None

    @staticmethod
    def _verify_window_job(vals, chain_id, blocks):
        """The executor-side unit: build the window's items + part
        sets (the make_part_set serialization per block lives HERE,
        off the event loop) and batch-verify. Returns (items,
        parts_list, results) so the consumer — prefetch hit or not —
        reuses both instead of re-serializing the window."""
        items, parts_list = window_items(blocks)
        return (items, parts_list,
                _batch_verify_window(vals, chain_id, items))

    @staticmethod
    def _retrieve_stale(fut) -> None:
        """Done-callback for a DISCARDED prefetch (valset change /
        re-fetched window): retrieve + log its exception so a failed
        job neither vanishes silently nor leaves 'exception was never
        retrieved' noise at GC (the PR-7 singleflight convention)."""
        exc = fut.exception() if not fut.cancelled() else None
        if exc is not None:
            logger.warning("discarded verify-ahead window failed: %r",
                           exc)

    async def verdicts(self, vals, chain_id, blocks):
        """This window's (items, part sets, per-block verdicts):
        consumed from a matching prefetch when one is in flight, else
        verified now — item/part-set building AND the device batch run
        in an executor thread either way, so neither freezes the event
        loop (gossip/timeouts keep running)."""
        key = self.window_key(vals, blocks)
        pf, self._prefetch = self._prefetch, None
        if pf is not None and pf[0] == key:
            self.prefetch_hits += 1
            return await pf[1]
        if pf is not None:
            # stale (valset changed / window shifted): discarded, but
            # never silently — see _retrieve_stale
            pf[1].add_done_callback(self._retrieve_stale)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, tracing.TRACER.wrap(self._verify_window_job),
            vals, chain_id, blocks)

    def start_ahead(self, vals, chain_id, peek, skip: int) -> None:
        """Launch the NEXT window's commit verification concurrently
        with the apply loop about to run: `peek(n)` returns up to n
        contiguous buffered blocks, `skip` is the length of the window
        just verified (its last block is the next window's first)."""
        if not self.enabled or self._prefetch is not None:
            return
        ahead = peek(skip - 1 + BATCH_WINDOW + 1)
        nxt = ahead[skip - 1:]
        if len(nxt) < 2:
            return
        key = self.window_key(vals, nxt)
        fut = asyncio.get_running_loop().run_in_executor(
            None, tracing.TRACER.wrap(self._verify_window_job),
            vals, chain_id, nxt)
        self._prefetch = (key, fut, nxt)
