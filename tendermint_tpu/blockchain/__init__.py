"""Fast sync: catch up to the chain head by downloading committed
blocks from peers in parallel and applying them without running
consensus (reference: blockchain/ — v0 pool design with v2's
deterministic, IO-free core for testability).

The TPU twist (SURVEY §3.5): block verification during catch-up is the
hottest loop — one VerifyCommitLight per block. Here contiguous runs
of fetched blocks are verified as ONE signature batch across blocks
(`reactor.BlockchainReactor._try_sync`), which is where the
sub-100ms-per-block headline number comes from.
"""

from .msgs import (
    BlockRequestMessage,
    BlockResponseMessage,
    NoBlockResponseMessage,
    StatusRequestMessage,
    StatusResponseMessage,
    decode_bc_msg,
    encode_bc_msg,
)
from .pool import BlockPool


def __getattr__(name: str):
    # The reactor is the only submodule that pulls in the p2p stack
    # (and its optional `cryptography` dependency); loading it lazily
    # keeps the pure core (pool, messages, the verify_ahead window
    # pipeline) — and its unit tests/benches — importable without
    # transport deps, same pattern as statesync/__init__.py.
    if name in ("BlockchainReactor", "BLOCKCHAIN_CHANNEL"):
        from . import reactor

        return getattr(reactor, name)
    raise AttributeError(name)


__all__ = [
    "BlockPool", "BlockchainReactor", "BLOCKCHAIN_CHANNEL",
    "StatusRequestMessage", "StatusResponseMessage", "BlockRequestMessage",
    "BlockResponseMessage", "NoBlockResponseMessage",
    "encode_bc_msg", "decode_bc_msg",
]
