"""Fast-sync wire messages (reference: blockchain/msgs.go).

Same tag+protobuf framing as the consensus codec; blocks ride their
canonical proto encoding."""

from __future__ import annotations

from dataclasses import dataclass

from ..encoding.proto import Reader, Writer
from ..types.block import Block

MAX_MSG_SIZE = 10_485_760 + 1024  # reference bcBlockResponseMessagePrefixSize


@dataclass
class BlockRequestMessage:
    height: int


@dataclass
class BlockResponseMessage:
    block: Block


@dataclass
class NoBlockResponseMessage:
    height: int


@dataclass
class StatusRequestMessage:
    pass


@dataclass
class StatusResponseMessage:
    height: int
    base: int


_TAG = {
    BlockRequestMessage: 1,
    BlockResponseMessage: 2,
    NoBlockResponseMessage: 3,
    StatusRequestMessage: 4,
    StatusResponseMessage: 5,
}
_BY_TAG = {v: k for k, v in _TAG.items()}


def encode_bc_msg(msg) -> bytes:
    w = Writer()
    if isinstance(msg, (BlockRequestMessage, NoBlockResponseMessage)):
        w.varint(1, msg.height)
    elif isinstance(msg, BlockResponseMessage):
        w.bytes(1, msg.block.to_bytes())
    elif isinstance(msg, StatusResponseMessage):
        w.varint(1, msg.height)
        w.varint(2, msg.base)
    elif isinstance(msg, StatusRequestMessage):
        pass
    else:
        raise ValueError(f"unknown blockchain message {type(msg)}")
    return bytes([_TAG[type(msg)]]) + w.finish()


def decode_bc_msg(data: bytes):
    if not data:
        raise ValueError("empty blockchain message")
    if len(data) > MAX_MSG_SIZE:
        raise ValueError("blockchain message exceeds max size")
    cls = _BY_TAG.get(data[0])
    if cls is None:
        raise ValueError(f"unknown blockchain message tag {data[0]}")
    r = Reader(data[1:])
    if cls is StatusRequestMessage:
        return cls()
    if cls is BlockResponseMessage:
        block = None
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                block = Block.from_bytes(r.bytes())
            else:
                r.skip(wt)
        if block is None:
            raise ValueError("block response without block")
        return cls(block)
    if cls in (BlockRequestMessage, NoBlockResponseMessage):
        height = 0
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                height = r.varint()
            else:
                r.skip(wt)
        if height < 1:
            raise ValueError("invalid height")
        return cls(height)
    # StatusResponseMessage
    height = base = 0
    while not r.at_end():
        f, wt = r.field()
        if f == 1:
            height = r.varint()
        elif f == 2:
            base = r.varint()
        else:
            r.skip(wt)
    if height < 0 or base < 0 or base > height:
        raise ValueError("invalid status response")
    return cls(height, base)
