"""Block pool: tracks which peer owes us which height and buffers
fetched blocks until the reactor verifies+applies them in order
(reference: blockchain/v0/pool.go:69).

Redesign: the reference runs one goroutine per in-flight height; here
the pool is a PURE state machine — no tasks, no clocks of its own
(v2's testability lesson, blockchain/v2/scheduler.go). The reactor
calls `make_next_requests(now)` / `tick(now)` and performs the IO the
pool decides on. Determinism makes the catch-up path unit-testable
without sockets."""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

logger = logging.getLogger("blockchain.pool")

MAX_PENDING_REQUESTS = 600       # reference pool.go maxPendingRequests
MAX_PENDING_PER_PEER = 20        # reference maxPendingRequestsPerPeer
REQUEST_TIMEOUT = 15.0           # reference requestRetrySeconds-ish
MIN_RECV_RATE = 7680             # bytes/s, reference minRecvRate


@dataclass
class _Peer:
    id: str
    base: int = 0
    height: int = 0
    pending: set[int] = field(default_factory=set)
    bytes_received: int = 0
    first_request_at: float = 0.0


@dataclass
class _Request:
    height: int
    peer_id: str
    sent_at: float
    block: object | None = None


class BlockPool:
    """next height to fetch is `self.height`; blocks wait in
    `self.requests[h].block` until popped in order."""

    def __init__(self, start_height: int):
        self.height = start_height
        self.peers: dict[str, _Peer] = {}
        self.requests: dict[int, _Request] = {}
        self._banned: set[str] = set()
        # monotonic timestamp of the last height advance; fed by the
        # reactor's clock (None until the first tick) so the pool stays
        # clock-free and deterministic in tests
        self.last_advance: float | None = None

    # -- peers --

    def set_peer_range(self, peer_id: str, base: int, height: int) -> None:
        if peer_id in self._banned:
            return
        p = self.peers.get(peer_id)
        if p is None:
            p = _Peer(peer_id)
            self.peers[peer_id] = p
        if height < p.height:
            # peer shrank its chain: suspicious but tolerated (reference
            # allows lower StatusResponse after reorg-free guarantee)
            pass
        p.base, p.height = base, height

    def remove_peer(self, peer_id: str, ban: bool = False) -> list[int]:
        """Drop the peer; returns heights that must be re-requested."""
        p = self.peers.pop(peer_id, None)
        if ban:
            self._banned.add(peer_id)
        redo = []
        if p is not None:
            for h in p.pending:
                req = self.requests.get(h)
                if req is not None and req.peer_id == peer_id and \
                        req.block is None:
                    del self.requests[h]
                    redo.append(h)
        return redo

    def max_peer_height(self) -> int:
        return max((p.height for p in self.peers.values()), default=0)

    # -- request scheduling (pure; the reactor does the sends) --

    def make_next_requests(self, now: float) -> list[tuple[str, int]]:
        """Assign unrequested heights to available peers. Returns
        (peer_id, height) pairs for the reactor to send."""
        out: list[tuple[str, int]] = []
        h = self.height
        while len(self.requests) < MAX_PENDING_REQUESTS:
            while h in self.requests:
                h += 1
            peer = self._pick_peer(h)
            if peer is None:
                break
            self.requests[h] = _Request(h, peer.id, now)
            peer.pending.add(h)
            if not peer.first_request_at:
                peer.first_request_at = now
            out.append((peer.id, h))
            h += 1
        return out

    def _pick_peer(self, height: int) -> _Peer | None:
        best = None
        for p in self.peers.values():
            if len(p.pending) >= MAX_PENDING_PER_PEER:
                continue
            if not (p.base <= height <= p.height):
                continue
            if best is None or len(p.pending) < len(best.pending):
                best = p
        return best

    def tick(self, now: float) -> list[str]:
        """Expire timed-out requests; returns peer ids to drop
        (reference: requestRoutine timeout → RemovePeer)."""
        if self.last_advance is None:
            self.last_advance = now
        bad: set[str] = set()
        for req in list(self.requests.values()):
            if req.block is None and now - req.sent_at > REQUEST_TIMEOUT:
                bad.add(req.peer_id)
        # slow-peer detection (reference pool.go:139 minRecvRate)
        for p in self.peers.values():
            if p.pending and p.first_request_at and \
                    now - p.first_request_at > REQUEST_TIMEOUT:
                rate = p.bytes_received / (now - p.first_request_at)
                if rate < MIN_RECV_RATE and p.bytes_received > 0:
                    bad.add(p.id)
        return list(bad)

    # -- block ingestion --

    def add_block(self, peer_id: str, block, size: int) -> bool:
        """Accept a block only from the peer we asked (DoS guard,
        reference pool.go AddBlock)."""
        h = block.header.height
        req = self.requests.get(h)
        if req is None or req.peer_id != peer_id or req.block is not None:
            return False
        req.block = block
        p = self.peers.get(peer_id)
        if p is not None:
            p.pending.discard(h)
            p.bytes_received += size
        return True

    def no_block(self, peer_id: str, height: int) -> None:
        """Peer says it doesn't have the height: re-request elsewhere."""
        req = self.requests.get(height)
        if req is not None and req.peer_id == peer_id and req.block is None:
            del self.requests[height]
            p = self.peers.get(peer_id)
            if p is not None:
                p.pending.discard(height)
                # it lied about its range; shrink it
                if p.height >= height:
                    p.height = height - 1

    # -- ordered consumption --

    def peek_blocks(self, n: int = 2) -> list:
        """Up to n contiguous buffered blocks starting at self.height
        (reference PeekTwoBlocks generalized for cross-block batch
        verification)."""
        out = []
        for h in range(self.height, self.height + n):
            req = self.requests.get(h)
            if req is None or req.block is None:
                break
            out.append(req.block)
        return out

    def pop_request(self, now: float | None = None) -> None:
        req = self.requests.pop(self.height, None)
        assert req is not None and req.block is not None
        self.height += 1
        if now is not None:
            self.last_advance = now

    def redo_request(self, height: int) -> str:
        """Block at `height` failed verification: ban the peer that sent
        it (and anything else pending from it gets re-assigned)."""
        req = self.requests.get(height)
        if req is None:
            return ""
        peer_id = req.peer_id
        # drop every buffered block from the lying peer
        for h, r in list(self.requests.items()):
            if r.peer_id == peer_id:
                del self.requests[h]
        self.remove_peer(peer_id, ban=True)
        return peer_id

    def is_caught_up(self) -> bool:
        """reference pool.go IsCaughtUp: within 1 of the tallest peer
        (syncing H needs H+1 for the LastCommit, hence the -1)."""
        if not self.peers:
            return False
        return self.height >= self.max_peer_height() - 1
