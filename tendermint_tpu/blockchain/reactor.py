"""Fast-sync reactor (reference: blockchain/v0/reactor.go, channel
0x40): serves committed blocks to catching-up peers and, when started
in fast-sync mode, drives the BlockPool to download, verify and apply
blocks until caught up, then hands off to consensus
(SwitchToConsensus, reference v0/reactor.go poolRoutine).

TPU-first redesign of the hot loop: the reference verifies one commit
per block (`VerifyCommitLight`, sequential per-sig). Here a contiguous
window of fetched blocks is verified as ONE signature batch
(`_batch_verify_window`): up to BATCH_WINDOW commits go to the device
in a single launch, amortizing dispatch and filling the lanes (SURVEY
§3.5: batch across blocks, not just within a commit). Large valsets
ride the expanded comb tables with device-assembled STRUCTURED sign
bytes — one template group per block's commit — via
ValidatorSet._batch_verify_lanes."""

from __future__ import annotations

import asyncio
import logging
import time

from ..p2p.conn.connection import ChannelDescriptor
from ..p2p.switch import Reactor
from ..types.block import BlockID
from ..types.validator_set import VerificationError
from .msgs import (
    BlockRequestMessage,
    BlockResponseMessage,
    NoBlockResponseMessage,
    StatusRequestMessage,
    StatusResponseMessage,
    decode_bc_msg,
    encode_bc_msg,
)
from .pool import BlockPool

logger = logging.getLogger("blockchain")

BLOCKCHAIN_CHANNEL = 0x40

TRY_SYNC_INTERVAL = 0.01          # reference trySyncTicker (10ms)
STATUS_UPDATE_INTERVAL = 10.0     # reference statusUpdateTicker
SWITCH_TO_CONSENSUS_INTERVAL = 1.0
SYNC_TIMEOUT = 60.0               # reference syncTimeout: no progress →
                                  # give up waiting and run consensus
BATCH_WINDOW = 16                 # blocks per device verification batch


def _batch_verify_window(vals, chain_id: str, items):
    """Verify the commits of several consecutive blocks — all signed by
    the SAME validator set — in one device batch. `items` is a list of
    (block_id, height, commit). Returns a list of per-block Exception
    or None, mirroring VerifyCommitLight's accept/reject per block
    (reference types/validator_set.go:720, batched across blocks).

    Large all-ed25519 sets go through the expanded comb tables with
    STRUCTURED sign bytes (one template group per block's commit,
    types/sign_batch.py MergedSignBatch) — the same valset verifies
    every block of the window AND every window of the catch-up, which
    is exactly the workload the device-resident tables exist for.
    Everything else (or any structural/device failure) falls back to
    the general BatchVerifier with full bytes."""
    spans: list = []
    results: list = [None] * len(items)
    lanes_all: list[int] = []
    sigs_all: list[bytes] = []
    per_commit: list[tuple] = []  # (commit, slots) per verifiable block
    for i, (bid, height, commit) in enumerate(items):
        start = len(lanes_all)
        try:
            vals._check_commit_basics(bid, height, commit)
            need = 2 * vals.total_voting_power()
            tallied = 0
            slots: list[int] = []
            for idx, cs in enumerate(commit.signatures):
                if not cs.for_block():
                    continue
                val = vals.validators[idx]
                lanes_all.append(idx)
                slots.append(idx)
                sigs_all.append(cs.signature)
                tallied += val.voting_power
                if 3 * tallied > need:
                    break
            if 3 * tallied <= need:
                raise VerificationError(
                    f"insufficient voting power at height {height}")
            spans.append((i, start, len(lanes_all)))
            per_commit.append((commit, slots))
        except Exception as e:
            results[i] = e
            # roll back this block's lanes
            del lanes_all[start:]
            del sigs_all[start:]
    if not lanes_all:
        return results

    verdicts = _window_lane_verdicts(
        vals, chain_id, lanes_all, sigs_all, per_commit)
    for i, start, end in spans:
        if not bool(verdicts[start:end].all()):
            results[i] = VerificationError(
                f"invalid commit signature(s) for height "
                f"{items[i][1]}")
    return results


def _window_lane_verdicts(vals, chain_id, lanes_all, sigs_all, per_commit):
    """Per-lane verdicts for a window's collected lanes.

    Builds the merged structured batch (one template group per
    block's commit) when the expanded device path will consume it and
    the commits' values fit the vectorized layout — hostile values
    (e.g. a timestamp past int64) get full bytes instead, WITHOUT
    tripping the device-failure cooldown, mirroring
    ValidatorSet._commit_msgs. The verify ladder itself (structured →
    bytes → host, device-failure degradation, logging) is owned by
    ValidatorSet._batch_verify_lanes — one copy for every call site."""
    from ..types.sign_batch import CommitSignBatch, MergedSignBatch

    msgs = vals.structured_or_bytes(
        lanes_all,
        lambda: MergedSignBatch([
            CommitSignBatch(chain_id, c, slots)
            for c, slots in per_commit
        ]),
        lambda: [c.vote_sign_bytes(chain_id, s)
                 for c, slots in per_commit for s in slots],
    )
    _, verdicts = vals._batch_verify_lanes(lanes_all, msgs, sigs_all)
    return verdicts


class BlockchainReactor(Reactor):
    def __init__(self, state, block_exec, block_store,
                 fast_sync: bool, consensus_reactor=None):
        super().__init__("blockchain")
        self.state = state
        self.block_exec = block_exec
        self.block_store = block_store
        self.fast_sync = fast_sync
        self.consensus_reactor = consensus_reactor
        self.pool = BlockPool(block_store.height + 1
                              if block_store.height else
                              state.last_block_height + 1)
        self._task: asyncio.Task | None = None
        self.synced = asyncio.Event()
        if not fast_sync:
            self.synced.set()
        self.blocks_synced = 0

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(id=BLOCKCHAIN_CHANNEL, priority=10,
                                  send_queue_capacity=1000,
                                  recv_message_capacity=10_485_760 + 1024,
                                  name="blockchain")]

    async def start(self) -> None:
        if self.fast_sync and self._task is None:
            from ..libs.metrics import consensus_metrics

            consensus_metrics().fast_syncing.set(1)
            self._task = asyncio.get_running_loop().create_task(
                self._pool_routine(), name="blockchain-pool")

    async def switch_to_fast_sync(self, state) -> None:
        """Statesync → fastsync handoff (reference node.go:132)."""
        self.state = state
        self.fast_sync = True
        self.synced.clear()
        self.pool = BlockPool(state.last_block_height + 1)
        if self._task is not None and self._task.done():
            self._task = None
        await self.start()

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    # -- p2p --

    def _our_status(self) -> bytes:
        return encode_bc_msg(StatusResponseMessage(
            height=self.block_store.height, base=self.block_store.base))

    async def add_peer(self, peer) -> None:
        peer.try_send(BLOCKCHAIN_CHANNEL, self._our_status())

    async def remove_peer(self, peer, reason) -> None:
        self.pool.remove_peer(peer.id)

    async def receive(self, chan_id: int, peer, msgb: bytes) -> None:
        msg = decode_bc_msg(msgb)
        if isinstance(msg, BlockRequestMessage):
            block = self.block_store.load_block(msg.height)
            if block is not None:
                await peer.send(BLOCKCHAIN_CHANNEL, encode_bc_msg(
                    BlockResponseMessage(block)))
            else:
                await peer.send(BLOCKCHAIN_CHANNEL, encode_bc_msg(
                    NoBlockResponseMessage(msg.height)))
        elif isinstance(msg, StatusRequestMessage):
            peer.try_send(BLOCKCHAIN_CHANNEL, self._our_status())
        elif isinstance(msg, StatusResponseMessage):
            self.pool.set_peer_range(peer.id, msg.base, msg.height)
        elif isinstance(msg, NoBlockResponseMessage):
            self.pool.no_block(peer.id, msg.height)
        elif isinstance(msg, BlockResponseMessage):
            from ..libs.metrics import blockchain_metrics

            blockchain_metrics().block_bytes_received.inc(len(msgb))
            self.pool.add_block(peer.id, msg.block, len(msgb))
        else:
            raise ValueError(f"unknown blockchain msg {type(msg)}")

    # -- sync driver --

    async def _pool_routine(self) -> None:
        from ..libs.metrics import blockchain_metrics

        bmet = blockchain_metrics()
        last_status = 0.0
        last_switch_check = 0.0
        try:
            while True:
                now = time.monotonic()
                bmet.pool_height.set(self.pool.height)
                bmet.pending_requests.set(len(self.pool.requests))
                bmet.num_peers.set(len(self.pool.peers))
                # expire slow/dead peers
                for pid in self.pool.tick(now):
                    self.pool.remove_peer(pid)
                    sw = self.switch
                    if sw is not None and pid in sw.peers:
                        sw._on_peer_error(sw.peers[pid],
                                          RuntimeError("fast-sync timeout"))
                # issue new requests
                sw = self.switch
                if sw is not None:
                    for pid, height in self.pool.make_next_requests(now):
                        peer = sw.peers.get(pid)
                        if peer is None:
                            self.pool.remove_peer(pid)
                            continue
                        peer.try_send(BLOCKCHAIN_CHANNEL, encode_bc_msg(
                            BlockRequestMessage(height)))
                # periodic status poll
                if now - last_status > STATUS_UPDATE_INTERVAL or \
                        not self.pool.peers:
                    last_status = now
                    if sw is not None:
                        sw.broadcast(BLOCKCHAIN_CHANNEL, encode_bc_msg(
                            StatusRequestMessage()))
                # drain what we can
                while await self._try_sync():
                    pass
                # caught up?
                if now - last_switch_check > SWITCH_TO_CONSENSUS_INTERVAL:
                    last_switch_check = now
                    stalled = self.pool.last_advance is not None and \
                        now - self.pool.last_advance > SYNC_TIMEOUT
                    if self.pool.is_caught_up() or stalled:
                        if stalled and not self.pool.is_caught_up():
                            logger.warning(
                                "no fast-sync progress for %.0fs; "
                                "switching to consensus", SYNC_TIMEOUT)
                        logger.info("fast sync complete at height %d "
                                    "(%d blocks)", self.pool.height - 1,
                                    self.blocks_synced)
                        self.synced.set()
                        from ..libs.metrics import consensus_metrics

                        consensus_metrics().fast_syncing.set(0)
                        if self.consensus_reactor is not None:
                            await self.consensus_reactor.\
                                switch_to_consensus(self.state)
                        return
                await asyncio.sleep(TRY_SYNC_INTERVAL)
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("fast-sync pool routine died")

    async def _try_sync(self) -> bool:
        """Verify+apply a window of contiguous fetched blocks. Block i
        is verified with block i+1's LastCommit, so with W+1 buffered
        blocks, W are verifiable — in one signature batch when the
        validator set is stable (the overwhelmingly common case)."""
        blocks = self.pool.peek_blocks(BATCH_WINDOW + 1)
        if len(blocks) < 2:
            return False
        vals = self.state.validators
        chain_id = self.state.chain_id
        items = []
        for i in range(len(blocks) - 1):
            first, second = blocks[i], blocks[i + 1]
            parts = first.make_part_set()
            bid = BlockID(first.hash(), parts.header())
            items.append((bid, first.header.height, second.last_commit))
        results = _batch_verify_window(vals, chain_id, items)

        applied = 0
        now = time.monotonic()
        assumed_vals_hash = vals.hash()
        for i, err in enumerate(results):
            if err is not None:
                # The failure implicates BOTH peers: the one that served
                # block H (possibly forged) and the one that served
                # block H+1 carrying the LastCommit used to verify H
                # (possibly forged commit). Redo + ban both, mirroring
                # reference blockchain/v0/reactor.go:409 — otherwise a
                # byzantine peer serving H+1 with a bad commit keeps its
                # block buffered while honest H-servers get banned one
                # by one, stalling the sync.
                bad_heights = (items[i][1], blocks[i + 1].header.height)
                sw = self.switch
                for h in bad_heights:
                    peer_id = self.pool.redo_request(h)
                    logger.warning(
                        "block %d failed verification (%s); banning "
                        "peer %s", h, err, peer_id,
                    )
                    if sw is not None and peer_id in sw.peers:
                        rep = getattr(sw, "reporter", None)
                        if rep is not None:
                            # feed the trust metric before the hard stop
                            rep.observe(peer_id, bad=1)
                        sw._on_peer_error(sw.peers[peer_id],
                                          RuntimeError(f"bad block: {err}"))
                break
            first = blocks[i]
            bid = items[i][0]
            parts = first.make_part_set()
            self.pool.pop_request(now)
            self.block_store.save_block(first, parts, blocks[i + 1].last_commit)
            self.state, _ = await self.block_exec.apply_block(
                self.state, bid, first)
            self.blocks_synced += 1
            applied += 1
            from ..libs.metrics import blockchain_metrics

            blockchain_metrics().blocks_synced.inc()
            if self.state.validators.hash() != assumed_vals_hash:
                # validator set changed mid-window: the remaining
                # verdicts were computed against the wrong set — leave
                # those blocks buffered for re-verification next pass
                break
        return applied > 0
