"""Fast-sync reactor (reference: blockchain/v0/reactor.go, channel
0x40): serves committed blocks to catching-up peers and, when started
in fast-sync mode, drives the BlockPool to download, verify and apply
blocks until caught up, then hands off to consensus
(SwitchToConsensus, reference v0/reactor.go poolRoutine).

TPU-first redesign of the hot loop: the reference verifies one commit
per block (`VerifyCommitLight`, sequential per-sig). Here a contiguous
window of fetched blocks is verified as ONE signature batch
(`_batch_verify_window`): up to BATCH_WINDOW commits go to the device
in a single launch, amortizing dispatch and filling the lanes (SURVEY
§3.5: batch across blocks, not just within a commit). Large valsets
ride the expanded comb tables with device-assembled STRUCTURED sign
bytes — one template group per block's commit — via
ValidatorSet._batch_verify_lanes."""

from __future__ import annotations

import asyncio
import logging
import time

# Module scope on purpose: the old per-synced-block function-local
# import re-acquired the import lock inside the hottest loop in fast
# sync (one acquisition per applied block).
from ..libs.metrics import blockchain_metrics
from ..p2p.conn.connection import ChannelDescriptor
from ..p2p.switch import Reactor
from .msgs import (
    BlockRequestMessage,
    BlockResponseMessage,
    NoBlockResponseMessage,
    StatusRequestMessage,
    StatusResponseMessage,
    decode_bc_msg,
    encode_bc_msg,
)
from .pool import BlockPool
from .verify_ahead import BATCH_WINDOW, WindowPipeline

logger = logging.getLogger("blockchain")

BLOCKCHAIN_CHANNEL = 0x40

TRY_SYNC_INTERVAL = 0.01          # reference trySyncTicker (10ms)
STATUS_UPDATE_INTERVAL = 10.0     # reference statusUpdateTicker
SWITCH_TO_CONSENSUS_INTERVAL = 1.0
SYNC_TIMEOUT = 60.0               # reference syncTimeout: no progress →
                                  # give up waiting and run consensus


class BlockchainReactor(Reactor):
    def __init__(self, state, block_exec, block_store,
                 fast_sync: bool, consensus_reactor=None,
                 verify_ahead: bool = True):
        super().__init__("blockchain")
        self.state = state
        self.block_exec = block_exec
        self.block_store = block_store
        self.fast_sync = fast_sync
        self.consensus_reactor = consensus_reactor
        self.pool = BlockPool(block_store.height + 1
                              if block_store.height else
                              state.last_block_height + 1)
        self._task: asyncio.Task | None = None
        self.synced = asyncio.Event()
        if not fast_sync:
            self.synced.set()
        self.blocks_synced = 0
        # Overlapped execution (verify_ahead.py WindowPipeline): while
        # window W's blocks execute through apply_block, window W+1's
        # commits — already buffered, their verdicts fully determined
        # by the fetched blocks — verify concurrently in an executor
        # thread. Pure pipelining: verdicts are identical either way,
        # and the save_block -> apply_block persistence order is
        # untouched (tools/crash_sweep.py is the acceptance gate).
        self.pipeline = WindowPipeline(enabled=verify_ahead)

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(id=BLOCKCHAIN_CHANNEL, priority=10,
                                  send_queue_capacity=1000,
                                  recv_message_capacity=10_485_760 + 1024,
                                  name="blockchain")]

    async def start(self) -> None:
        if self.fast_sync and self._task is None:
            from ..libs.metrics import consensus_metrics

            consensus_metrics().fast_syncing.set(1)
            self._task = asyncio.get_running_loop().create_task(
                self._pool_routine(), name="blockchain-pool")

    async def switch_to_fast_sync(self, state) -> None:
        """Statesync → fastsync handoff (reference node.go:132)."""
        self.state = state
        self.fast_sync = True
        self.synced.clear()
        self.pool = BlockPool(state.last_block_height + 1)
        self.pipeline.reset()
        if self._task is not None and self._task.done():
            self._task = None
        await self.start()

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    # -- p2p --

    def _our_status(self) -> bytes:
        return encode_bc_msg(StatusResponseMessage(
            height=self.block_store.height, base=self.block_store.base))

    async def add_peer(self, peer) -> None:
        peer.try_send(BLOCKCHAIN_CHANNEL, self._our_status())

    async def remove_peer(self, peer, reason) -> None:
        self.pool.remove_peer(peer.id)

    async def receive(self, chan_id: int, peer, msgb: bytes) -> None:
        msg = decode_bc_msg(msgb)
        if isinstance(msg, BlockRequestMessage):
            block = self.block_store.load_block(msg.height)
            if block is not None:
                await peer.send(BLOCKCHAIN_CHANNEL, encode_bc_msg(
                    BlockResponseMessage(block)))
            else:
                await peer.send(BLOCKCHAIN_CHANNEL, encode_bc_msg(
                    NoBlockResponseMessage(msg.height)))
        elif isinstance(msg, StatusRequestMessage):
            peer.try_send(BLOCKCHAIN_CHANNEL, self._our_status())
        elif isinstance(msg, StatusResponseMessage):
            self.pool.set_peer_range(peer.id, msg.base, msg.height)
        elif isinstance(msg, NoBlockResponseMessage):
            self.pool.no_block(peer.id, msg.height)
        elif isinstance(msg, BlockResponseMessage):
            blockchain_metrics().block_bytes_received.inc(len(msgb))
            self.pool.add_block(peer.id, msg.block, len(msgb))
        else:
            raise ValueError(f"unknown blockchain msg {type(msg)}")

    # -- sync driver --

    async def _pool_routine(self) -> None:
        bmet = blockchain_metrics()
        last_status = 0.0
        last_switch_check = 0.0
        try:
            while True:
                now = time.monotonic()
                bmet.pool_height.set(self.pool.height)
                bmet.pending_requests.set(len(self.pool.requests))
                bmet.num_peers.set(len(self.pool.peers))
                # expire slow/dead peers
                for pid in self.pool.tick(now):
                    self.pool.remove_peer(pid)
                    sw = self.switch
                    if sw is not None and pid in sw.peers:
                        sw._on_peer_error(sw.peers[pid],
                                          RuntimeError("fast-sync timeout"))
                # issue new requests
                sw = self.switch
                if sw is not None:
                    for pid, height in self.pool.make_next_requests(now):
                        peer = sw.peers.get(pid)
                        if peer is None:
                            self.pool.remove_peer(pid)
                            continue
                        peer.try_send(BLOCKCHAIN_CHANNEL, encode_bc_msg(
                            BlockRequestMessage(height)))
                # periodic status poll
                if now - last_status > STATUS_UPDATE_INTERVAL or \
                        not self.pool.peers:
                    last_status = now
                    if sw is not None:
                        sw.broadcast(BLOCKCHAIN_CHANNEL, encode_bc_msg(
                            StatusRequestMessage()))
                # drain what we can
                while await self._try_sync():
                    pass
                # caught up?
                if now - last_switch_check > SWITCH_TO_CONSENSUS_INTERVAL:
                    last_switch_check = now
                    stalled = self.pool.last_advance is not None and \
                        now - self.pool.last_advance > SYNC_TIMEOUT
                    if self.pool.is_caught_up() or stalled:
                        if stalled and not self.pool.is_caught_up():
                            logger.warning(
                                "no fast-sync progress for %.0fs; "
                                "switching to consensus", SYNC_TIMEOUT)
                        logger.info("fast sync complete at height %d "
                                    "(%d blocks)", self.pool.height - 1,
                                    self.blocks_synced)
                        self.synced.set()
                        from ..libs.metrics import consensus_metrics

                        consensus_metrics().fast_syncing.set(0)
                        if self.consensus_reactor is not None:
                            await self.consensus_reactor.\
                                switch_to_consensus(self.state)
                        return
                await asyncio.sleep(TRY_SYNC_INTERVAL)
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("fast-sync pool routine died")

    async def _try_sync(self) -> bool:
        """Verify+apply a window of contiguous fetched blocks. Block i
        is verified with block i+1's LastCommit, so with W+1 buffered
        blocks, W are verifiable — in one signature batch when the
        validator set is stable (the overwhelmingly common case).
        While this window's blocks execute, the NEXT window's batch
        verifies concurrently (verify-ahead), so steady-state catch-up
        pays max(verify, apply) per window instead of their sum."""
        blocks = self.pool.peek_blocks(BATCH_WINDOW + 1)
        if len(blocks) < 2:
            return False
        vals = self.state.validators
        chain_id = self.state.chain_id
        items, parts_list, results = await self.pipeline.verdicts(
            vals, chain_id, blocks)
        self.pipeline.start_ahead(vals, chain_id,
                                  self.pool.peek_blocks, len(blocks))

        applied = 0
        now = time.monotonic()
        assumed_vals_hash = vals.hash()
        for i, err in enumerate(results):
            if err is not None:
                # The failure implicates BOTH peers: the one that served
                # block H (possibly forged) and the one that served
                # block H+1 carrying the LastCommit used to verify H
                # (possibly forged commit). Redo + ban both, mirroring
                # reference blockchain/v0/reactor.go:409 — otherwise a
                # byzantine peer serving H+1 with a bad commit keeps its
                # block buffered while honest H-servers get banned one
                # by one, stalling the sync.
                bad_heights = (items[i][1], blocks[i + 1].header.height)
                sw = self.switch
                for h in bad_heights:
                    peer_id = self.pool.redo_request(h)
                    logger.warning(
                        "block %d failed verification (%s); banning "
                        "peer %s", h, err, peer_id,
                    )
                    if sw is not None and peer_id in sw.peers:
                        rep = getattr(sw, "reporter", None)
                        if rep is not None:
                            # feed the trust metric before the hard stop
                            rep.observe(peer_id, bad=1)
                        sw._on_peer_error(sw.peers[peer_id],
                                          RuntimeError(f"bad block: {err}"))
                break
            first = blocks[i]
            bid = items[i][0]
            # the part set built (off-loop) by the verify job — never
            # re-serialize a full block on the event loop
            parts = parts_list[i]
            self.pool.pop_request(now)
            self.block_store.save_block(first, parts, blocks[i + 1].last_commit)
            self.state, _ = await self.block_exec.apply_block(
                self.state, bid, first)
            self.blocks_synced += 1
            applied += 1
            blockchain_metrics().blocks_synced.inc()
            if self.state.validators.hash() != assumed_vals_hash:
                # validator set changed mid-window: the remaining
                # verdicts were computed against the wrong set — leave
                # those blocks buffered for re-verification next pass
                # (any in-flight verify-ahead window is stale too: its
                # key carries the old valset hash, so the next pass
                # discards it and re-verifies under the new set)
                break
        return applied > 0
