"""Handshake block-replay (reference: consensus/replay.go:201-420).

On boot, reconcile three heights: the app's (ABCI Info), the state
store's, and the block store's. The app may be behind (crashed before
Commit) — replay stored blocks into it; tendermint state may be one
behind the block store (crashed between SaveBlock and ApplyBlock) —
re-apply the last block through the full executor path."""

from __future__ import annotations

from ..abci import types as abci_t
from ..abci.client import Client
from ..state import State as SmState, make_genesis_state
from ..state.execution import (
    BlockExecutor, abci_header_from_block, build_last_commit_info,
    validator_updates_from_abci,
)
from ..state.store import Store
from ..store import BlockStore
from ..types.genesis import GenesisDoc


class HandshakeError(Exception):
    pass


class _MockReplayClient(Client):
    """Stands in for the app when replaying a block it has already
    committed: answers from the ABCI responses saved at apply time and
    reports the app's own hash on Commit, so tendermint state catches
    up without double-executing (reference replay.go:370-415)."""

    def __init__(self, saved_responses: dict | None, app_hash: bytes):
        super().__init__(name="abci.MockReplayClient")
        self._saved = saved_responses
        self._app_hash = app_hash
        self._tx_i = 0

    async def deliver(self, req):
        if isinstance(req, abci_t.RequestBeginBlock):
            return (self._saved or {}).get("begin_block") \
                or abci_t.ResponseBeginBlock()
        if isinstance(req, abci_t.RequestDeliverTx):
            txs = (self._saved or {}).get("deliver_txs") or []
            r = (txs[self._tx_i] if self._tx_i < len(txs)
                 else abci_t.ResponseDeliverTx())
            self._tx_i += 1
            return r
        if isinstance(req, abci_t.RequestEndBlock):
            return (self._saved or {}).get("end_block") \
                or abci_t.ResponseEndBlock()
        if isinstance(req, abci_t.RequestCommit):
            return abci_t.ResponseCommit(data=self._app_hash)
        raise HandshakeError(f"mock replay client got {type(req).__name__}")


class Handshaker:
    def __init__(self, state_store: Store, state: SmState,
                 block_store: BlockStore, genesis_doc: GenesisDoc,
                 event_bus=None):
        self.state_store = state_store
        self.initial_state = state
        self.block_store = block_store
        self.genesis_doc = genesis_doc
        self.event_bus = event_bus
        self.n_blocks_replayed = 0

    async def handshake(self, app_conns) -> bytes:
        """Returns the app hash both sides agree on after replay."""
        info = await app_conns.query.info(abci_t.RequestInfo(
            version="tendermint_tpu", block_version=11, p2p_version=8,
        ))
        app_height = info.last_block_height
        app_hash = info.last_block_app_hash
        if app_height < 0:
            raise HandshakeError(f"app reported negative height {app_height}")

        state = self.initial_state
        state.app_version = info.app_version or state.app_version

        app_hash = await self.replay_blocks(state, app_hash, app_height,
                                            app_conns)
        return app_hash

    async def replay_blocks(self, state: SmState, app_hash: bytes,
                            app_height: int, app_conns) -> bytes:
        """reference replay.go:285 replayBlocks — all height cases."""
        store_height = self.block_store.height
        state_height = state.last_block_height

        # genesis: app has never seen InitChain
        if app_height == 0 and state_height == 0:
            vals = [
                abci_t.ValidatorUpdate(
                    v.pub_key.type_name, v.pub_key.bytes(), v.voting_power
                )
                for v in state.validators.validators
            ]
            res = await app_conns.consensus.init_chain(abci_t.RequestInitChain(
                time=self.genesis_doc.genesis_time,
                chain_id=self.genesis_doc.chain_id,
                consensus_params=state.consensus_params.to_json(),
                validators=vals,
                app_state_bytes=(
                    __import__("json").dumps(self.genesis_doc.app_state).encode()
                    if self.genesis_doc.app_state is not None else b""
                ),
                initial_height=self.genesis_doc.initial_height,
            ))
            if store_height == 0:
                # app may amend genesis valset / params / app hash
                if res.validators:
                    updates = validator_updates_from_abci(res.validators)
                    from ..types.validator_set import ValidatorSet

                    if not state.validators.validators:
                        state.validators = ValidatorSet(updates)
                        state.next_validators = state.validators.copy()
                        state.next_validators.increment_proposer_priority(1)
                    else:
                        state.next_validators = state.validators.copy()
                if res.app_hash:
                    state.app_hash = res.app_hash
                    app_hash = res.app_hash
                self.state_store.save(state)

        if store_height == 0:
            self._assert_app_hash(state, app_hash)
            return app_hash

        if store_height < app_height:
            raise HandshakeError(
                f"app height {app_height} ahead of block store {store_height}"
            )
        if state_height > store_height:
            raise HandshakeError(
                f"state height {state_height} ahead of block store {store_height}"
            )

        # replay blocks the app is missing, exec-only (no state updates)
        first = app_height + 1
        # the last block needs the FULL apply path if tendermint state is
        # also behind (crash between SaveBlock and ApplyBlock)
        full_apply_last = state_height < store_height
        exec_until = store_height - 1 if full_apply_last else store_height

        for h in range(first, exec_until + 1):
            app_hash = await self._exec_block(h, app_conns)
            self.n_blocks_replayed += 1

        if full_apply_last:
            block = self.block_store.load_block(store_height)
            if block is None:
                raise HandshakeError(f"missing block {store_height}")
            prev_state = self.state_store.load() or state
            if store_height >= first:
                # app is also missing this block: full apply drives it
                client = app_conns.consensus
            else:
                # app already committed it (crash between app Commit and
                # state save) — bring ONLY tendermint state forward, via
                # a mock client replaying the saved ABCI responses
                # (reference replay.go:370-415 newMockProxyApp).
                client = _MockReplayClient(
                    self.state_store.load_abci_responses(store_height),
                    app_hash,
                )
            executor = BlockExecutor(self.state_store, client,
                                     event_bus=self.event_bus)
            new_state, _ = await executor.apply_block(
                prev_state, block.block_id(), block
            )
            app_hash = new_state.app_hash
            self.n_blocks_replayed += 1

        self._assert_app_hash(self.state_store.load() or state, app_hash)
        return app_hash

    async def _exec_block(self, height: int, app_conns) -> bytes:
        """Execute one stored block against the app WITHOUT touching
        tendermint state (reference replay.go applyBlock-to-proxy path)."""
        import asyncio

        block = self.block_store.load_block(height)
        if block is None:
            raise HandshakeError(f"missing block {height} in store")
        client: Client = app_conns.consensus
        await client.begin_block(abci_t.RequestBeginBlock(
            hash=block.hash(),
            header=abci_header_from_block(block),
            last_commit_info=build_last_commit_info(
                block, self.state_store,
                self.initial_state.initial_height,
            ),
        ))
        tasks = [client.submit(abci_t.RequestDeliverTx(tx))
                 for tx in block.data.txs]
        if tasks:
            await asyncio.gather(*tasks)
        await client.end_block(abci_t.RequestEndBlock(height))
        res = await client.commit()
        return res.data

    def _assert_app_hash(self, state: SmState, app_hash: bytes) -> None:
        if state.last_block_height > 0 and state.app_hash != app_hash:
            raise HandshakeError(
                f"app hash mismatch after replay: state "
                f"{state.app_hash.hex()} != app {app_hash.hex()}"
            )


async def handshake_and_load_state(
    config, state_store: Store, block_store: BlockStore,
    genesis_doc: GenesisDoc, app_conns, event_bus=None,
) -> SmState:
    """Load-or-genesis state, handshake the app, return the
    post-handshake state (the node assembly entry point)."""
    state = state_store.load()
    if state is None:
        state = make_genesis_state(genesis_doc)
        state_store.save(state)
    h = Handshaker(state_store, state, block_store, genesis_doc, event_bus)
    await h.handshake(app_conns)
    return state_store.load() or state
