"""Handshake block-replay + startup reconciliation (reference:
consensus/replay.go:201-420).

On boot, reconcile three heights: the app's (ABCI Info), the state
store's, and the block store's. The app may be behind (crashed before
Commit) — replay stored blocks into it; tendermint state may be one
behind the block store (crashed between SaveBlock and ApplyBlock) —
re-apply the last block through the full executor path.

The Handshaker doubles as an explicit RECONCILER: every legal
cross-store skew a commit-pipeline crash can leave (see
libs/failpoints.py COMMIT_PIPELINE and the docs/CHAOS.md
"Crash-recovery runbook") is enumerated, healed, and recorded in a
RecoveryReport — each repair named from the closed REPAIR_KINDS
catalog, counted in the `recovery` metrics namespace, and surfaced in
GET /status for the life of the process."""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field

from ..abci import types as abci_t
from ..abci.client import Client
from ..state import State as SmState, make_genesis_state
from ..state.execution import (
    BlockExecutor, abci_header_from_block, build_last_commit_info,
    validator_updates_from_abci,
)
from ..state.store import Store
from ..store import BlockStore
from ..types.genesis import GenesisDoc

logger = logging.getLogger("consensus.replay")


class HandshakeError(Exception):
    pass


# The closed catalog of startup repairs. tools/check_recovery.py lints
# that every kind is documented in the docs/CHAOS.md runbook table and
# produced by at least one record() call site.
REPAIR_KINDS: dict[str, str] = {
    "wal_torn_tail":
        "corrupt consensus-WAL head tail quarantined and truncated "
        "(crash mid-append)",
    "app_replay":
        "app behind the block store: stored blocks re-executed into "
        "the app, exec-only (crash before the app's Commit)",
    "state_reapply":
        "tendermint state one behind the block store: the last stored "
        "block re-applied through the full executor path (crash "
        "between save_block and apply_block)",
    "state_from_responses":
        "state behind an app that already committed: state brought "
        "forward from the saved ABCI responses without re-executing "
        "(crash between app Commit and the state save)",
}


@dataclass
class RecoveryReport:
    """What the startup reconciler found and did — kept on the Node
    (`node.recovery_report`) and rendered by the /status `recovery`
    check so the last boot's crash-recovery story is inspectable on a
    live validator, not just greppable from logs."""

    app_height: int = 0
    state_height: int = 0
    store_height: int = 0
    wal_end_height: int | None = None
    wal_tail_repaired_bytes: int = 0
    quarantined_files: list[str] = field(default_factory=list)
    repairs: list[dict] = field(default_factory=list)
    blocks_replayed: int = 0

    def record(self, kind: str, detail: str = "", blocks: int = 0) -> None:
        assert kind in REPAIR_KINDS, kind
        self.repairs.append({"kind": kind, "detail": detail})
        self.blocks_replayed += blocks
        logger.warning("startup recovery: %s — %s", kind, detail)
        try:
            from ..libs.metrics import recovery_metrics

            m = recovery_metrics()
            m.repairs.inc(kind=kind)
            if blocks:
                m.blocks_replayed.inc(blocks)
        except Exception:  # metrics must never block recovery
            logger.exception("recovery metrics update failed")

    def to_dict(self) -> dict:
        return {
            "app_height": self.app_height,
            "state_height": self.state_height,
            "store_height": self.store_height,
            "wal_end_height": self.wal_end_height,
            "wal_tail_repaired_bytes": self.wal_tail_repaired_bytes,
            "quarantined_files": list(self.quarantined_files),
            "repairs": list(self.repairs),
            "blocks_replayed": self.blocks_replayed,
        }


class _MockReplayClient(Client):
    """Stands in for the app when replaying a block it has already
    committed: answers from the ABCI responses saved at apply time and
    reports the app's own hash on Commit, so tendermint state catches
    up without double-executing (reference replay.go:370-415)."""

    def __init__(self, saved_responses: dict | None, app_hash: bytes):
        super().__init__(name="abci.MockReplayClient")
        self._saved = saved_responses
        self._app_hash = app_hash
        self._tx_i = 0

    async def deliver(self, req):
        if isinstance(req, abci_t.RequestBeginBlock):
            return (self._saved or {}).get("begin_block") \
                or abci_t.ResponseBeginBlock()
        if isinstance(req, abci_t.RequestDeliverTx):
            txs = (self._saved or {}).get("deliver_txs") or []
            r = (txs[self._tx_i] if self._tx_i < len(txs)
                 else abci_t.ResponseDeliverTx())
            self._tx_i += 1
            return r
        if isinstance(req, abci_t.RequestEndBlock):
            return (self._saved or {}).get("end_block") \
                or abci_t.ResponseEndBlock()
        if isinstance(req, abci_t.RequestCommit):
            return abci_t.ResponseCommit(data=self._app_hash)
        raise HandshakeError(f"mock replay client got {type(req).__name__}")


class Handshaker:
    def __init__(self, state_store: Store, state: SmState,
                 block_store: BlockStore, genesis_doc: GenesisDoc,
                 event_bus=None, report: RecoveryReport | None = None):
        self.state_store = state_store
        self.initial_state = state
        self.block_store = block_store
        self.genesis_doc = genesis_doc
        self.event_bus = event_bus
        self.n_blocks_replayed = 0
        self.report = report if report is not None else RecoveryReport()

    async def handshake(self, app_conns) -> bytes:
        """Returns the app hash both sides agree on after replay."""
        info = await app_conns.query.info(abci_t.RequestInfo(
            version="tendermint_tpu", block_version=11, p2p_version=8,
        ))
        app_height = info.last_block_height
        app_hash = info.last_block_app_hash
        if app_height < 0:
            raise HandshakeError(f"app reported negative height {app_height}")

        state = self.initial_state
        state.app_version = info.app_version or state.app_version

        app_hash = await self.replay_blocks(state, app_hash, app_height,
                                            app_conns)
        return app_hash

    async def replay_blocks(self, state: SmState, app_hash: bytes,
                            app_height: int, app_conns) -> bytes:
        """reference replay.go:285 replayBlocks — all height cases."""
        store_height = self.block_store.height
        state_height = state.last_block_height
        rep = self.report
        rep.app_height = app_height
        rep.state_height = state_height
        rep.store_height = store_height

        # genesis: app has never seen InitChain
        if app_height == 0 and state_height == 0:
            vals = [
                abci_t.ValidatorUpdate(
                    v.pub_key.type_name, v.pub_key.bytes(), v.voting_power
                )
                for v in state.validators.validators
            ]
            res = await app_conns.consensus.init_chain(abci_t.RequestInitChain(
                time=self.genesis_doc.genesis_time,
                chain_id=self.genesis_doc.chain_id,
                consensus_params=state.consensus_params.to_json(),
                validators=vals,
                app_state_bytes=(
                    __import__("json").dumps(self.genesis_doc.app_state).encode()
                    if self.genesis_doc.app_state is not None else b""
                ),
                initial_height=self.genesis_doc.initial_height,
            ))
            if store_height == 0:
                # app may amend genesis valset / params / app hash
                if res.validators:
                    updates = validator_updates_from_abci(res.validators)
                    from ..types.validator_set import ValidatorSet

                    if not state.validators.validators:
                        state.validators = ValidatorSet(updates)
                        state.next_validators = state.validators.copy()
                        state.next_validators.increment_proposer_priority(1)
                    else:
                        state.next_validators = state.validators.copy()
                if res.app_hash:
                    state.app_hash = res.app_hash
                    app_hash = res.app_hash
                self.state_store.save(state)

        if store_height == 0:
            self._assert_app_hash(state, app_hash)
            return app_hash

        if store_height < app_height:
            raise HandshakeError(
                f"app height {app_height} ahead of block store {store_height}"
            )
        if state_height > store_height:
            raise HandshakeError(
                f"state height {state_height} ahead of block store {store_height}"
            )

        # replay blocks the app is missing, exec-only (no state updates)
        first = app_height + 1
        # the last block needs the FULL apply path if tendermint state is
        # also behind (crash between SaveBlock and ApplyBlock)
        full_apply_last = state_height < store_height
        exec_until = store_height - 1 if full_apply_last else store_height

        for h in range(first, exec_until + 1):
            app_hash = await self._exec_block(h, app_conns)
            self.n_blocks_replayed += 1
        if exec_until >= first:
            rep.record(
                "app_replay",
                f"re-executed stored blocks {first}..{exec_until} into "
                f"the app (app was at {app_height})",
                blocks=exec_until - first + 1)

        if full_apply_last:
            block = self.block_store.load_block(store_height)
            if block is None:
                raise HandshakeError(f"missing block {store_height}")
            prev_state = self.state_store.load() or state
            if store_height >= first:
                # app is also missing this block: full apply drives it
                client = app_conns.consensus
                rep.record(
                    "state_reapply",
                    f"re-applied block {store_height} through the full "
                    f"executor path (state was at {state_height})",
                    blocks=1)
            else:
                # app already committed it (crash between app Commit and
                # state save) — bring ONLY tendermint state forward, via
                # a mock client replaying the saved ABCI responses
                # (reference replay.go:370-415 newMockProxyApp).
                client = _MockReplayClient(
                    self.state_store.load_abci_responses(store_height),
                    app_hash,
                )
                rep.record(
                    "state_from_responses",
                    f"rebuilt state for block {store_height} from saved "
                    f"ABCI responses (app already committed it)",
                    blocks=1)
            executor = BlockExecutor(self.state_store, client,
                                     event_bus=self.event_bus)
            new_state, _ = await executor.apply_block(
                prev_state, block.block_id(), block
            )
            app_hash = new_state.app_hash
            self.n_blocks_replayed += 1

        self._assert_app_hash(self.state_store.load() or state, app_hash)
        return app_hash

    async def _exec_block(self, height: int, app_conns) -> bytes:
        """Execute one stored block against the app WITHOUT touching
        tendermint state (reference replay.go applyBlock-to-proxy path)."""
        import asyncio

        block = self.block_store.load_block(height)
        if block is None:
            raise HandshakeError(f"missing block {height} in store")
        client: Client = app_conns.consensus
        await client.begin_block(abci_t.RequestBeginBlock(
            hash=block.hash(),
            header=abci_header_from_block(block),
            last_commit_info=build_last_commit_info(
                block, self.state_store,
                self.initial_state.initial_height,
            ),
        ))
        tasks = [client.submit(abci_t.RequestDeliverTx(tx))
                 for tx in block.data.txs]
        if tasks:
            await asyncio.gather(*tasks)
        await client.end_block(abci_t.RequestEndBlock(height))
        res = await client.commit()
        return res.data

    def _assert_app_hash(self, state: SmState, app_hash: bytes) -> None:
        if state.last_block_height > 0 and state.app_hash != app_hash:
            raise HandshakeError(
                f"app hash mismatch after replay: state "
                f"{state.app_hash.hex()} != app {app_hash.hex()}"
            )


def _reconcile_wal(wal_path: str, report: RecoveryReport) -> None:
    """Pre-handshake WAL reconciliation: quarantine+truncate a torn
    head tail (so consensus catchup replays a clean record sequence)
    and note the newest committed-height marker for the report. The
    consensus loop re-opens the WAL for append later; repair() here is
    idempotent — a clean head is a no-op."""
    from .wal import WAL, EndHeightMessage

    if not os.path.exists(wal_path):
        return
    w = WAL(wal_path)
    try:
        # ONE decode pass serves both the torn-tail check and the
        # end-height scan (a boot-time WAL head can be 10 MB; decoding
        # it once per question adds up).
        msgs, consumed, size = WAL._decode_file(wal_path)
        torn = size - consumed
        if torn > 0 and w.repair():
            report.wal_tail_repaired_bytes = torn
            report.record(
                "wal_torn_tail",
                f"quarantined {torn} torn tail bytes of {wal_path}")
        end = None
        for msg in msgs:
            if isinstance(msg.msg, EndHeightMessage):
                end = msg.msg.height
        if end is None:
            # the newest marker may sit in a rotated segment (crash
            # right after a rotation leaves an empty/markerless head):
            # walk older segments newest-first, stop at the first hit
            for seg in reversed(w.segment_paths()[:-1]):
                for msg in w._read_segment(seg):
                    if isinstance(msg.msg, EndHeightMessage):
                        end = msg.msg.height
                if end is not None:
                    break
        report.wal_end_height = end
    finally:
        w.close()


def _scan_quarantine(dirs, report: RecoveryReport) -> None:
    """List corruption-evidence files (`*.corrupt.NNN` from FileDB
    replay and WAL repair — including the one a _reconcile_wal call
    just wrote) so operators see accumulated evidence in /status and
    on the recovery_quarantined_files gauge, instead of discovering it
    by du(1) years later."""
    found: list[str] = []
    for d in dict.fromkeys(d for d in dirs if d):
        if not os.path.isdir(d):
            continue
        for name in sorted(os.listdir(d)):
            if ".corrupt." in name:
                found.append(os.path.join(d, name))
    report.quarantined_files = found
    try:
        from ..libs.metrics import recovery_metrics

        recovery_metrics().quarantined_files.set(len(found))
    except Exception:
        logger.exception("recovery metrics update failed")


async def reconcile_and_handshake(
    config, state_store: Store, block_store: BlockStore,
    genesis_doc: GenesisDoc, app_conns, event_bus=None,
    wal_path: str | None = None, scan_dirs=(),
) -> tuple[SmState, RecoveryReport]:
    """Full startup reconciliation: repair the WAL tail, inventory
    quarantined evidence, load-or-genesis state, handshake the app
    (healing every legal cross-store skew), and return the
    post-handshake state plus the RecoveryReport describing what was
    found and repaired (the node assembly entry point)."""
    report = RecoveryReport()
    _scan_quarantine(list(scan_dirs), report)
    if wal_path:
        _reconcile_wal(wal_path, report)
        # the repair may have just minted a quarantine file: rescan
        if report.wal_tail_repaired_bytes:
            _scan_quarantine(list(scan_dirs), report)
    state = state_store.load()
    if state is None:
        state = make_genesis_state(genesis_doc)
        state_store.save(state)
    h = Handshaker(state_store, state, block_store, genesis_doc,
                   event_bus, report=report)
    await h.handshake(app_conns)
    # report.{app,state,store}_height stay as replay_blocks recorded
    # them PRE-repair — /status documents them as the skew the boot
    # recovered from, not the healed values.
    state = state_store.load() or state
    return state, report


async def handshake_and_load_state(
    config, state_store: Store, block_store: BlockStore,
    genesis_doc: GenesisDoc, app_conns, event_bus=None,
) -> SmState:
    """Load-or-genesis state, handshake the app, return the
    post-handshake state (compatibility wrapper around
    reconcile_and_handshake for callers that don't keep the report)."""
    state, _ = await reconcile_and_handshake(
        config, state_store, block_store, genesis_doc, app_conns,
        event_bus)
    return state
