"""Consensus messages (reference: consensus/msgs.go proto codec).

Wire form: a one-byte type tag + a payload. Votes/proposals ride their
canonical proto encodings (types/vote.py, types/proposal.py); block
parts carry their merkle proof inline. The same codec serves the WAL
and, later, the consensus reactor channels."""

from __future__ import annotations

from dataclasses import dataclass

from ..types.block import BlockID, Part, block_id_writer, read_block_id
from ..encoding.proto import Reader, Writer
from ..libs.bits import BitArray
from ..types.proposal import Proposal
from ..types.vote import Vote


@dataclass
class NewRoundStepMessage:
    height: int
    round: int
    step: int
    seconds_since_start_time: int = 0
    last_commit_round: int = 0


@dataclass
class NewValidBlockMessage:
    height: int
    round: int
    block_parts_header: object  # PartSetHeader
    block_parts: BitArray
    is_commit: bool


@dataclass
class ProposalMessage:
    proposal: Proposal
    # Optional cross-node trace context (libs/tracing.py origin tag):
    # opaque on the wire, skipped by decoders that predate it. Rides
    # the three block-lifecycle messages only (Proposal/BlockPart/Vote).
    origin: bytes | None = None


@dataclass
class ProposalPOLMessage:
    height: int
    proposal_pol_round: int
    proposal_pol: BitArray


@dataclass
class BlockPartMessage:
    height: int
    round: int
    part: Part
    origin: bytes | None = None


@dataclass
class VoteMessage:
    vote: Vote
    origin: bytes | None = None


@dataclass
class HasVoteMessage:
    height: int
    round: int
    type: int
    index: int


@dataclass
class VoteSetMaj23Message:
    height: int
    round: int
    type: int
    block_id: BlockID


@dataclass
class VoteSetBitsMessage:
    height: int
    round: int
    type: int
    block_id: BlockID
    votes: BitArray


# --- wire codec --------------------------------------------------------------

_TAG = {
    NewRoundStepMessage: 1,
    NewValidBlockMessage: 2,
    ProposalMessage: 3,
    ProposalPOLMessage: 4,
    BlockPartMessage: 5,
    VoteMessage: 6,
    HasVoteMessage: 7,
    VoteSetMaj23Message: 8,
    VoteSetBitsMessage: 9,
}
_BY_TAG = {v: k for k, v in _TAG.items()}


def _bits_writer(b: BitArray) -> Writer:
    w = Writer()
    w.varint(1, b.size)
    w.bytes(2, b.to_bytes())
    return w


def _read_bits(data: bytes) -> BitArray:
    r = Reader(data)
    size, raw = 0, b""
    while not r.at_end():
        f, wt = r.field()
        if f == 1:
            size = r.varint()
        elif f == 2:
            raw = r.bytes()
        else:
            r.skip(wt)
    return BitArray.from_bytes(size, raw)


def _part_writer(p: Part) -> Writer:
    return p.to_proto()


def _read_part(data: bytes) -> Part:
    return Part.from_bytes(data)


def encode_consensus_msg(msg) -> bytes:
    tag = _TAG[type(msg)]
    w = Writer()
    if isinstance(msg, NewRoundStepMessage):
        w.varint(1, msg.height)
        w.varint(2, msg.round, skip_zero=False)
        w.varint(3, msg.step)
        w.varint(4, msg.seconds_since_start_time)
        w.varint(5, msg.last_commit_round)
    elif isinstance(msg, NewValidBlockMessage):
        w.varint(1, msg.height)
        w.varint(2, msg.round, skip_zero=False)
        ph = Writer()
        ph.varint(1, msg.block_parts_header.total)
        ph.bytes(2, msg.block_parts_header.hash)
        w.message(3, ph)
        w.message(4, _bits_writer(msg.block_parts))
        w.bool(5, msg.is_commit)
    elif isinstance(msg, ProposalMessage):
        w.message(1, msg.proposal.to_proto())
        if msg.origin:
            w.bytes(15, msg.origin)
    elif isinstance(msg, ProposalPOLMessage):
        w.varint(1, msg.height)
        w.varint(2, msg.proposal_pol_round, skip_zero=False)
        w.message(3, _bits_writer(msg.proposal_pol))
    elif isinstance(msg, BlockPartMessage):
        w.varint(1, msg.height)
        w.varint(2, msg.round, skip_zero=False)
        w.message(3, _part_writer(msg.part))
        if msg.origin:
            w.bytes(15, msg.origin)
    elif isinstance(msg, VoteMessage):
        w.message(1, msg.vote.to_proto())
        if msg.origin:
            w.bytes(15, msg.origin)
    elif isinstance(msg, HasVoteMessage):
        w.varint(1, msg.height)
        w.varint(2, msg.round, skip_zero=False)
        w.varint(3, msg.type)
        w.varint(4, msg.index, skip_zero=False)
    elif isinstance(msg, VoteSetMaj23Message):
        w.varint(1, msg.height)
        w.varint(2, msg.round, skip_zero=False)
        w.varint(3, msg.type)
        w.message(4, block_id_writer(msg.block_id))
    elif isinstance(msg, VoteSetBitsMessage):
        w.varint(1, msg.height)
        w.varint(2, msg.round, skip_zero=False)
        w.varint(3, msg.type)
        w.message(4, block_id_writer(msg.block_id))
        w.message(5, _bits_writer(msg.votes))
    return bytes([tag]) + w.finish()


def decode_consensus_msg(data: bytes):
    if not data:
        raise ValueError("empty consensus message")
    cls = _BY_TAG.get(data[0])
    if cls is None:
        raise ValueError(f"unknown consensus message tag {data[0]}")
    r = Reader(data[1:])
    if cls is NewRoundStepMessage:
        kw = dict(height=0, round=0, step=0, seconds_since_start_time=0,
                  last_commit_round=0)
        names = {1: "height", 2: "round", 3: "step",
                 4: "seconds_since_start_time", 5: "last_commit_round"}
        while not r.at_end():
            f, wt = r.field()
            if f in names:
                kw[names[f]] = r.varint()
            else:
                r.skip(wt)
        return cls(**kw)
    if cls is NewValidBlockMessage:
        from ..types.block import PartSetHeader

        height = round_ = 0
        psh = PartSetHeader(0, b"")
        bits = BitArray(0)
        is_commit = False
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                height = r.varint()
            elif f == 2:
                round_ = r.varint()
            elif f == 3:
                rr = Reader(r.bytes())
                total, h = 0, b""
                while not rr.at_end():
                    ff, wwt = rr.field()
                    if ff == 1:
                        total = rr.varint()
                    elif ff == 2:
                        h = rr.bytes()
                    else:
                        rr.skip(wwt)
                psh = PartSetHeader(total, h)
            elif f == 4:
                bits = _read_bits(r.bytes())
            elif f == 5:
                is_commit = bool(r.varint())
            else:
                r.skip(wt)
        return cls(height, round_, psh, bits, is_commit)
    if cls is ProposalMessage:
        prop = None
        origin = None
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                prop = Proposal.from_bytes(r.bytes())
            elif f == 15:
                origin = r.bytes()
            else:
                r.skip(wt)
        if prop is None:
            raise ValueError("ProposalMessage without a proposal")
        return cls(prop, origin=origin)
    if cls is ProposalPOLMessage:
        height = pol_round = 0
        bits = BitArray(0)
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                height = r.varint()
            elif f == 2:
                pol_round = r.varint()
            elif f == 3:
                bits = _read_bits(r.bytes())
            else:
                r.skip(wt)
        return cls(height, pol_round, bits)
    if cls is BlockPartMessage:
        height = round_ = 0
        part = None
        origin = None
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                height = r.varint()
            elif f == 2:
                round_ = r.varint()
            elif f == 3:
                part = _read_part(r.bytes())
            elif f == 15:
                origin = r.bytes()
            else:
                r.skip(wt)
        if part is None:
            raise ValueError("BlockPartMessage without a part")
        return cls(height, round_, part, origin=origin)
    if cls is VoteMessage:
        vote = None
        origin = None
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                vote = Vote.from_bytes(r.bytes())
            elif f == 15:
                origin = r.bytes()
            else:
                r.skip(wt)
        if vote is None:
            raise ValueError("VoteMessage without a vote")
        return cls(vote, origin=origin)
    if cls is HasVoteMessage:
        kw = dict(height=0, round=0, type=0, index=0)
        names = {1: "height", 2: "round", 3: "type", 4: "index"}
        while not r.at_end():
            f, wt = r.field()
            if f in names:
                kw[names[f]] = r.varint()
            else:
                r.skip(wt)
        return cls(**kw)
    if cls is VoteSetMaj23Message:
        height = round_ = type_ = 0
        bid = BlockID(b"", None)
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                height = r.varint()
            elif f == 2:
                round_ = r.varint()
            elif f == 3:
                type_ = r.varint()
            elif f == 4:
                bid = read_block_id(r.bytes())
            else:
                r.skip(wt)
        return cls(height, round_, type_, bid)
    if cls is VoteSetBitsMessage:
        height = round_ = type_ = 0
        bid = BlockID(b"", None)
        bits = BitArray(0)
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                height = r.varint()
            elif f == 2:
                round_ = r.varint()
            elif f == 3:
                type_ = r.varint()
            elif f == 4:
                bid = read_block_id(r.bytes())
            elif f == 5:
                bits = _read_bits(r.bytes())
            else:
                r.skip(wt)
        return cls(height, round_, type_, bid, bits)
    raise AssertionError("unreachable")
