"""Consensus engine (reference: consensus/).

The Tendermint BFT state machine, asyncio-native: one serialized
receive loop per instance (the analogue of receiveRoutine,
consensus/state.go:686), a WAL written before acting on any message,
a timeout ticker, and gossip hooks the reactor attaches to."""

from .state import ConsensusState  # noqa: F401
from .cstypes import RoundState, RoundStep  # noqa: F401
