"""Consensus WAL (reference: consensus/wal.go).

Append-only fsync'd log of everything the consensus state machine acts
on, written BEFORE acting — crash recovery replays the tail of the log
to rebuild in-flight round state. Record framing matches the
reference's shape (wal.go:288): crc32 + length + payload, with a hard
per-message size bound. An EndHeightMessage delimits each committed
height (wal.go:42); recovery seeks the last one (wal.go:231)."""

from __future__ import annotations

import logging
import os
import struct
import zlib
from dataclasses import dataclass

logger = logging.getLogger("wal")

from ..encoding.proto import Reader, Writer
from ..libs import failpoints, tracing

MAX_MSG_SIZE = 1 << 20  # 1MB, reference wal.go maxMsgSizeBytes


@dataclass
class EndHeightMessage:
    height: int


@dataclass
class MsgInfo:
    """A peer or internal consensus message (votes/proposals/parts),
    carried as its consensus-codec bytes."""

    peer_id: str
    msg_bytes: bytes


@dataclass
class TimeoutInfo:
    duration_s: float
    height: int
    round: int
    step: int


@dataclass
class RoundStateMessage:
    """Step-transition marker (the reference WALs EventDataRoundState)."""

    height: int
    round: int
    step: int


@dataclass
class TimedWALMessage:
    time_ns: int
    msg: object


def _encode_wal_msg(m: TimedWALMessage) -> bytes:
    w = Writer()
    w.varint(1, m.time_ns)
    inner = m.msg
    if isinstance(inner, EndHeightMessage):
        w.message(2, Writer().varint(1, inner.height))
    elif isinstance(inner, MsgInfo):
        iw = Writer()
        iw.string(1, inner.peer_id)
        iw.bytes(2, inner.msg_bytes)
        w.message(3, iw)
    elif isinstance(inner, TimeoutInfo):
        iw = Writer()
        iw.varint(1, int(inner.duration_s * 1e9))
        iw.varint(2, inner.height)
        iw.varint(3, inner.round, skip_zero=False)
        iw.varint(4, inner.step)
        w.message(4, iw)
    elif isinstance(inner, RoundStateMessage):
        iw = Writer()
        iw.varint(1, inner.height)
        iw.varint(2, inner.round, skip_zero=False)
        iw.varint(3, inner.step)
        w.message(5, iw)
    else:
        raise TypeError(f"unknown WAL message {type(inner).__name__}")
    return w.finish()


def _decode_wal_msg(data: bytes) -> TimedWALMessage:
    r = Reader(data)
    time_ns = 0
    msg: object | None = None
    while not r.at_end():
        f, wt = r.field()
        if f == 1:
            time_ns = r.varint()
        elif f == 2:
            rr = Reader(r.bytes())
            height = 0
            while not rr.at_end():
                ff, wwt = rr.field()
                if ff == 1:
                    height = rr.varint()
                else:
                    rr.skip(wwt)
            msg = EndHeightMessage(height)
        elif f == 3:
            rr = Reader(r.bytes())
            peer, mb = "", b""
            while not rr.at_end():
                ff, wwt = rr.field()
                if ff == 1:
                    peer = rr.string()
                elif ff == 2:
                    mb = rr.bytes()
                else:
                    rr.skip(wwt)
            msg = MsgInfo(peer, mb)
        elif f == 4:
            rr = Reader(r.bytes())
            dur = height = round_ = step = 0
            while not rr.at_end():
                ff, wwt = rr.field()
                if ff == 1:
                    dur = rr.varint()
                elif ff == 2:
                    height = rr.varint()
                elif ff == 3:
                    round_ = rr.varint()
                elif ff == 4:
                    step = rr.varint()
                else:
                    rr.skip(wwt)
            msg = TimeoutInfo(dur / 1e9, height, round_, step)
        elif f == 5:
            rr = Reader(r.bytes())
            height = round_ = step = 0
            while not rr.at_end():
                ff, wwt = rr.field()
                if ff == 1:
                    height = rr.varint()
                elif ff == 2:
                    round_ = rr.varint()
                elif ff == 3:
                    step = rr.varint()
                else:
                    rr.skip(wwt)
            msg = RoundStateMessage(height, round_, step)
        else:
            r.skip(wt)
    if msg is None:
        raise ValueError("WAL message missing payload")
    return TimedWALMessage(time_ns, msg)


_FRAME = struct.Struct(">II")  # crc32, length


class WALCorruptionError(Exception):
    pass


def rotated_indices(path: str) -> list[int]:
    """Indices of rotated segments next to a WAL head path. Module
    level (not a method) so read-only consumers — replay-console —
    can enumerate segments without opening the head for append."""
    d = os.path.dirname(path) or "."
    base = os.path.basename(path) + "."
    out = []
    for name in os.listdir(d):
        if name.startswith(base) and name[len(base):].isdigit():
            out.append(int(name[len(base):]))
    return sorted(out)


def segment_paths(path: str) -> list[str]:
    """All segment files for a WAL head path, oldest first, head last."""
    return [f"{path}.{i:03d}" for i in rotated_indices(path)] + [path]


class WAL:
    """File-backed WAL with size-bounded rotation. write() buffers;
    write_sync() flushes + fsyncs. The consensus loop write_sync's
    before acting on any message that could change state (matching
    BaseWAL.WriteSync, wal.go:201).

    Rotation mirrors autofile.Group (reference consensus/wal.go:97 on
    libs/autofile/group.go:301): the head file lives at `path`; when
    it crosses head_size_limit it is renamed to `path.NNN` (NNN
    ascending, oldest = smallest) and a fresh head opens. When the
    segments together exceed total_size_limit the oldest are deleted
    (group.go:268 checkTotalSizeLimit) — replay data for long-
    committed heights is owned by the block/state stores, not the
    WAL. Rotation happens between records, so every segment is a
    clean record sequence; only the head can have a torn tail."""

    HEAD_SIZE_LIMIT = 10 * 1024 * 1024  # group.go:21
    TOTAL_SIZE_LIMIT = 1 << 30          # group.go:22

    def __init__(self, path: str, head_size_limit: int | None = None,
                 total_size_limit: int | None = None):
        self.path = path
        self.head_size_limit = head_size_limit or self.HEAD_SIZE_LIMIT
        self.total_size_limit = total_size_limit or self.TOTAL_SIZE_LIMIT
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "ab")
        self._head_size = os.path.getsize(path)

    # -- segments --

    def _rotated_indices(self) -> list[int]:
        return rotated_indices(self.path)

    def segment_paths(self) -> list[str]:
        """All segment files, oldest first, head last."""
        return segment_paths(self.path)

    def _rotate(self) -> None:
        self.flush_and_sync()
        self._f.close()
        idxs = self._rotated_indices()
        nxt = (idxs[-1] + 1) if idxs else 0
        os.rename(self.path, f"{self.path}.{nxt:03d}")
        self._f = open(self.path, "ab")
        self._head_size = 0
        # total-size bound: drop oldest segments
        segs = self.segment_paths()
        sizes = {p: os.path.getsize(p) for p in segs if os.path.exists(p)}
        total = sum(sizes.values())
        for p in segs[:-1]:
            if total <= self.total_size_limit:
                break
            total -= sizes.get(p, 0)
            os.unlink(p)

    # -- writing --

    def write(self, msg: object, time_ns: int = 0) -> None:
        data = _encode_wal_msg(TimedWALMessage(time_ns, msg))
        if len(data) > MAX_MSG_SIZE:
            raise ValueError(f"WAL message too big: {len(data)}")
        frame = _FRAME.pack(zlib.crc32(data), len(data)) + data
        # chaos: `corrupt` writes a bit-flipped/truncated frame — the
        # torn-write shape repair() must quarantine on the next boot
        frame = failpoints.hit("wal.torn_write", payload=frame)
        self._f.write(frame)
        self._head_size += len(frame)
        if self._head_size >= self.head_size_limit:
            self._rotate()

    def write_sync(self, msg: object, time_ns: int = 0) -> None:
        self.write(msg, time_ns)
        with tracing.TRACER.span(tracing.WAL_FSYNC):
            self.flush_and_sync()

    def flush_and_sync(self) -> None:
        failpoints.hit("wal.fsync")
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        try:
            self.flush_and_sync()
        except (OSError, ValueError):
            pass
        self._f.close()

    # -- reading --

    @staticmethod
    def _read_bytes(path: str) -> bytes:
        """One atomic read of a segment's current contents. Decoding
        and size accounting below both work off THIS byte string —
        never a re-stat of the live file (see _decode_file)."""
        if not os.path.exists(path):
            return b""
        with open(path, "rb") as f:
            return f.read()

    @staticmethod
    def _iter_records(path: str, strict: bool = False):
        """Yield (TimedWALMessage, consumed_bytes_after) one record at
        a time. On a corrupt/torn record, stop (strict=False — crash
        tails are expected) or raise (strict=True)."""
        yield from WAL._iter_data(WAL._read_bytes(path), strict)

    @staticmethod
    def _iter_data(data: bytes, strict: bool = False):
        pos = 0
        while pos + _FRAME.size <= len(data):
            crc, ln = _FRAME.unpack_from(data, pos)
            if ln > MAX_MSG_SIZE:
                if strict:
                    raise WALCorruptionError(f"record length {ln} too big")
                return
            body = data[pos + _FRAME.size : pos + _FRAME.size + ln]
            if len(body) < ln or zlib.crc32(body) != crc:
                if strict:
                    raise WALCorruptionError("crc mismatch / torn record")
                return
            try:
                msg = _decode_wal_msg(body)
            except ValueError:
                if strict:
                    raise
                return
            pos += _FRAME.size + ln
            yield msg, pos

    @staticmethod
    def _decode_file(path: str,
                     strict: bool = False
                     ) -> tuple[list[TimedWALMessage], int, int]:
        """Every record of one file; returns (messages,
        consumed_bytes, bytes_read).

        The size reported is len() of the bytes actually decoded, NOT
        a fresh stat: a record appended between the read and a re-stat
        would make size > consumed and repair() would truncate the
        perfectly valid new record off a healthy WAL."""
        data = WAL._read_bytes(path)
        out: list[TimedWALMessage] = []
        pos = 0
        for msg, pos in WAL._iter_data(data, strict):
            out.append(msg)
        return out, pos, len(data)

    @staticmethod
    def decode_all(path: str, strict: bool = False) -> list[TimedWALMessage]:
        return WAL._decode_file(path, strict)[0]

    @staticmethod
    def decode_iter(path: str, strict: bool = False):
        """Record-at-a-time generator: peak memory is one segment's
        raw bytes + ONE decoded message (decode_all materializes the
        whole list — wrong for the replay console over a big WAL)."""
        for msg, _ in WAL._iter_records(path, strict):
            yield msg

    def _read_segment(self, path: str) -> list[TimedWALMessage]:
        """One segment's valid records. Rotated segments were sealed
        at a record boundary, so mid-file corruption is real — the
        valid prefix is still returned (dropping it could erase the
        very EndHeightMessage recovery is looking for), with a
        warning for the lost tail. The head's torn tail is expected
        (crash) and not warned about here; repair() handles it."""
        msgs, consumed, size = self._decode_file(path)
        if consumed < size and path != self.path:
            logger.warning(
                "corrupt rotated WAL segment %s: %d of %d bytes "
                "unreadable after record %d",
                path, size - consumed, size, len(msgs))
        return msgs

    def read_all(self) -> list[TimedWALMessage]:
        """Every valid record across all segments, oldest first."""
        out: list[TimedWALMessage] = []
        for p in self.segment_paths():
            out.extend(self._read_segment(p))
        return out

    def search_for_end_height(self, height: int) -> tuple[list[TimedWALMessage], bool]:
        """Messages AFTER the EndHeightMessage for `height` (i.e. the
        in-flight messages of height+1), and whether it was found
        (reference wal.go:231 SearchForEndHeight) — spanning segment
        boundaries: the marker may sit in a rotated segment while the
        in-flight tail continues in the head. Segments are scanned
        NEWEST first and the scan stops at the first (newest) segment
        containing the marker, so boot cost is ~one segment, not the
        whole group (the group can be 1 GiB). Two phases so the
        marker-ABSENT case (a normal boot path after fast sync) holds
        at most one decoded segment in memory at a time instead of
        accumulating the whole group."""
        segs = self.segment_paths()
        found_seg = None
        for si in range(len(segs) - 1, -1, -1):
            if any(isinstance(m.msg, EndHeightMessage)
                   and m.msg.height == height
                   for m in self._read_segment(segs[si])):
                found_seg = si
                break
        if found_seg is None:
            return [], False
        # Rebuild the tail: marker segment + everything newer. The
        # common case (marker in the head) re-decodes one file.
        tail: list[TimedWALMessage] = []
        for si in range(found_seg, len(segs)):
            msgs = self._read_segment(segs[si])
            if si == found_seg:
                idx = max(i for i, m in enumerate(msgs)
                          if isinstance(m.msg, EndHeightMessage)
                          and m.msg.height == height)
                msgs = msgs[idx + 1:]
            tail.extend(msgs)
        return tail, True

    def repair(self) -> bool:
        """Cut a corrupted tail off the HEAD segment, keeping every
        valid record (reference: consensus/state.go:2217 repairWalFile
        — crashes only ever tear the file being appended). Returns
        True if anything was cut. The cut point is the decoder's
        consumed-bytes offset — the exact on-disk boundary,
        independent of whether re-encoding would be byte-identical.

        The tail is QUARANTINED, not deleted: the bytes move to
        `<path>.corrupt.NNN` before the truncate, so a repair that cut
        more than a crash tail (bad disk, injected mid-record torn
        write) leaves the evidence on disk for post-mortem instead of
        silently destroying it."""
        _, consumed, size = self._decode_file(self.path)
        if size <= consumed:
            return False
        self._f.close()
        with open(self.path, "rb") as f:
            f.seek(consumed)
            tail = f.read()
        qpath = self._quarantine_path()
        with open(qpath, "wb") as qf:
            qf.write(tail)
            qf.flush()
            os.fsync(qf.fileno())
        with open(self.path, "r+b") as f:
            f.truncate(consumed)
        logger.warning(
            "WAL repair: quarantined %d corrupt tail bytes of %s "
            "to %s", len(tail), self.path, qpath)
        self._f = open(self.path, "ab")
        self._head_size = consumed
        return True

    def _quarantine_path(self) -> str:
        """First free `<path>.corrupt.NNN` — repeated repairs (chaos
        sweeps, flaky disks) must not overwrite earlier evidence."""
        n = 0
        while True:
            p = f"{self.path}.corrupt.{n:03d}"
            if not os.path.exists(p):
                return p
            n += 1
