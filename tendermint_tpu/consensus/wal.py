"""Consensus WAL (reference: consensus/wal.go).

Append-only fsync'd log of everything the consensus state machine acts
on, written BEFORE acting — crash recovery replays the tail of the log
to rebuild in-flight round state. Record framing matches the
reference's shape (wal.go:288): crc32 + length + payload, with a hard
per-message size bound. An EndHeightMessage delimits each committed
height (wal.go:42); recovery seeks the last one (wal.go:231)."""

from __future__ import annotations

import os
import struct
import zlib
from dataclasses import dataclass

from ..encoding.proto import Reader, Writer

MAX_MSG_SIZE = 1 << 20  # 1MB, reference wal.go maxMsgSizeBytes


@dataclass
class EndHeightMessage:
    height: int


@dataclass
class MsgInfo:
    """A peer or internal consensus message (votes/proposals/parts),
    carried as its consensus-codec bytes."""

    peer_id: str
    msg_bytes: bytes


@dataclass
class TimeoutInfo:
    duration_s: float
    height: int
    round: int
    step: int


@dataclass
class RoundStateMessage:
    """Step-transition marker (the reference WALs EventDataRoundState)."""

    height: int
    round: int
    step: int


@dataclass
class TimedWALMessage:
    time_ns: int
    msg: object


def _encode_wal_msg(m: TimedWALMessage) -> bytes:
    w = Writer()
    w.varint(1, m.time_ns)
    inner = m.msg
    if isinstance(inner, EndHeightMessage):
        w.message(2, Writer().varint(1, inner.height))
    elif isinstance(inner, MsgInfo):
        iw = Writer()
        iw.string(1, inner.peer_id)
        iw.bytes(2, inner.msg_bytes)
        w.message(3, iw)
    elif isinstance(inner, TimeoutInfo):
        iw = Writer()
        iw.varint(1, int(inner.duration_s * 1e9))
        iw.varint(2, inner.height)
        iw.varint(3, inner.round, skip_zero=False)
        iw.varint(4, inner.step)
        w.message(4, iw)
    elif isinstance(inner, RoundStateMessage):
        iw = Writer()
        iw.varint(1, inner.height)
        iw.varint(2, inner.round, skip_zero=False)
        iw.varint(3, inner.step)
        w.message(5, iw)
    else:
        raise TypeError(f"unknown WAL message {type(inner).__name__}")
    return w.finish()


def _decode_wal_msg(data: bytes) -> TimedWALMessage:
    r = Reader(data)
    time_ns = 0
    msg: object | None = None
    while not r.at_end():
        f, wt = r.field()
        if f == 1:
            time_ns = r.varint()
        elif f == 2:
            rr = Reader(r.bytes())
            height = 0
            while not rr.at_end():
                ff, wwt = rr.field()
                if ff == 1:
                    height = rr.varint()
                else:
                    rr.skip(wwt)
            msg = EndHeightMessage(height)
        elif f == 3:
            rr = Reader(r.bytes())
            peer, mb = "", b""
            while not rr.at_end():
                ff, wwt = rr.field()
                if ff == 1:
                    peer = rr.string()
                elif ff == 2:
                    mb = rr.bytes()
                else:
                    rr.skip(wwt)
            msg = MsgInfo(peer, mb)
        elif f == 4:
            rr = Reader(r.bytes())
            dur = height = round_ = step = 0
            while not rr.at_end():
                ff, wwt = rr.field()
                if ff == 1:
                    dur = rr.varint()
                elif ff == 2:
                    height = rr.varint()
                elif ff == 3:
                    round_ = rr.varint()
                elif ff == 4:
                    step = rr.varint()
                else:
                    rr.skip(wwt)
            msg = TimeoutInfo(dur / 1e9, height, round_, step)
        elif f == 5:
            rr = Reader(r.bytes())
            height = round_ = step = 0
            while not rr.at_end():
                ff, wwt = rr.field()
                if ff == 1:
                    height = rr.varint()
                elif ff == 2:
                    round_ = rr.varint()
                elif ff == 3:
                    step = rr.varint()
                else:
                    rr.skip(wwt)
            msg = RoundStateMessage(height, round_, step)
        else:
            r.skip(wt)
    if msg is None:
        raise ValueError("WAL message missing payload")
    return TimedWALMessage(time_ns, msg)


_FRAME = struct.Struct(">II")  # crc32, length


class WALCorruptionError(Exception):
    pass


class WAL:
    """File-backed WAL. write() buffers; write_sync() flushes + fsyncs.
    The consensus loop write_sync's before acting on any message that
    could change state (matching BaseWAL.WriteSync, wal.go:201)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "ab")

    def write(self, msg: object, time_ns: int = 0) -> None:
        data = _encode_wal_msg(TimedWALMessage(time_ns, msg))
        if len(data) > MAX_MSG_SIZE:
            raise ValueError(f"WAL message too big: {len(data)}")
        self._f.write(_FRAME.pack(zlib.crc32(data), len(data)) + data)

    def write_sync(self, msg: object, time_ns: int = 0) -> None:
        self.write(msg, time_ns)
        self.flush_and_sync()

    def flush_and_sync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        try:
            self.flush_and_sync()
        except (OSError, ValueError):
            pass
        self._f.close()

    # -- reading --

    @staticmethod
    def decode_all(path: str, strict: bool = False) -> list[TimedWALMessage]:
        """Read every record; on a corrupt/torn record, stop (strict=False
        — crash tails are expected) or raise (strict=True)."""
        out: list[TimedWALMessage] = []
        if not os.path.exists(path):
            return out
        with open(path, "rb") as f:
            data = f.read()
        pos = 0
        while pos + _FRAME.size <= len(data):
            crc, ln = _FRAME.unpack_from(data, pos)
            if ln > MAX_MSG_SIZE:
                if strict:
                    raise WALCorruptionError(f"record length {ln} too big")
                break
            body = data[pos + _FRAME.size : pos + _FRAME.size + ln]
            if len(body) < ln or zlib.crc32(body) != crc:
                if strict:
                    raise WALCorruptionError("crc mismatch / torn record")
                break
            try:
                out.append(_decode_wal_msg(body))
            except ValueError:
                if strict:
                    raise
                break
            pos += _FRAME.size + ln
        return out

    def search_for_end_height(self, height: int) -> tuple[list[TimedWALMessage], bool]:
        """Messages AFTER the EndHeightMessage for `height` (i.e. the
        in-flight messages of height+1), and whether it was found
        (reference wal.go:231 SearchForEndHeight)."""
        msgs = self.decode_all(self.path)
        idx = None
        for i, m in enumerate(msgs):
            if isinstance(m.msg, EndHeightMessage) and m.msg.height == height:
                idx = i
        if idx is None:
            return [], False
        return msgs[idx + 1 :], True

    def repair(self) -> bool:
        """Truncate a corrupted tail in place, keeping every valid
        record (reference: consensus/state.go:2217 repairWalFile).
        Returns True if anything was cut."""
        good = self.decode_all(self.path)
        valid_bytes = 0
        for m in good:
            data = _encode_wal_msg(m)
            valid_bytes += _FRAME.size + len(data)
        actual = os.path.getsize(self.path)
        if actual <= valid_bytes:
            return False
        self._f.close()
        with open(self.path, "r+b") as f:
            f.truncate(valid_bytes)
        self._f = open(self.path, "ab")
        return True
