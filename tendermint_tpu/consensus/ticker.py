"""Timeout ticker (reference: consensus/ticker.go:31,94).

One in-flight timer; scheduling a new timeout for a later (h, r, step)
cancels the old one (the reference drains its timer channel). Fired
timeouts land on an asyncio queue the consensus loop selects on."""

from __future__ import annotations

import asyncio

from .wal import TimeoutInfo


class TimeoutTicker:
    def __init__(self):
        self.queue: asyncio.Queue[TimeoutInfo] = asyncio.Queue()
        self._timer: asyncio.TimerHandle | None = None
        self._current: TimeoutInfo | None = None

    def schedule(self, ti: TimeoutInfo) -> None:
        """Replace the active timer iff ti is for a later (h, r, step)
        — or unconditionally when no timer is active."""
        cur = self._current
        if cur is not None and self._timer is not None:
            if (ti.height, ti.round, ti.step) < (cur.height, cur.round, cur.step):
                return  # stale schedule, keep the newer timer
            self._timer.cancel()
        self._current = ti
        loop = asyncio.get_running_loop()
        self._timer = loop.call_later(ti.duration_s, self._fire, ti)

    def _fire(self, ti: TimeoutInfo) -> None:
        if self._current is ti:
            self._current = None
            self._timer = None
        self.queue.put_nowait(ti)

    def stop(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
            self._current = None
