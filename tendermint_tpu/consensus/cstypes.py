"""Consensus round types (reference: consensus/types/).

RoundStep progression, RoundState (the full mutable state of one
consensus instance, round_state.go:67), and HeightVoteSet (per-round
prevote/precommit VoteSets, height_vote_set.go:41)."""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from ..types.block import Block, BlockID, Commit, PartSet
from ..types.proposal import Proposal
from ..types.validator_set import ValidatorSet
from ..types.vote import Vote, VoteType
from ..types.vote_set import VoteSet, VoteSetError


class RoundStep(enum.IntEnum):
    NEW_HEIGHT = 1
    NEW_ROUND = 2
    PROPOSE = 3
    PREVOTE = 4
    PREVOTE_WAIT = 5
    PRECOMMIT = 6
    PRECOMMIT_WAIT = 7
    COMMIT = 8


@dataclass
class RoundState:
    """Reference: consensus/types/round_state.go:67."""

    height: int = 0
    round: int = 0
    step: RoundStep = RoundStep.NEW_HEIGHT
    start_time: float = 0.0
    commit_time: float = 0.0
    validators: ValidatorSet | None = None
    proposal: Proposal | None = None
    proposal_block: Block | None = None
    proposal_block_parts: PartSet | None = None
    locked_round: int = -1
    locked_block: Block | None = None
    locked_block_parts: PartSet | None = None
    valid_round: int = -1
    valid_block: Block | None = None
    valid_block_parts: PartSet | None = None
    votes: "HeightVoteSet | None" = None
    commit_round: int = -1
    last_commit: VoteSet | None = None
    last_validators: ValidatorSet | None = None
    triggered_timeout_precommit: bool = False

    def proposal_complete(self) -> bool:
        return (
            self.proposal is not None
            and self.proposal_block is not None
        )


class HeightVoteSet:
    """Prevotes+precommits for every round of one height, created
    lazily up to round+1 (reference: height_vote_set.go).

    Tracks one catchup round per peer: a peer claiming +2/3 at a
    future round lets us open vote sets there (SetPeerMaj23)."""

    def __init__(self, chain_id: str, height: int, val_set: ValidatorSet):
        self.chain_id = chain_id
        self.height = height
        self.val_set = val_set
        self.round = 0
        self._round_vote_sets: dict[int, tuple[VoteSet, VoteSet]] = {}
        self._peer_catchup_rounds: dict[str, list[int]] = {}
        self._add_round(0)
        self._add_round(1)

    def _add_round(self, round_: int) -> None:
        if round_ in self._round_vote_sets:
            return
        self._round_vote_sets[round_] = (
            VoteSet(self.chain_id, self.height, round_, VoteType.PREVOTE,
                    self.val_set),
            VoteSet(self.chain_id, self.height, round_, VoteType.PRECOMMIT,
                    self.val_set),
        )

    def set_round(self, round_: int) -> None:
        """Ensure vote sets exist through round+1."""
        if round_ < self.round:
            raise ValueError("set_round going backwards")
        for r in range(self.round, round_ + 2):
            self._add_round(r)
        self.round = round_

    def prevotes(self, round_: int) -> VoteSet | None:
        rs = self._round_vote_sets.get(round_)
        return rs[0] if rs else None

    def precommits(self, round_: int) -> VoteSet | None:
        rs = self._round_vote_sets.get(round_)
        return rs[1] if rs else None

    def add_vote(self, vote: Vote, peer_id: str = "",
                 verify: bool = True) -> bool:
        """Route to the right round's VoteSet. Votes from rounds beyond
        round+1 are only admitted once per peer (catchup; DoS bound,
        reference height_vote_set.go AddVote). verify=False commits a
        vote whose signature the micro-batch scheduler already checked
        on device."""
        if not VoteType.is_valid(int(vote.type)):
            raise VoteSetError("invalid vote type")
        vs = self._get(vote.round, vote.type)
        if vs is None:
            rounds = self._peer_catchup_rounds.setdefault(peer_id, [])
            if len(rounds) < 2:
                self._add_round(vote.round)
                vs = self._get(vote.round, vote.type)
                rounds.append(vote.round)
            else:
                raise VoteSetError(
                    f"unwanted round {vote.round} from peer {peer_id}"
                )
        return vs.add_vote(vote, verify=verify)

    def _get(self, round_: int, type_: VoteType) -> VoteSet | None:
        return (self.prevotes(round_) if type_ == VoteType.PREVOTE
                else self.precommits(round_))

    def pol_info(self) -> tuple[int, BlockID | None]:
        """Highest round with a prevote +2/3 (proof-of-lock)."""
        for r in sorted(self._round_vote_sets, reverse=True):
            pv = self.prevotes(r)
            if pv is not None:
                bid, ok = pv.two_thirds_majority()
                if ok:
                    return r, bid
        return -1, None

    def set_peer_maj23(self, round_: int, type_: VoteType, peer_id: str,
                       block_id: BlockID) -> None:
        self._add_round(round_)
        vs = self._get(round_, type_)
        if vs is not None:
            vs.set_peer_maj23(peer_id, block_id)
