"""Consensus reactor: gossips round state, proposals, block parts and
votes between peers (reference: consensus/reactor.go:27-30, the four
channels 0x20-0x23; gossipDataRoutine :492, gossipVotesRoutine :632,
queryMaj23Routine :765; PeerState :932).

Redesign notes (asyncio, not goroutines): each peer gets three
supervised tasks (data / votes / maj23) started on add_peer and
cancelled on remove_peer. Outbound state changes arrive via
ConsensusState.broadcast_hooks — a synchronous fan-out the reactor
turns into non-blocking `Switch.broadcast` calls — rather than the
reference's internal event switch. All inbound consensus messages are
funneled into the consensus state's single serialized receive queue
(`add_peer_msg`), preserving the reference's one-event-loop invariant.
"""

from __future__ import annotations

import asyncio
import logging

from ..libs import clock, tracing
from ..libs.bits import BitArray
from ..p2p.conn.connection import ChannelDescriptor
from ..p2p.switch import Reactor
from ..types.block import NIL_BLOCK_ID, PartSetHeader
from ..types.vote import VoteType
from . import messages as m
from .cstypes import RoundState, RoundStep
from .state import ConsensusState

logger = logging.getLogger("consensus.reactor")

STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22
VOTE_SET_BITS_CHANNEL = 0x23

PEER_GOSSIP_SLEEP = 0.1   # reference: peerGossipSleepDuration (100ms)
PEER_QUERY_MAJ23_SLEEP = 2.0  # reference: peerQueryMaj23SleepDuration

# Rounds of vote bit-arrays retained per peer; a byzantine peer spinning
# rounds must not grow our bookkeeping without bound.
_MAX_TRACKED_ROUNDS = 64


class PeerState:
    """What we know about one peer's view of consensus
    (reference: consensus/reactor.go:932 PeerState + PeerRoundState)."""

    def __init__(self, peer):
        self.peer = peer
        self.height = 0
        self.round = -1
        self.step = RoundStep.NEW_HEIGHT
        self.start_time = 0.0
        self.proposal = False
        self.proposal_block_parts_header: PartSetHeader | None = None
        self.proposal_block_parts: BitArray | None = None
        self.proposal_pol_round = -1
        self.proposal_pol: BitArray | None = None
        self.prevotes: dict[int, BitArray] = {}
        self.precommits: dict[int, BitArray] = {}
        self.last_commit_round = -1
        self.last_commit: BitArray | None = None
        self.catchup_commit_round = -1
        self.catchup_commit: BitArray | None = None
        # stats (reference PeerState.Stats → MarkPeerAsGood)
        self.votes_received = 0
        self.block_parts_received = 0

    # -- bit-array bookkeeping --

    def _ensure(self, d: dict[int, BitArray], round_: int, n: int) -> BitArray:
        ba = d.get(round_)
        if ba is None or ba.size != n:
            ba = BitArray(n)
            d[round_] = ba
            while len(d) > _MAX_TRACKED_ROUNDS:
                del d[min(d)]
        return ba

    def get_vote_bits(self, height: int, round_: int,
                      type_: int) -> BitArray | None:
        """reference: PeerState.getVoteBitArray."""
        if self.height == height:
            if type_ == VoteType.PREVOTE:
                if round_ == self.proposal_pol_round and \
                        self.proposal_pol is not None:
                    return self.proposal_pol
                return self.prevotes.get(round_)
            if round_ == self.catchup_commit_round and \
                    self.catchup_commit is not None:
                return self.catchup_commit
            return self.precommits.get(round_)
        if self.height == height + 1 and type_ == VoteType.PRECOMMIT \
                and round_ == self.last_commit_round:
            return self.last_commit
        return None

    def ensure_vote_bits(self, height: int, round_: int, type_: int,
                         num_validators: int) -> BitArray | None:
        if self.height != height:
            return self.get_vote_bits(height, round_, type_)
        d = self.prevotes if type_ == VoteType.PREVOTE else self.precommits
        self._ensure(d, round_, num_validators)
        return self.get_vote_bits(height, round_, type_)

    def set_has_vote(self, height: int, round_: int, type_: int,
                     index: int, num_validators: int = 0) -> None:
        bits = self.ensure_vote_bits(height, round_, type_,
                                     num_validators) if num_validators \
            else self.get_vote_bits(height, round_, type_)
        if bits is not None and 0 <= index < bits.size:
            bits.set(index, True)

    def set_has_part(self, height: int, round_: int, index: int) -> None:
        if self.height == height and self.round == round_ and \
                self.proposal_block_parts is not None and \
                0 <= index < self.proposal_block_parts.size:
            self.proposal_block_parts.set(index, True)

    # -- message application (all reference Apply*Message methods) --

    def apply_new_round_step(self, msg: m.NewRoundStepMessage) -> None:
        ph, pr = self.height, self.round
        if msg.height < ph or (msg.height == ph and msg.round < pr):
            return  # stale
        self.height = msg.height
        self.round = msg.round
        self.step = RoundStep(msg.step)
        self.start_time = clock.monotonic() - msg.seconds_since_start_time
        if ph != msg.height or pr != msg.round:
            self.proposal = False
            self.proposal_block_parts_header = None
            self.proposal_block_parts = None
            self.proposal_pol_round = -1
            self.proposal_pol = None
        if ph != msg.height:
            # Their precommits for the previous height become last-commit
            # (reference ApplyNewRoundStepMessage).
            if ph + 1 == msg.height and pr == msg.last_commit_round:
                self.last_commit = self.precommits.get(pr)
            else:
                self.last_commit = None
            self.last_commit_round = msg.last_commit_round
            self.prevotes = {}
            self.precommits = {}
            self.catchup_commit_round = -1
            self.catchup_commit = None

    def apply_new_valid_block(self, msg: m.NewValidBlockMessage) -> None:
        if self.height != msg.height:
            return
        if self.round != msg.round and not msg.is_commit:
            return
        # REPLACE, not OR: the sender's advert is its true holdings,
        # and our marks include optimistic send-time marks that may be
        # wrong (parts sent against a header it since replaced). An OR
        # would preserve exactly the stale marks the periodic
        # commit-advert exists to heal; the cost — re-sending a few
        # in-flight parts after each advert — is bounded and ends at
        # block completion.
        self.proposal_block_parts_header = msg.block_parts_header
        self.proposal_block_parts = msg.block_parts

    def set_proposal(self, proposal) -> None:
        if self.height != proposal.height or self.round != proposal.round:
            return
        if self.proposal:
            return
        self.proposal = True
        if self.proposal_block_parts is not None:
            return  # already set via NewValidBlock
        self.proposal_pol_round = proposal.pol_round
        self.proposal_pol = None  # filled by ProposalPOLMessage

    def set_proposal_parts_header(self, header: PartSetHeader) -> None:
        if self.proposal_block_parts is None:
            self.proposal_block_parts_header = header
            self.proposal_block_parts = BitArray(header.total)

    def apply_proposal_pol(self, msg: m.ProposalPOLMessage) -> None:
        if self.height != msg.height:
            return
        if self.proposal_pol_round != msg.proposal_pol_round:
            return
        self.proposal_pol = msg.proposal_pol

    def apply_has_vote(self, msg: m.HasVoteMessage) -> None:
        if self.height != msg.height:
            return
        self.set_has_vote(msg.height, msg.round, msg.type, msg.index)

    def apply_vote_set_bits(self, msg: m.VoteSetBitsMessage,
                            our_votes: BitArray | None) -> None:
        """reference: ApplyVoteSetBitsMessage (reactor.go:1362) — the
        peer's SELF-REPORT replaces our bookkeeping for the reported
        block's votes (bits outside our tally for that block are kept).
        Replacement, not OR, is load-bearing: gossip optimistically
        marks votes as delivered on send, and a vote sent while the
        peer was still in wait_sync is dropped on its floor — an OR
        could never clear the stale mark and the peer would be starved
        of those votes forever (observed deadlocking a restarted node
        at the prevote step)."""
        bits = self.get_vote_bits(msg.height, msg.round, msg.type)
        if bits is None or msg.votes.size != bits.size:
            return
        if our_votes is not None and our_votes.size == bits.size:
            other = bits.sub(our_votes)
            new_bits = other.or_(msg.votes)
        else:
            new_bits = msg.votes  # conservative overwrite
        d = self.prevotes if msg.type == VoteType.PREVOTE \
            else self.precommits
        if msg.height == self.height and msg.round in d:
            d[msg.round] = new_bits

    def ensure_catchup_commit(self, height: int, round_: int,
                              num_validators: int) -> None:
        """reference: PeerState.EnsureCatchupCommitRound."""
        if self.height != height or self.catchup_commit_round == round_:
            return
        self.catchup_commit_round = round_
        if round_ == self.round:
            self.catchup_commit = self.precommits.get(round_)
        else:
            self.catchup_commit = BitArray(num_validators)

    def __repr__(self) -> str:
        return (f"PeerState({self.peer.id[:8]} h={self.height} "
                f"r={self.round} s={self.step.name})")


def _new_valid_block_msg(rs: RoundState, parts,
                         is_commit: bool) -> m.NewValidBlockMessage:
    return m.NewValidBlockMessage(
        height=rs.height, round=rs.round,
        block_parts_header=parts.header(),
        block_parts=parts.parts_bitarray,
        is_commit=is_commit)


def _new_round_step_msg(rs: RoundState) -> m.NewRoundStepMessage:
    lcr = rs.last_commit.round if rs.last_commit is not None else -1
    return m.NewRoundStepMessage(
        height=rs.height, round=rs.round, step=int(rs.step),
        seconds_since_start_time=max(0, int(clock.monotonic() -
                                            rs.start_time)),
        last_commit_round=lcr)


class ConsensusReactor(Reactor):
    """reference: consensus/reactor.go ConsensusReactor."""

    def __init__(self, cs: ConsensusState, wait_sync: bool = False,
                 gossip_sleep: float = PEER_GOSSIP_SLEEP):
        super().__init__("consensus")
        self.cs = cs
        self.wait_sync = wait_sync
        self.gossip_sleep = gossip_sleep
        self.peer_states: dict[str, PeerState] = {}
        self._peer_tasks: dict[str, list[asyncio.Task]] = {}
        cs.broadcast_hooks.append(self._on_cs_event)
        # Lets the state machine feed verified/rejected vote counts
        # into the trust metric (behaviour.SwitchReporter) without
        # knowing about the p2p layer.
        cs.reporter_fn = lambda: getattr(self.switch, "reporter", None)

    # -- origin stamping (height forensics) --

    def _origin_label(self) -> str:
        """Node label carried on outgoing lifecycle messages: the
        builder-set cs.trace_node, falling back to our p2p node id."""
        label = self.cs.trace_node
        if label:
            return label
        sw = self.switch
        ni = getattr(sw, "node_info_fn", None) if sw is not None else None
        try:
            return ni().node_id[:16] if ni is not None else ""
        except Exception:
            return ""

    def _stamped(self, msg) -> bytes:
        """Encode a lifecycle message (Proposal/BlockPart/Vote) with a
        cross-node origin tag (libs/tracing.py). ALL reactor sends of
        the three lifecycle types go through here — check_spans.py
        lints the parity. Non-lifecycle messages pass through
        unstamped."""
        if isinstance(msg, m.VoteMessage):
            msg.origin = tracing.origin_stamp(
                self._origin_label(), msg.vote.height, msg.vote.round)
        elif isinstance(msg, m.ProposalMessage):
            msg.origin = tracing.origin_stamp(
                self._origin_label(), msg.proposal.height,
                msg.proposal.round)
        elif isinstance(msg, m.BlockPartMessage):
            msg.origin = tracing.origin_stamp(
                self._origin_label(), msg.height, msg.round)
        return m.encode_consensus_msg(msg)

    def get_channels(self) -> list[ChannelDescriptor]:
        # priorities/capacities follow reference reactor.go GetChannels
        return [
            ChannelDescriptor(id=STATE_CHANNEL, priority=6,
                              send_queue_capacity=100, name="state"),
            ChannelDescriptor(id=DATA_CHANNEL, priority=10,
                              send_queue_capacity=100, name="data"),
            ChannelDescriptor(id=VOTE_CHANNEL, priority=7,
                              send_queue_capacity=100, name="vote"),
            ChannelDescriptor(id=VOTE_SET_BITS_CHANNEL, priority=1,
                              send_queue_capacity=2, name="votebits"),
        ]

    # -- lifecycle --

    async def switch_to_consensus(self, state, skip_wal: bool = False) -> None:
        """Fast-sync → consensus handoff (reference: SwitchToConsensus,
        reactor.go:106 — reconstructLastCommit THEN updateToState +
        start gossip for existing peers)."""
        self.cs.update_to_state(state)
        if state.last_block_height > 0:
            # Without this a fast-synced node that becomes proposer
            # cannot build a block ("cannot propose: no last commit")
            # and a 1/3-power set of such nodes halts the net.
            self.cs.reconstruct_last_commit()
        self.wait_sync = False
        await self.cs.start()
        for pid, ps in self.peer_states.items():
            if pid not in self._peer_tasks:
                self._start_gossip(ps)

    async def stop(self) -> None:
        for tasks in self._peer_tasks.values():
            for t in tasks:
                t.cancel()
        self._peer_tasks.clear()

    # -- peer lifecycle --

    async def add_peer(self, peer) -> None:
        ps = PeerState(peer)
        self.peer_states[peer.id] = ps
        # other reactors (evidence, mempool) read the peer's consensus
        # height from here (reference: types.PeerStateKey on peer kv)
        peer.set("consensus_peer_state", ps)
        # Tell the new peer where we are (reference AddPeer: it sends
        # NewRoundStep ONLY when !WaitSync, reactor.go:199). While
        # fast/state sync runs, this reactor DROPS incoming consensus
        # messages — advertising a (height, round) here would invite
        # peers to firehose votes into that drop window and mark them
        # delivered, permanently starving us of them after the switch
        # (observed deadlocking a restarted node, and with it the net).
        # Peers learn our real position from the step broadcasts that
        # fire when consensus starts.
        if not self.wait_sync:
            peer.try_send(STATE_CHANNEL, m.encode_consensus_msg(
                _new_round_step_msg(self.cs.rs)))
            self._start_gossip(ps)

    def _start_gossip(self, ps: PeerState) -> None:
        loop = asyncio.get_running_loop()
        tasks = [
            loop.create_task(self._gossip_data_routine(ps),
                             name=f"gossip-data-{ps.peer.id[:8]}"),
            loop.create_task(self._gossip_votes_routine(ps),
                             name=f"gossip-votes-{ps.peer.id[:8]}"),
            loop.create_task(self._query_maj23_routine(ps),
                             name=f"maj23-{ps.peer.id[:8]}"),
        ]
        self._peer_tasks[ps.peer.id] = tasks

    async def remove_peer(self, peer, reason) -> None:
        for t in self._peer_tasks.pop(peer.id, []):
            t.cancel()
        self.peer_states.pop(peer.id, None)

    # -- inbound --

    async def receive(self, chan_id: int, peer, msgb: bytes) -> None:
        msg = m.decode_consensus_msg(msgb)
        # Origin rehydration: the connection's recv routine runs us
        # inside a live p2p.recv_msg span — fold the sender's tag
        # (node, height, round, send-side span id) into its attrs so
        # this receive links to the send span on the origin node.
        origin = getattr(msg, "origin", None)
        if origin is not None:
            tracing.rehydrate_origin(origin)
        ps = self.peer_states.get(peer.id)
        if ps is None:
            return
        if chan_id == STATE_CHANNEL:
            if isinstance(msg, m.NewRoundStepMessage):
                if msg.height < 1 or msg.round < 0 or \
                        not 1 <= msg.step <= 8:
                    raise ValueError("invalid NewRoundStep")
                ps.apply_new_round_step(msg)
            elif isinstance(msg, m.NewValidBlockMessage):
                ps.apply_new_valid_block(msg)
            elif isinstance(msg, m.HasVoteMessage):
                ps.apply_has_vote(msg)
            elif isinstance(msg, m.VoteSetMaj23Message):
                await self._handle_maj23(ps, peer, msg)
            else:
                raise ValueError(f"bad msg on state channel: {type(msg)}")
        elif chan_id == DATA_CHANNEL:
            if self.wait_sync:
                return
            if isinstance(msg, m.ProposalMessage):
                ps.set_proposal(msg.proposal)
                await self.cs.add_peer_msg(msg, peer.id)
            elif isinstance(msg, m.ProposalPOLMessage):
                ps.apply_proposal_pol(msg)
            elif isinstance(msg, m.BlockPartMessage):
                ps.set_has_part(msg.height, msg.round, msg.part.index)
                ps.block_parts_received += 1
                await self.cs.add_peer_msg(msg, peer.id)
            else:
                raise ValueError(f"bad msg on data channel: {type(msg)}")
        elif chan_id == VOTE_CHANNEL:
            if self.wait_sync:
                return
            if isinstance(msg, m.VoteMessage):
                v = msg.vote
                n = len(self.cs.rs.validators) if self.cs.rs.validators \
                    else 0
                ps.ensure_vote_bits(v.height, v.round, int(v.type), n)
                ps.set_has_vote(v.height, v.round, int(v.type),
                                v.validator_index)
                ps.votes_received += 1
                await self.cs.add_peer_msg(msg, peer.id)
                # NOTE: no trust credit here — votes are credited (or
                # debited) by the state machine AFTER signature
                # verification (state.py _verify_and_commit_batch);
                # crediting decodable-but-unverified votes would let a
                # byzantine peer farm reputation with garbage.
            else:
                raise ValueError(f"bad msg on vote channel: {type(msg)}")
        elif chan_id == VOTE_SET_BITS_CHANNEL:
            if isinstance(msg, m.VoteSetBitsMessage):
                rs = self.cs.rs
                ours = None
                if rs.height == msg.height and rs.votes is not None:
                    vs = (rs.votes.prevotes(msg.round)
                          if msg.type == VoteType.PREVOTE
                          else rs.votes.precommits(msg.round))
                    if vs is not None:
                        ours = vs.bit_array_by_block_id(None) \
                            if msg.block_id is None or msg.block_id.is_nil() \
                            else vs.bit_array_by_block_id(msg.block_id)
                logger.debug("bits from %s h=%d r=%d t=%d: %s (ours %s)",
                             peer.id[:8], msg.height, msg.round,
                             msg.type, msg.votes, ours)
                ps.apply_vote_set_bits(msg, ours)
            else:
                raise ValueError(
                    f"bad msg on votebits channel: {type(msg)}")

    async def _handle_maj23(self, ps: PeerState, peer,
                            msg: m.VoteSetMaj23Message) -> None:
        """Peer claims +2/3 at (height, round, type, block_id): record it
        and reply with which of those votes we already have
        (reference reactor.go Receive StateChannel VoteSetMaj23)."""
        rs = self.cs.rs
        if rs.height != msg.height or rs.votes is None:
            return
        if not VoteType.is_valid(msg.type):
            raise ValueError("invalid vote type in maj23")
        rs.votes.set_peer_maj23(msg.round, VoteType(msg.type), peer.id,
                                msg.block_id)
        vs = (rs.votes.prevotes(msg.round) if msg.type == VoteType.PREVOTE
              else rs.votes.precommits(msg.round))
        our_bits = vs.bit_array_by_block_id(msg.block_id) if vs else None
        if our_bits is None:
            our_bits = BitArray(len(rs.validators) if rs.validators else 0)
        logger.debug("maj23 from %s h=%d r=%d t=%d; replying bits %s",
                     peer.id[:8], msg.height, msg.round, msg.type,
                     our_bits)
        await peer.send(VOTE_SET_BITS_CHANNEL, m.encode_consensus_msg(
            m.VoteSetBitsMessage(height=msg.height, round=msg.round,
                                 type=msg.type, block_id=msg.block_id,
                                 votes=our_bits)))

    # -- outbound broadcast (ConsensusState hooks) --

    def _on_cs_event(self, event: str, payload) -> None:
        if self.switch is None:
            return
        if event == "step":
            rs: RoundState = payload
            self.switch.broadcast(STATE_CHANNEL, m.encode_consensus_msg(
                _new_round_step_msg(rs)))
            if rs.valid_block is not None and \
                    rs.valid_block_parts is not None:
                self.switch.broadcast(
                    STATE_CHANNEL,
                    m.encode_consensus_msg(_new_valid_block_msg(
                        rs, rs.valid_block_parts,
                        is_commit=rs.step == RoundStep.COMMIT)))
        elif event == "valid_block":
            rs = payload
            if rs.proposal_block_parts is not None:
                self.switch.broadcast(
                    STATE_CHANNEL,
                    m.encode_consensus_msg(_new_valid_block_msg(
                        rs, rs.proposal_block_parts,
                        is_commit=rs.step == RoundStep.COMMIT)))
        elif event == "has_vote":
            self.switch.broadcast(STATE_CHANNEL,
                                  m.encode_consensus_msg(payload))
        elif event == "vote_split":
            # Maverick equivocation (consensus/misbehavior.py): every
            # peer receives BOTH conflicting votes, in alternating
            # order. (Sending each half to half the peers — the
            # reference maverick's split — makes evidence creation a
            # race against the commit: prevotes stop being gossiped
            # once the height advances. Delivering both directly makes
            # the conflict, and thus DuplicateVoteEvidence, determinate
            # while still exercising the same add-vote conflict path.)
            vote_a, vote_b = payload
            for i, peer in enumerate(list(self.switch.peers.values())):
                pair = (vote_a, vote_b) if i % 2 == 0 else (vote_b, vote_a)
                for msg in pair:
                    peer.try_send(VOTE_CHANNEL, self._stamped(msg))
        elif event == "proposal_split":
            # Maverick double-proposal: odd peers get the alternate
            # proposal + its parts directly (even peers see the primary
            # through normal gossip).
            (_, _), (prop_b, parts_b) = payload
            for i, peer in enumerate(list(self.switch.peers.values())):
                if i % 2 == 0:
                    continue
                peer.try_send(DATA_CHANNEL,
                              self._stamped(m.ProposalMessage(prop_b)))
                for j in range(parts_b.total):
                    peer.try_send(DATA_CHANNEL, self._stamped(
                        m.BlockPartMessage(prop_b.height, prop_b.round,
                                           parts_b.get_part(j))))

    # -- gossip routines --

    async def _gossip_data_routine(self, ps: PeerState) -> None:
        """reference: gossipDataRoutine (reactor.go:492)."""
        peer = ps.peer
        last_advert = 0.0
        try:
            while True:
                rs = self.cs.rs
                # 0) WE are stuck in COMMIT missing the decided block:
                # remind this peer which part set we accept. The
                # one-shot valid_block broadcast from _enter_commit is
                # best-effort (peers may not even be connected yet at
                # net start), and peers gate their catch-up gossip on
                # having seen it — a lost advert wedged a node at its
                # commit height FOREVER while the net raced ahead
                # (found by the 120-run double-propose stress).
                if rs.step == RoundStep.COMMIT and \
                        rs.proposal_block is None and \
                        rs.proposal_block_parts is not None and \
                        clock.monotonic() - last_advert > 1.0:
                    last_advert = clock.monotonic()
                    await peer.send(
                        STATE_CHANNEL,
                        m.encode_consensus_msg(_new_valid_block_msg(
                            rs, rs.proposal_block_parts,
                            is_commit=True)))
                # demoted slow peer (switch slow-peer escalation): its
                # send queue cannot absorb bulk data — pause block-part
                # and catchup gossip (steps 1-3) until it drains. The
                # tiny state-class advert ABOVE stays exempt: skipping
                # it would re-open the wedged-at-COMMIT-forever hole
                # the periodic re-advert exists to close. Votes/state
                # routines keep serving the peer throughout.
                if getattr(peer, "slow_level", 0) >= 2:
                    await asyncio.sleep(self.gossip_sleep)
                    continue
                # 1) send a proposal block part the peer lacks
                if rs.height == ps.height and rs.round == ps.round and \
                        rs.proposal_block_parts is not None and \
                        ps.proposal_block_parts is not None and \
                        rs.proposal_block_parts.has_header(
                            ps.proposal_block_parts_header):
                    if await self._send_missing_part(
                            ps, rs.proposal_block_parts, rs.height,
                            rs.round):
                        continue
                # 2) peer is behind: feed it parts of committed blocks
                if ps.height != 0 and rs.height > ps.height:
                    if await self._gossip_catchup_part(ps):
                        continue
                # 3) send the proposal itself (+POL). SNAPSHOT the
                # proposal/parts/votes: the `await peer.send` yields to
                # the event loop, and a round change can null
                # rs.proposal mid-iteration (observed crashing this
                # routine under a maverick double-proposal — a dead
                # gossip routine silently starves the peer).
                proposal = rs.proposal
                parts = rs.proposal_block_parts
                votes = rs.votes
                # Round must match set_proposal's acceptance guard
                # (PeerState.set_proposal drops a proposal for another
                # round WITHOUT latching ps.proposal): sending on a
                # round mismatch re-sent the same proposal every
                # iteration with no sleep — a CPU-burning spin against
                # any peer sitting in a different round, found the
                # moment the sim harness made gossip time virtual.
                if rs.height == ps.height and proposal is not None \
                        and ps.round == proposal.round \
                        and not ps.proposal:
                    await peer.send(DATA_CHANNEL, self._stamped(
                        m.ProposalMessage(proposal)))
                    ps.set_proposal(proposal)
                    if parts is not None:
                        ps.set_proposal_parts_header(parts.header())
                    if proposal.pol_round >= 0 and votes is not None:
                        pol = votes.prevotes(proposal.pol_round)
                        if pol is not None:
                            await peer.send(
                                DATA_CHANNEL,
                                m.encode_consensus_msg(m.ProposalPOLMessage(
                                    height=proposal.height,
                                    proposal_pol_round=proposal.pol_round,
                                    proposal_pol=pol.bit_array())))
                    continue
                await asyncio.sleep(self.gossip_sleep)
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("gossip data routine for %r died", ps)

    async def _send_missing_part(self, ps: PeerState, parts, height: int,
                                 round_: int) -> bool:
        if ps.proposal_block_parts is None:
            return False
        missing = parts.parts_bitarray.sub(ps.proposal_block_parts)
        idx, ok = missing.pick_random()
        if not ok:
            return False
        part = parts.get_part(idx)
        if part is None:
            return False
        await ps.peer.send(DATA_CHANNEL, self._stamped(
            m.BlockPartMessage(height=height, round=round_, part=part)))
        ps.set_has_part(height, round_, idx)
        return True

    async def _gossip_catchup_part(self, ps: PeerState) -> bool:
        """Send one part of the block committed at the peer's height —
        only once the peer advertises (via NewValidBlock from its
        enterCommit) that it accepts this part-set; parts pushed before
        then would be dropped on its floor and never re-sent
        (reference: gossipDataForCatchup checks the headers match)."""
        meta = self.cs.block_store.load_block_meta(ps.height)
        if meta is None:
            await asyncio.sleep(self.gossip_sleep)
            return True
        header = meta.block_id.part_set_header
        if ps.proposal_block_parts is None or \
                ps.proposal_block_parts_header != header:
            await asyncio.sleep(self.gossip_sleep)
            return True
        # Burst several parts per iteration: one part per gossip_sleep
        # capped catch-up below the net's commit rate on bigger blocks
        # (same starvation mode as the one-vote-per-tick commit gossip).
        # Every send awaits, so the peer can complete its block and
        # advance (NewRoundStep nulls ps.proposal_block_parts) MID-
        # burst — the common case when bursting works. Re-check the
        # live PeerState each iteration and mark via the guarded
        # set_has_part; a raw .set() here crashed the routine.
        height, round_ = ps.height, ps.round
        missing = ps.proposal_block_parts.not_()
        sent_any = False
        for _ in range(8):
            idx, ok = missing.pick_random()
            if not ok:
                break
            if ps.height != height or ps.proposal_block_parts is None:
                break  # peer advanced mid-burst: done with this height
            part = self.cs.block_store.load_block_part(height, idx)
            if part is None:
                break
            await ps.peer.send(DATA_CHANNEL, self._stamped(
                m.BlockPartMessage(height=height, round=round_,
                                   part=part)))
            ps.set_has_part(height, round_, idx)
            missing.set(idx, False)
            sent_any = True
        if not sent_any:
            await asyncio.sleep(self.gossip_sleep)
        return True

    async def _gossip_votes_routine(self, ps: PeerState) -> None:
        """reference: gossipVotesRoutine (reactor.go:632)."""
        try:
            while True:
                rs = self.cs.rs
                sent = False
                if rs.height == ps.height:
                    sent = await self._gossip_votes_for_height(rs, ps)
                # peer is one height behind: our last commit
                if not sent and ps.height != 0 and \
                        rs.height == ps.height + 1 and \
                        rs.last_commit is not None:
                    sent = await self._pick_send_vote(ps, rs.last_commit)
                # peer is far behind: commit from the block store
                if not sent and ps.height != 0 and \
                        rs.height >= ps.height + 2:
                    sent = await self._gossip_catchup_commit(ps)
                if not sent:
                    await asyncio.sleep(self.gossip_sleep)
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("gossip votes routine for %r died", ps)

    async def _gossip_votes_for_height(self, rs: RoundState,
                                       ps: PeerState) -> bool:
        """reference: gossipVotesForHeight."""
        votes = rs.votes
        if votes is None:
            return False
        # peer is at a previous round: just send its round's votes
        if ps.proposal_pol_round != -1 and ps.step <= RoundStep.PROPOSE:
            pol = votes.prevotes(ps.proposal_pol_round)
            if pol is not None and await self._pick_send_vote(ps, pol):
                return True
        if ps.step <= RoundStep.PREVOTE_WAIT and 0 <= ps.round <= rs.round:
            pv = votes.prevotes(ps.round)
            if pv is not None and await self._pick_send_vote(ps, pv):
                return True
        if ps.step <= RoundStep.PRECOMMIT_WAIT and \
                0 <= ps.round <= rs.round:
            pc = votes.precommits(ps.round)
            if pc is not None and await self._pick_send_vote(ps, pc):
                return True
        if 0 <= ps.round <= rs.round:
            pv = votes.prevotes(ps.round)
            if pv is not None and await self._pick_send_vote(ps, pv):
                return True
        if ps.proposal_pol_round != -1:
            pol = votes.prevotes(ps.proposal_pol_round)
            if pol is not None and await self._pick_send_vote(ps, pol):
                return True
        return False

    def _load_commit(self, height: int):
        """Commit for `height` FOR GOSSIP: the canonical one when block
        height+1 exists, else the locally-seen commit at the tip
        (reference consensus/state.go LoadCommit). Without the tip
        fallback, a peer finishing the tip height can never be fed its
        missing precommits — observed deadlocking a restarted node (and
        with it the whole net, once >1/3 power depended on it).
        Evidence verification deliberately does NOT use this (rounds of
        seen commits differ per node; gossip only needs valid votes)."""
        bs = self.cs.block_store
        if height == bs.height:
            return bs.load_seen_commit(height)
        return bs.load_block_commit(height)

    async def _gossip_catchup_commit(self, ps: PeerState) -> bool:
        commit = self._load_commit(ps.height)
        if commit is None:
            return False
        # Rebuild votes from commit sigs; need that height's valset —
        # reference uses LoadBlockCommit + ps.PickSendVote on a VoteSet
        # view. We send the precommit of a random signer the peer lacks.
        bits = ps.ensure_vote_bits(ps.height, commit.round,
                                   VoteType.PRECOMMIT, len(commit.signatures))
        if bits is None:
            ps.ensure_catchup_commit(ps.height, commit.round,
                                     len(commit.signatures))
            bits = ps.catchup_commit
        if bits is None:
            return False
        have = BitArray(len(commit.signatures))
        for i, cs_ in enumerate(commit.signatures):
            if cs_.for_block():
                have.set(i, True)
        missing = have.sub(bits)
        # Send EVERY missing commit vote in one iteration: a peer this
        # far behind needs the whole commit to advance, and pacing one
        # vote per gossip_sleep put the catch-up rate BELOW the net's
        # commit rate on 6+ validator nets — a restarted node would
        # chase the tip forever (observed in soak runs).
        sent = False
        for idx in range(len(commit.signatures)):
            if not missing.get(idx):
                continue
            vote = self._commit_to_vote(commit, idx)
            if vote is None:
                continue
            await ps.peer.send(VOTE_CHANNEL,
                               self._stamped(m.VoteMessage(vote)))
            bits.set(idx, True)
            sent = True
        return sent

    def _commit_to_vote(self, commit, idx: int):
        from ..types.vote import Vote
        cs_ = commit.signatures[idx]
        if not cs_.for_block():
            return None
        return Vote(type=VoteType.PRECOMMIT, height=commit.height,
                    round=commit.round,
                    block_id=cs_.block_id_for(commit.block_id),
                    timestamp=cs_.timestamp,
                    validator_address=cs_.validator_address,
                    validator_index=idx, signature=cs_.signature)

    async def _pick_send_vote(self, ps: PeerState, vs) -> bool:
        """Pick one vote the peer lacks and send it
        (reference: PeerState.PickSendVote)."""
        peer_bits = ps.ensure_vote_bits(vs.height, vs.round, int(vs.type),
                                        vs.size())
        if peer_bits is None:
            return False
        ours = vs.bit_array()
        missing = ours.sub(peer_bits)
        idx, ok = missing.pick_random()
        if not ok:
            return False
        vote = vs.get_by_index(idx)
        if vote is None:
            return False
        ok = await ps.peer.send(VOTE_CHANNEL,
                                self._stamped(m.VoteMessage(vote)))
        if ok:
            logger.debug("sent vote h=%d r=%d t=%d idx=%d to %s",
                         vote.height, vote.round, int(vote.type), idx,
                         ps.peer.id[:8])
            ps.set_has_vote(vote.height, vote.round, int(vote.type), idx)
        return ok

    async def _query_maj23_routine(self, ps: PeerState) -> None:
        """Periodically tell peers which (h,r,type,blockID) we've seen
        +2/3 votes for, so they can send us what we're missing
        (reference: queryMaj23Routine reactor.go:765)."""
        try:
            while True:
                await asyncio.sleep(PEER_QUERY_MAJ23_SLEEP)
                rs = self.cs.rs
                if rs.votes is None:
                    continue
                if rs.height == ps.height:
                    for type_, vs in ((VoteType.PREVOTE,
                                       rs.votes.prevotes(ps.round)),
                                      (VoteType.PRECOMMIT,
                                       rs.votes.precommits(ps.round))):
                        if vs is None:
                            continue
                        bid, ok = vs.two_thirds_majority()
                        if ok:
                            # NIL majorities announce too (bid None =
                            # +2/3 for nil): the bits-reconciliation
                            # reply is what un-starves a peer whose
                            # votes were sent into its wait_sync window
                            # — skipping nil deadlocked a restarted
                            # node at the prevote step (no proposer ->
                            # the majority IS nil in that scenario).
                            logger.debug(
                                "announce maj23 h=%d r=%d t=%d to %s",
                                rs.height, ps.round, int(type_),
                                ps.peer.id[:8])
                            await ps.peer.send(
                                STATE_CHANNEL,
                                m.encode_consensus_msg(m.VoteSetMaj23Message(
                                    height=rs.height, round=ps.round,
                                    type=int(type_),
                                    block_id=bid or NIL_BLOCK_ID)))
                # catchup: advertise the commit of the peer's height
                if rs.height != ps.height and ps.height > 0 and \
                        ps.height >= self.cs.block_store.base:
                    commit = self._load_commit(ps.height)
                    if commit is not None:
                        await ps.peer.send(
                            STATE_CHANNEL,
                            m.encode_consensus_msg(m.VoteSetMaj23Message(
                                height=ps.height, round=commit.round,
                                type=int(VoteType.PRECOMMIT),
                                block_id=commit.block_id)))
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("maj23 routine for %r died", ps)
