"""The Tendermint BFT state machine (reference: consensus/state.go:85).

One asyncio task serializes everything (the receiveRoutine analogue,
state.go:686-765): peer messages, internal messages (our own proposals
and votes loop back through the same queue), and timeouts. Every
message that can change state is WAL'd before being acted on; an
EndHeightMessage delimits committed heights for crash recovery.

Transitions (state.go:909-1596):
  NewRound → Propose → Prevote → PrevoteWait → Precommit →
  PrecommitWait → Commit → (apply via BlockExecutor) → NewHeight

Signature verification throughout rides the BatchVerifier surfaces in
types/ (vote_set.py, validator_set.py) — on TPU for wide batches."""

from __future__ import annotations

import asyncio
import time as _time

from ..libs import clock as _clock
from dataclasses import dataclass

from ..config import ConsensusConfig
from ..libs import tracing
from ..libs.failpoints import hit as _failpoint
from ..libs.overload import CONTROLLER, PriorityFunnel
from ..libs.service import Service
from ..mempool import Mempool, NopMempool
from ..state import State as SmState
from ..state.execution import BlockExecutor
from ..store import BlockStore
from ..types.block import Block, BlockID, BlockIDFlag, Commit, NIL_BLOCK_ID, PartSet
from ..types.events import (
    EventBus, EventDataRoundState, EventDataVote,
)
from ..types.priv_validator import PrivValidator
from ..types.proposal import Proposal
from ..types.vote import Vote, VoteType
from ..types.vote_set import ConflictingVoteError, VoteSet, VoteSetError
from . import messages as m
from .cstypes import HeightVoteSet, RoundState, RoundStep
from .ticker import TimeoutTicker
from .wal import (
    EndHeightMessage, MsgInfo, RoundStateMessage, TimeoutInfo, WAL,
)


@dataclass
class _QueuedMsg:
    msg: object
    peer_id: str


class ConsensusState(Service):
    # Span handles for the per-height trace timeline. Class-level
    # defaults because update_to_state (which rolls them) runs inside
    # __init__ before any instance attribute could be assigned.
    _ht_span = None
    _step_span = None
    # Node label for height forensics: when non-empty, every height/
    # step span carries node=<label> and outgoing lifecycle messages
    # are origin-stamped with it. Set by the builder (moniker) after
    # construction; "" (the default) disables both — single-node use
    # needs no identity. Class-level for the same __init__ reason.
    trace_node = ""

    def __init__(self, config: ConsensusConfig, state: SmState,
                 block_exec: BlockExecutor, block_store: BlockStore,
                 mempool: Mempool | None = None, evpool=None,
                 wal: WAL | None = None, event_bus: EventBus | None = None,
                 speculation=None):
        super().__init__(name="consensus.State")
        self.config = config
        # Verify-ahead plane (consensus/speculation.py, wired by
        # node._build from [speculation]): fed the proposal BlockID at
        # _set_proposal and every current-height precommit at
        # _add_vote; BlockExecutor serves commit verdicts from it.
        self.speculation = speculation
        self.block_exec = block_exec
        self.block_store = block_store
        self.mempool = mempool or NopMempool()
        self.evpool = evpool
        self.wal = wal
        self.event_bus = event_bus
        self.priv_validator: PrivValidator | None = None
        self.priv_validator_address: bytes | None = None

        self.rs = RoundState()
        self.state: SmState | None = None
        # Priority-split bounded receive funnel (libs/overload.py):
        # state/vote/proposal messages block the sender when full
        # (backpressure, the reference's peerMsgQueue channel send);
        # block parts / catchup data shed when full — a gossip flood
        # must not starve round progression or grow memory unboundedly.
        self.peer_funnel = PriorityFunnel(
            config.peer_funnel_votes_size, config.peer_funnel_data_size,
            high_queue="consensus.funnel.votes",
            low_queue="consensus.funnel.data")
        self.internal_msg_queue: asyncio.Queue[_QueuedMsg] = asyncio.Queue(1000)
        self.ticker = TimeoutTicker()
        self._replay_mode = False
        # Serializes state transitions between the receive routine and
        # the vote micro-batch scheduler (the analogue of reference
        # cs.mtx — asyncio tasks interleave at awaits, and step
        # transitions contain awaits).
        self._state_mtx = asyncio.Lock()
        # Vote micro-batch scheduler buffers (SURVEY §7 latency budget):
        # (vote, peer_id, pub_key) triples awaiting one device batch.
        self._vote_buf: list = []
        self._vote_pending = asyncio.Event()
        CONTROLLER.register("consensus.vote_buf",
                            lambda: len(self._vote_buf),
                            config.vote_buf_max, owner=self)
        self._tpu_metrics = None  # lazy tpu_metrics() handle (hot path)
        self._height_done = asyncio.Event()  # pulsed on every commit
        # reactor hooks: fn(event_name, payload); events: "step",
        # "proposal", "block_part", "vote", "has_vote", and the
        # maverick split events "vote_split"/"proposal_split"
        self.broadcast_hooks: list = []
        # Maverick hook points (test/maverick analogue): height ->
        # Misbehavior; consulted at enter_propose/prevote/precommit
        # (consensus/misbehavior.py). Empty for honest nodes.
        self.misbehaviors: dict = {}
        # () -> behaviour.SwitchReporter | None; set by the reactor so
        # verified/rejected vote counts feed the peer trust metric.
        self.reporter_fn = lambda: None

        self.update_to_state(state)
        if state.last_block_height > 0:
            self.reconstruct_last_commit()

    # -- wiring --

    def set_priv_validator(self, pv: PrivValidator | None) -> None:
        self.priv_validator = pv
        self.priv_validator_address = (
            pv.get_pub_key().address() if pv is not None else None
        )

    def _broadcast(self, event: str, payload) -> None:
        for hook in self.broadcast_hooks:
            hook(event, payload)

    # -- lifecycle --

    async def on_start(self) -> None:
        if self.wal is not None:
            await self._catchup_replay()
        self.spawn(self._receive_routine(), name="cs-receive")
        if self.config.vote_batch_window_ms > 0:
            self.spawn(self._vote_scheduler(), name="cs-vote-batch")
        self._schedule_round0()

    async def on_stop(self) -> None:
        self.ticker.stop()
        # drop overload registrations: a stopped node's frozen queue
        # depths must not pin the process-wide level (owner-checked —
        # a newer in-process node's same-name entries survive)
        self.peer_funnel.close()
        CONTROLLER.unregister("consensus.vote_buf", owner=self)
        if self.speculation is not None:
            self.speculation.close()
        if self.wal is not None:
            self.wal.close()

    def _schedule_round0(self) -> None:
        # fire NewHeight immediately (start_time already accounts for
        # timeout_commit when coming off a commit)
        delay = max(self.rs.start_time - _clock.monotonic(), 0.0)
        self.ticker.schedule(TimeoutInfo(
            delay, self.rs.height, 0, int(RoundStep.NEW_HEIGHT)
        ))

    # -- state sync between heights (reference updateToState, state.go:566) --

    def update_to_state(self, state: SmState) -> None:
        rs = self.rs
        if rs.commit_round > -1 and 0 < rs.height != state.last_block_height:
            raise RuntimeError(
                f"update_to_state height mismatch {rs.height} vs "
                f"{state.last_block_height}"
            )
        last_precommits: VoteSet | None = None
        if rs.commit_round > -1 and rs.votes is not None:
            pc = rs.votes.precommits(rs.commit_round)
            if pc is None or not pc.has_two_thirds_majority():
                raise RuntimeError("commit round has no +2/3 precommits")
            last_precommits = pc

        height = state.last_block_height + 1
        if height == 1:
            height = state.initial_height

        validators = state.validators.copy()
        self.rs = RoundState(
            height=height,
            round=0,
            step=RoundStep.NEW_HEIGHT,
            start_time=_clock.monotonic() + (
                self.config.commit_timeout()
                if not self.config.skip_timeout_commit and rs.commit_round > -1
                else 0.0
            ),
            validators=validators,
            votes=HeightVoteSet(state.chain_id, height, validators),
            last_commit=last_precommits,
            last_validators=state.last_validators.copy(),
            commit_round=-1,
            locked_round=-1,
            valid_round=-1,
        )
        self.state = state
        if self.speculation is not None:
            self.speculation.retire_below(height)
        self._trace_new_height(height)

    def _trace_new_height(self, height: int) -> None:
        """Roll the per-height trace timeline: seal the previous
        height's step + root spans, open the next root. Manually
        managed (not a with-block) because a height's lifetime spans
        many handler invocations across two tasks (receive routine and
        vote scheduler)."""
        t = tracing.TRACER
        if self._step_span is not None:
            self._step_span.end()
            self._step_span = None
        if self._ht_span is not None:
            self._ht_span.end()
        # parent=NOOP_SPAN pins the root parentless: update_to_state
        # can run inside the vote scheduler's active vote_batch span,
        # and a height must never parent under a vote batch.
        self._ht_span = t.begin(tracing.CONSENSUS_HEIGHT,
                                parent=tracing.NOOP_SPAN, height=height)
        if self.trace_node:
            self._ht_span.set_attr("node", self.trace_node)

    def reconstruct_last_commit(self) -> None:
        """Rebuild rs.last_commit from the stored seen commit
        (reference state.go:549)."""
        assert self.state is not None
        seen = self.block_store.load_seen_commit(self.state.last_block_height)
        if seen is None:
            raise RuntimeError(
                f"no seen commit for height {self.state.last_block_height}"
            )
        last_precommits = VoteSet(
            self.state.chain_id, seen.height, seen.round,
            VoteType.PRECOMMIT, self.state.last_validators,
        )
        votes = []
        for idx, cs_sig in enumerate(seen.signatures):
            if cs_sig.is_absent():
                continue
            votes.append(Vote(
                type=VoteType.PRECOMMIT,
                height=seen.height,
                round=seen.round,
                block_id=cs_sig.block_id_for(seen.block_id),
                timestamp=cs_sig.timestamp,
                validator_address=cs_sig.validator_address,
                validator_index=idx,
                signature=cs_sig.signature,
            ))
        # One device batch for the whole stored commit instead of a
        # per-signature host loop (this is our own store, but the
        # reference verifies here too — state.go:549 via AddVote).
        from ..crypto.batch import BatchVerifier

        bv = BatchVerifier()
        vals = self.state.last_validators
        for v in votes:
            val = vals.get_by_index(v.validator_index)
            bv.add(val.pub_key, v.sign_bytes(self.state.chain_id), v.signature)
        _, verdicts = bv.verify()
        for v, ok in zip(votes, verdicts):
            if not ok:
                raise RuntimeError(
                    f"invalid signature in seen commit (val index "
                    f"{v.validator_index})"
                )
            last_precommits.add_vote(v, verify=False)
        if not last_precommits.has_two_thirds_majority():
            raise RuntimeError("seen commit lacks +2/3")
        self.rs.last_commit = last_precommits

    # -- the serialized event loop --

    async def _receive_routine(self) -> None:
        while True:
            internal = asyncio.ensure_future(self.internal_msg_queue.get())
            peer = asyncio.ensure_future(self.peer_funnel.get())
            timeout = asyncio.ensure_future(self.ticker.queue.get())
            done, pending = await asyncio.wait(
                [internal, peer, timeout],
                return_when=asyncio.FIRST_COMPLETED,
            )
            for p in pending:
                p.cancel()
            try:
                if internal in done:
                    qm = internal.result()
                    self._wal_write_sync(MsgInfo(
                        "", m.encode_consensus_msg(qm.msg)
                    ))
                    async with self._state_mtx:
                        await self._handle_msg(qm)
                if peer in done:
                    qm = peer.result()
                    self._wal_write(MsgInfo(
                        qm.peer_id, m.encode_consensus_msg(qm.msg)
                    ))
                    async with self._state_mtx:
                        await self._handle_msg(qm)
                if timeout in done:
                    ti = timeout.result()
                    self._wal_write_sync(ti)
                    async with self._state_mtx:
                        await self._handle_timeout(ti)
            except asyncio.CancelledError:
                raise
            except Exception:
                self.logger.exception("consensus handler failed; halting")
                raise

    def _wal_write(self, msg) -> None:
        if self.wal is not None and not self._replay_mode:
            self.wal.write(msg, _clock.time_ns())

    def _wal_write_sync(self, msg) -> None:
        if self.wal is not None and not self._replay_mode:
            self.wal.write_sync(msg, _clock.time_ns())

    async def _handle_msg(self, qm: _QueuedMsg) -> None:
        """Validation failures on a single message are logged and
        dropped — one byzantine peer must not halt the node (reference
        handleMsg logs setProposal/AddProposalBlockPart errors and
        continues). Errors inside step *transitions* still propagate:
        those are local invariant violations (reference panics →
        graceful halt)."""
        msg = qm.msg
        if isinstance(msg, m.ProposalMessage):
            try:
                self._set_proposal(msg.proposal)
            except Exception as e:
                self.logger.warning("rejecting proposal from %r: %s",
                                    qm.peer_id, e)
                return
            # parts may have completed before the proposal arrived
            if self.rs.proposal_complete():
                await self._proposal_completed()
        elif isinstance(msg, m.BlockPartMessage):
            try:
                added = self._add_proposal_block_part(msg)
            except Exception as e:
                self.logger.warning("rejecting block part from %r: %s",
                                    qm.peer_id, e)
                return
            if added and self.rs.step == RoundStep.COMMIT and \
                    self.rs.proposal_block is not None:
                # catchup: block completed while waiting in commit with
                # no Proposal (reference addProposalBlockPart →
                # tryFinalizeCommit when cs.Step == RoundStepCommit)
                await self._try_finalize_commit(self.rs.height)
            elif added and self.rs.proposal_complete():
                await self._proposal_completed()
        elif isinstance(msg, m.VoteMessage):
            if (self._replay_mode or self.config.vote_batch_window_ms <= 0
                    or not self._enqueue_vote(msg.vote, qm.peer_id)):
                await self._try_add_vote(msg.vote, qm.peer_id)
        else:
            self.logger.warning("unknown consensus msg %r", type(msg))

    async def _handle_timeout(self, ti: TimeoutInfo) -> None:
        rs = self.rs
        if ti.height != rs.height or ti.round < rs.round or (
            ti.round == rs.round and ti.step < int(rs.step)
        ):
            return  # stale
        step = RoundStep(ti.step)

        def fire(publisher_name):  # reference state.go:854-864
            if self.event_bus is not None:
                getattr(self.event_bus, publisher_name)(
                    EventDataRoundState(ti.height, ti.round, step.name))

        if step == RoundStep.NEW_HEIGHT:
            await self._enter_new_round(ti.height, 0)
        elif step == RoundStep.NEW_ROUND:
            await self._enter_propose(ti.height, 0)
        elif step == RoundStep.PROPOSE:
            fire("publish_timeout_propose")
            await self._enter_prevote(ti.height, ti.round)
        elif step == RoundStep.PREVOTE_WAIT:
            fire("publish_timeout_wait")
            await self._enter_precommit(ti.height, ti.round)
        elif step == RoundStep.PRECOMMIT_WAIT:
            fire("publish_timeout_wait")
            await self._enter_precommit(ti.height, ti.round)
            await self._enter_new_round(ti.height, ti.round + 1)

    # -- step transitions --

    def _new_step(self, step: RoundStep) -> None:
        self.rs.step = step
        if self._step_span is not None:
            self._step_span.end()
        self._step_span = tracing.TRACER.begin(
            tracing.consensus_step_kind(step.name), parent=self._ht_span,
            height=self.rs.height, round=self.rs.round)
        if self.trace_node:
            self._step_span.set_attr("node", self.trace_node)
        rsm = RoundStateMessage(self.rs.height, self.rs.round, int(step))
        self._wal_write(rsm)
        if self.event_bus is not None:
            self.event_bus.publish_new_round_step(EventDataRoundState(
                self.rs.height, self.rs.round, step.name
            ))
        self._broadcast("step", self.rs)

    async def _enter_new_round(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step != RoundStep.NEW_HEIGHT
        ):
            return
        if round_ > rs.round and rs.validators is not None:
            # advance proposer rotation for skipped rounds
            rs.validators.increment_proposer_priority(round_ - rs.round)
        rs.round = round_
        rs.step = RoundStep.NEW_ROUND
        if round_ > 0:
            # new round: prior proposal is void
            rs.proposal = None
            rs.proposal_block = None
            rs.proposal_block_parts = None
        rs.votes.set_round(round_)
        rs.triggered_timeout_precommit = False
        if self.event_bus is not None:
            self.event_bus.publish_new_round(EventDataRoundState(
                height, round_, rs.step.name
            ))
        await self._enter_propose(height, round_)

    async def _enter_propose(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= RoundStep.PROPOSE
        ):
            return
        rs.round = round_
        self._new_step(RoundStep.PROPOSE)

        self.ticker.schedule(TimeoutInfo(
            self.config.propose_timeout(round_), height, round_,
            int(RoundStep.PROPOSE),
        ))

        mb = self.misbehaviors.get(height)
        if mb is not None and await mb.enter_propose(self, height, round_):
            return

        if self._is_proposer() and self.priv_validator is not None:
            await self._decide_proposal(height, round_)

        if rs.proposal_complete():
            await self._enter_prevote(height, round_)

    def _is_proposer(self) -> bool:
        return (
            self.priv_validator_address is not None
            and self.rs.validators is not None
            and self.rs.validators.get_proposer().address
            == self.priv_validator_address
        )

    async def _decide_proposal(self, height: int, round_: int) -> None:
        """reference defaultDecideProposal (state.go:1063)."""
        rs = self.rs
        if rs.valid_block is not None:
            block, parts = rs.valid_block, rs.valid_block_parts
        else:
            commit = None
            if height == self.state.initial_height:
                commit = Commit(0, 0, NIL_BLOCK_ID, [])
            elif rs.last_commit is not None and rs.last_commit.has_two_thirds_majority():
                commit = rs.last_commit.make_commit()
            else:
                self.logger.error("cannot propose: no last commit")
                return
            block = self.block_exec.create_proposal_block(
                height, self.state, commit, self.priv_validator_address,
            )
            parts = block.make_part_set()

        block_id = BlockID(block.hash(), parts.header())
        proposal = Proposal(
            height=height, round=round_, pol_round=rs.valid_round,
            block_id=block_id, timestamp=_clock.time_ns(),
        )
        try:
            res = self.priv_validator.sign_proposal(self.state.chain_id,
                                                    proposal)
            if asyncio.iscoroutine(res):
                await res  # remote signer round-trip
        except Exception as e:
            self.logger.error("failed to sign proposal: %r", e)
            return
        # Forensics anchor: this node built the block for this round.
        # The collector picks the proposer's propose span by this attr.
        if self._step_span is not None:
            self._step_span.set_attr("proposer", True)
        self._send_internal(m.ProposalMessage(proposal))
        for i in range(parts.total):
            self._send_internal(m.BlockPartMessage(height, round_,
                                                   parts.get_part(i)))

    def _send_internal(self, msg) -> None:
        self.internal_msg_queue.put_nowait(_QueuedMsg(msg, ""))

    async def _proposal_completed(self) -> None:
        """Block fully received: react based on the current step
        (reference addProposalBlockPart, state.go:1775-1840)."""
        rs = self.rs
        prevotes = rs.votes.prevotes(rs.round)
        bid, has_maj = (prevotes.two_thirds_majority()
                        if prevotes is not None else (None, False))
        if has_maj and bid is not None and not bid.is_nil() and rs.valid_round < rs.round:
            if rs.proposal_block.hash() == bid.hash:
                rs.valid_round = rs.round
                rs.valid_block = rs.proposal_block
                rs.valid_block_parts = rs.proposal_block_parts
                if self.event_bus is not None:  # state.go:1450
                    self.event_bus.publish_valid_block(
                        EventDataRoundState(rs.height, rs.round,
                                            rs.step.name))
        if rs.step <= RoundStep.PROPOSE and rs.proposal_complete():
            await self._enter_prevote(rs.height, rs.round)
            if has_maj:
                await self._enter_precommit(rs.height, rs.round)
        elif rs.step == RoundStep.COMMIT:
            await self._try_finalize_commit(rs.height)

    async def _enter_prevote(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= RoundStep.PREVOTE
        ):
            return
        self._new_step(RoundStep.PREVOTE)
        mb = self.misbehaviors.get(height)
        if mb is not None and await mb.enter_prevote(self, height, round_):
            return
        # reference defaultDoPrevote (state.go:1229)
        if rs.locked_block is not None:
            await self._sign_add_vote(VoteType.PREVOTE, rs.locked_block.hash(),
                                rs.locked_block_parts.header())
        elif rs.proposal_block is None:
            await self._sign_add_vote(VoteType.PREVOTE, b"", None)
        else:
            try:
                await self.block_exec.validate_block_async(
                    self.state, rs.proposal_block)
                await self._sign_add_vote(
                    VoteType.PREVOTE, rs.proposal_block.hash(),
                    rs.proposal_block_parts.header(),
                )
            except Exception as e:
                self.logger.warning("invalid proposal block: %r", e)
                await self._sign_add_vote(VoteType.PREVOTE, b"", None)

    async def _enter_prevote_wait(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= RoundStep.PREVOTE_WAIT
        ):
            return
        self._new_step(RoundStep.PREVOTE_WAIT)
        self.ticker.schedule(TimeoutInfo(
            self.config.prevote_timeout(round_), height, round_,
            int(RoundStep.PREVOTE_WAIT),
        ))

    async def _enter_precommit(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= RoundStep.PRECOMMIT
        ):
            return
        self._new_step(RoundStep.PRECOMMIT)
        mb = self.misbehaviors.get(height)
        if mb is not None and await mb.enter_precommit(self, height, round_):
            return
        prevotes = rs.votes.prevotes(round_)
        bid, has_maj = (prevotes.two_thirds_majority()
                        if prevotes is not None else (None, False))

        if not has_maj:
            # no polka: precommit nil
            await self._sign_add_vote(VoteType.PRECOMMIT, b"", None)
            return

        if self.event_bus is not None:
            self.event_bus.publish_polka(EventDataRoundState(
                height, round_, rs.step.name
            ))

        if bid is None or bid.is_nil():
            # +2/3 prevoted nil: unlock and precommit nil (state.go:1320)
            if rs.locked_block is not None and self.event_bus is not None:
                self.event_bus.publish_unlock(EventDataRoundState(
                    height, round_, rs.step.name))
            rs.locked_round = -1
            rs.locked_block = None
            rs.locked_block_parts = None
            await self._sign_add_vote(VoteType.PRECOMMIT, b"", None)
            return

        # +2/3 for a block
        if rs.locked_block is not None and rs.locked_block.hash() == bid.hash:
            rs.locked_round = round_  # re-lock at this round
            if self.event_bus is not None:  # state.go:1327
                self.event_bus.publish_relock(EventDataRoundState(
                    height, round_, rs.step.name))
            await self._sign_add_vote(VoteType.PRECOMMIT, bid.hash,
                                bid.part_set_header)
            return
        if rs.proposal_block is not None and rs.proposal_block.hash() == bid.hash:
            try:
                await self.block_exec.validate_block_async(
                    self.state, rs.proposal_block)
            except Exception as e:
                self.logger.error("polka for invalid block: %r", e)
                await self._sign_add_vote(VoteType.PRECOMMIT, b"", None)
                return
            rs.locked_round = round_
            rs.locked_block = rs.proposal_block
            rs.locked_block_parts = rs.proposal_block_parts
            if self.event_bus is not None:
                self.event_bus.publish_lock(EventDataRoundState(
                    height, round_, rs.step.name
                ))
            await self._sign_add_vote(VoteType.PRECOMMIT, bid.hash,
                                bid.part_set_header)
            return

        # polka for a block we don't have: unlock, precommit nil, fetch
        if rs.locked_block is not None and self.event_bus is not None:
            self.event_bus.publish_unlock(EventDataRoundState(
                height, round_, rs.step.name))  # state.go:1362
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
            bid.part_set_header
        ):
            rs.proposal_block = None
            rs.proposal_block_parts = PartSet(
                bid.part_set_header.total, bid.part_set_header.hash
            )
        await self._sign_add_vote(VoteType.PRECOMMIT, b"", None)

    async def _enter_precommit_wait(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.triggered_timeout_precommit
        ):
            return
        rs.triggered_timeout_precommit = True
        self.ticker.schedule(TimeoutInfo(
            self.config.precommit_timeout(round_), height, round_,
            int(RoundStep.PRECOMMIT_WAIT),
        ))

    async def _enter_commit(self, height: int, commit_round: int) -> None:
        rs = self.rs
        if rs.height != height or rs.step >= RoundStep.COMMIT:
            return
        rs.commit_round = commit_round
        rs.commit_time = _clock.monotonic()
        # Forensics anchor: the instant the precommit quorum landed
        # here (enter_commit fires exactly on +2/3). Stamped on the
        # height root so the collector reads it without span joins.
        if self._ht_span is not None:
            self._ht_span.set_attr("precommit_quorum_ns",
                                   _time.perf_counter_ns())
        self._new_step(RoundStep.COMMIT)

        precommits = rs.votes.precommits(commit_round)
        bid, ok = precommits.two_thirds_majority()
        assert ok and bid is not None and not bid.is_nil()

        # if we have the block locked, promote it to proposal slots
        if rs.locked_block is not None and rs.locked_block.hash() == bid.hash:
            rs.proposal_block = rs.locked_block
            rs.proposal_block_parts = rs.locked_block_parts
        # if we don't have the full block yet, set up parts to receive it
        if rs.proposal_block is None or rs.proposal_block.hash() != bid.hash:
            if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
                bid.part_set_header
            ):
                rs.proposal_block = None
                rs.proposal_block_parts = PartSet(
                    bid.part_set_header.total, bid.part_set_header.hash
                )
                # advertise which part-set we now accept so peers'
                # catchup gossip starts feeding us the block
                # (reference enterCommit → PublishEventValidBlock →
                # reactor broadcasts NewValidBlockMessage)
                self._broadcast("valid_block", rs)
        await self._try_finalize_commit(height)

    async def _try_finalize_commit(self, height: int) -> None:
        rs = self.rs
        if rs.height != height:
            return
        precommits = rs.votes.precommits(rs.commit_round)
        bid, ok = precommits.two_thirds_majority()
        if not ok or bid is None or bid.is_nil():
            return
        if rs.proposal_block is None or rs.proposal_block.hash() != bid.hash:
            return  # don't have the block yet
        await self._finalize_commit(height)

    async def _finalize_commit(self, height: int) -> None:
        """reference finalizeCommit (state.go:1491)."""
        rs = self.rs
        precommits = rs.votes.precommits(rs.commit_round)
        bid, _ = precommits.two_thirds_majority()
        block, parts = rs.proposal_block, rs.proposal_block_parts

        block.validate_basic()

        # Explicit trace handoff: finalize can run from the receive
        # routine OR the vote scheduler task, so the commit step span
        # is attached by handle (not ambient context) — wal.fsync and
        # state.apply_block below then nest under it either way.
        with tracing.TRACER.attach(self._step_span):
            await self._finalize_commit_traced(height, bid, block, parts,
                                               precommits)

    async def _finalize_commit_traced(self, height, bid, block, parts,
                                      precommits) -> None:
        rs = self.rs
        if self.block_store.height < block.header.height:
            seen_commit = precommits.make_commit()
            self.block_store.save_block(block, parts, seen_commit)

        _failpoint("consensus.commit.block_saved")

        if self.wal is not None and not self._replay_mode:
            self.wal.write_sync(EndHeightMessage(height), _clock.time_ns())

        _failpoint("consensus.commit.wal_delimited")

        state_copy = self.state.copy()
        new_state, retain_height = await self.block_exec.apply_block(
            state_copy, bid, block
        )
        if retain_height > 0:
            try:
                pruned = self.block_store.prune_blocks(retain_height)
                self.block_exec.store.prune_states(1, retain_height)
                self.logger.debug("pruned %d blocks to %d", pruned, retain_height)
            except Exception as e:
                self.logger.error("prune failed: %r", e)

        self._record_commit_metrics(block, precommits,
                                    rs.proposal_block_parts)
        self.update_to_state(new_state)
        self._height_done.set()
        self._height_done = asyncio.Event()
        self._schedule_round0()

    def _record_commit_metrics(self, block, precommits, parts=None) -> None:
        """reference consensus/metrics.go recording (state.go:1612
        recordMetrics)."""
        from ..libs.metrics import consensus_metrics

        met = consensus_metrics()
        met.height.set(block.header.height)
        met.rounds.set(self.rs.round)
        vals = self.rs.validators
        met.validators.set(len(vals))
        met.validators_power.set(vals.total_voting_power())
        missing = missing_power = 0
        for i in range(len(vals)):
            if precommits.get_by_index(i) is None:
                missing += 1
                missing_power += vals.validators[i].voting_power
        met.missing_validators.set(missing)
        met.missing_validators_power.set(missing_power)
        # evidence in THIS block tallies byzantine signers (set
        # unconditionally: the gauges must drop back to 0 on
        # evidence-free blocks, like the reference's)
        byz = {e.vote_a.validator_address
               for e in block.evidence.evidence
               if hasattr(e, "vote_a")}
        met.byzantine_validators.set(len(byz))
        met.byzantine_validators_power.set(sum(
            v.voting_power for v in vals.validators
            if v.address in byz))
        if self.priv_validator_address is not None and \
                vals.has_address(self.priv_validator_address):
            idx, own = vals.get_by_address(self.priv_validator_address)
            met.validator_power.set(own.voting_power)
            if precommits.get_by_index(idx) is not None:
                met.validator_last_signed_height.set(block.header.height)
            else:
                met.validator_missed_blocks.inc()
        ntx = len(block.data.txs)
        met.num_txs.set(ntx)
        met.total_txs.inc(ntx)
        # The part set already holds the serialized size — re-encoding
        # the whole block here would add avoidable per-commit latency.
        if parts is not None:
            met.block_size_bytes.set(parts.byte_size)
        prev = self.block_store.load_block_meta(block.header.height - 1)
        if prev is not None:
            met.block_interval_seconds.observe(
                max(block.header.time - prev.header.time, 0) / 1e9
            )

    # -- proposals & parts --

    def _set_proposal(self, proposal: Proposal) -> None:
        """reference defaultSetProposal (state.go:1719)."""
        rs = self.rs
        if rs.proposal is not None:
            return
        if proposal.height != rs.height or proposal.round != rs.round:
            return
        proposal.validate_basic()
        if proposal.pol_round != -1 and not (
            0 <= proposal.pol_round < proposal.round
        ):
            raise VoteSetError("invalid POL round")
        proposer = rs.validators.get_proposer()
        if not proposer.pub_key.verify_signature(
            proposal.sign_bytes(self.state.chain_id), proposal.signature
        ):
            raise VoteSetError("invalid proposal signature")
        rs.proposal = proposal
        if rs.proposal_block_parts is None:
            rs.proposal_block_parts = PartSet(
                proposal.block_id.part_set_header.total,
                proposal.block_id.part_set_header.hash,
            )
        if self.speculation is not None:
            # the precommit sign-byte template for this height is now
            # fully determined — start the verify-ahead pipeline
            self.speculation.begin_height(
                self.state.chain_id, rs.validators, rs.height,
                proposal.round, proposal.block_id)
        self._broadcast("proposal", proposal)

    def _add_proposal_block_part(self, msg: m.BlockPartMessage) -> bool:
        rs = self.rs
        if msg.height != rs.height:
            return False
        if rs.proposal_block_parts is None:
            return False
        added = rs.proposal_block_parts.add_part(msg.part)
        if added:
            from ..libs.metrics import consensus_metrics

            consensus_metrics().block_parts.inc()
        if added and rs.proposal_block_parts.is_complete():
            data = rs.proposal_block_parts.assemble()
            block = Block.from_bytes(data)
            # The part-set header (each part merkle-proven into it) is
            # the authoritative identity of what we accepted. Compare
            # against the proposal only when the proposal refers to
            # THIS part set: during commit-time catch-up the parts
            # carry the DECIDED block (header installed by
            # _enter_commit from the +2/3 block id), which legitimately
            # differs from a stale earlier-round proposal — rejecting
            # it wedged a late-joining node behind a racing net for
            # good (found by the statesync e2e under suite load).
            if (rs.proposal is not None and
                    rs.proposal_block_parts.has_header(
                        rs.proposal.block_id.part_set_header) and
                    block.hash() != rs.proposal.block_id.hash):
                raise VoteSetError(
                    "completed block hash != proposal block id")
            rs.proposal_block = block
            # Forensics anchor: first full part set on this node (the
            # proposer hits it too, via its own internal loopback).
            prior = getattr(self._ht_span, "attrs", None) or {}
            if self._ht_span is not None and "parts_complete_ns" not in prior:
                self._ht_span.set_attr("parts_complete_ns",
                                       _time.perf_counter_ns())
            if self.event_bus is not None:
                self.event_bus.publish_complete_proposal(EventDataRoundState(
                    rs.height, rs.round, "CompleteProposal"
                ))
            self._broadcast("block_part", msg)
        elif added:
            self._broadcast("block_part", msg)
        return added

    # -- votes --

    # -- vote micro-batch scheduler --
    #
    # The TPU latency-budget restructuring SURVEY §7 names: votes are
    # not verified one-at-a-time under the VoteSet lock (reference
    # vote_set.go:203); they accumulate for vote_batch_window_ms (or
    # until vote_batch_max) and verify as ONE device batch in a worker
    # thread, then commit under the state mutex with verify=False.
    # Duplicate/conflict semantics are preserved because add_vote
    # re-runs every non-signature check at commit time; the pubkey each
    # lane was verified against is resolved per (height, index), and a
    # height's validator mapping never changes, so a vote cannot be
    # committed against a different key than it was verified with.

    def _enqueue_vote(self, vote: Vote, peer_id: str) -> bool:
        """True if the vote was queued for batch verification (or is a
        known gossip duplicate); False -> caller takes the sync path."""
        resolved = self._resolve_vote_pubkey(vote)
        if resolved is None:
            return False
        pk, vals = resolved
        vs = self._target_vote_set(vote)
        if vs is not None and vs.is_duplicate(vote):
            return True  # already tallied; don't burn a device lane
        if len(self._vote_buf) >= self.config.vote_buf_max:
            if not peer_id:
                # our OWN vote (internal loopback): no peer holds it,
                # so a shed here would silently skip our prevote/
                # precommit for the round — take the sync path instead
                return False
            # Bounded scheduler buffer: shedding a PEER vote (not the
            # sync path — seconds of on-loop crypto is the failure
            # mode this exists to prevent) is safe because gossip
            # re-sends votes the votebits reconciliation shows we
            # still lack.
            CONTROLLER.shed("consensus.vote_buf")
            self._vote_pending.set()  # make sure the drain is awake
            return True
        # vals rides along so the scheduler can route the batch
        # through the expanded structured path (validator-index lanes
        # against the SAME set pk was resolved from).
        self._vote_buf.append((vote, peer_id, pk, vals))
        m = self._tpu_metrics
        if m is None:
            from ..libs.metrics import tpu_metrics

            self._tpu_metrics = m = tpu_metrics()
        m.verify_queue_depth.set(len(self._vote_buf))
        self._vote_pending.set()
        return True

    def _target_vote_set(self, vote: Vote):
        rs = self.rs
        if vote.height + 1 == rs.height and vote.type == VoteType.PRECOMMIT:
            return rs.last_commit
        if vote.height == rs.height and rs.votes is not None:
            return (rs.votes.prevotes(vote.round)
                    if vote.type == VoteType.PREVOTE
                    else rs.votes.precommits(vote.round))
        return None

    def _resolve_vote_pubkey(self, vote: Vote):
        """(pubkey, validator_set) this vote must verify against, or
        None if it is not addressable right now (wrong height, unknown
        index...) — such votes take the synchronous path, which
        rejects them cheaply before any signature work."""
        rs = self.rs
        if vote.height + 1 == rs.height and vote.type == VoteType.PRECOMMIT:
            vals = (rs.last_commit.val_set
                    if rs.last_commit is not None else None)
        elif vote.height == rs.height:
            vals = rs.validators
        else:
            return None
        if vals is None:
            return None
        val = vals.get_by_index(vote.validator_index)
        if val is None or val.address != vote.validator_address:
            return None
        return val.pub_key, vals

    async def _vote_scheduler(self) -> None:
        from ..libs.metrics import consensus_metrics, tpu_metrics

        met = consensus_metrics()
        tmet = tpu_metrics()
        loop = asyncio.get_running_loop()
        while True:
            await self._vote_pending.wait()
            t_window = _time.perf_counter()
            window = self.config.vote_batch_window_ms / 1e3
            # Early flush under pressure: once the buffer passes half
            # its bound, waiting out the batching window only deepens
            # the backlog (and the shedding it causes) — verify NOW.
            if window > 0 and \
                    len(self._vote_buf) < self.config.vote_batch_max and \
                    len(self._vote_buf) * 2 < self.config.vote_buf_max:
                await asyncio.sleep(window)
            batch, self._vote_buf = self._vote_buf, []
            tmet.verify_queue_depth.set(0)
            self._vote_pending.clear()
            if not batch:
                continue
            met.vote_batch_wait_seconds.observe(
                _time.perf_counter() - t_window)
            try:
                await self._verify_and_commit_batch(batch, met, loop)
            except asyncio.CancelledError:
                raise
            except Exception:
                # One bad batch (device error, malformed-but-decodable
                # vote, transient executor failure) must not kill this
                # task: the node would keep enqueueing votes that no
                # one ever verifies — consensus halting while gossip
                # and RPC still look healthy. Degrade to per-vote HOST
                # verification — but still OFF the event loop and
                # outside _state_mtx (a device failure during a
                # 10k-sig burst must not turn into seconds of on-loop
                # crypto that blocks gossip, timeouts and RPC); the
                # mutex is then held only per-vote for the tally.
                self.logger.exception(
                    "vote batch of %d failed; degrading to host-verify "
                    "off-loop", len(batch))
                chain_id = self.state.chain_id

                def _host_verify_all(b=batch, cid=chain_id):
                    out = []
                    for vote, _pid, pk, _vals in b:
                        try:
                            out.append(pk.verify_signature(
                                vote.sign_bytes(cid), vote.signature))
                        except Exception:
                            out.append(False)
                    return out

                try:
                    verdicts = await loop.run_in_executor(
                        None, _host_verify_all)
                except Exception:
                    self.logger.exception(
                        "degraded host verify failed; dropping batch")
                    continue
                per_peer: dict[str, list[int]] = {}
                for (vote, peer_id, _, _), ok in zip(batch, verdicts):
                    if peer_id:
                        counts = per_peer.setdefault(peer_id, [0, 0])
                        counts[0 if ok else 1] += 1
                    if not ok:
                        self.logger.debug(
                            "degraded path rejected vote from %r",
                            peer_id)
                        continue
                    try:
                        async with self._state_mtx:
                            await self._try_add_vote(vote, peer_id,
                                                     preverified=True)
                    except Exception:
                        self.logger.exception(
                            "dropping unprocessable vote from %r", peer_id)
                # Same trust feedback as the happy path: a peer
                # streaming invalid votes must not farm free host
                # crypto just because the device is down. Guarded per
                # peer like the happy path — an exception escaping
                # this except-handler would kill the scheduler task,
                # the silent-halt mode this fallback exists to prevent.
                rep = self.reporter_fn()
                if rep is not None:
                    for peer_id, (good, bad) in per_peer.items():
                        try:
                            rep.observe(peer_id, good=good, bad=bad)
                            if bad:
                                await rep.enforce(
                                    peer_id, "invalid vote signature")
                        except Exception:
                            self.logger.exception(
                                "trust feedback failed for %r", peer_id)

    def _batch_verdicts(self, batch, chain_id):
        """Per-lane verdicts for a vote micro-batch (runs in the
        executor, off the event loop).

        Lanes group by the validator set each vote resolved against
        (current height vs last-commit precommits); each group routes
        through ValidatorSet._batch_verify_lanes — the same
        structured->bytes->host ladder every commit-verify call site
        uses, so big all-ed25519 bursts hit the expanded comb tables
        with device-assembled sign bytes (VoteSignBatch: one template
        group per distinct (type, height, round, block_id)) instead of
        shipping full sign-byte rows through the general kernel."""
        import numpy as _np

        from ..types.sign_batch import VoteSignBatch

        verdicts = _np.zeros(len(batch), bool)
        groups: dict[int, tuple] = {}
        for j, (vote, _peer, _pk, vals) in enumerate(batch):
            entry = groups.get(id(vals))
            if entry is None:
                groups[id(vals)] = entry = (vals, [])
            entry[1].append(j)
        for vals, idxs in groups.values():
            votes = [batch[j][0] for j in idxs]
            lanes = [v.validator_index for v in votes]
            sigs = [v.signature for v in votes]
            msgs = vals.structured_or_bytes(
                lanes,
                lambda: VoteSignBatch(chain_id, votes),
                lambda: [v.sign_bytes(chain_id) for v in votes],
            )
            _, group_verdicts = vals._batch_verify_lanes(
                lanes, msgs, sigs)
            verdicts[_np.asarray(idxs)] = _np.asarray(group_verdicts)
        return verdicts

    async def _verify_and_commit_batch(self, batch, met, loop) -> None:
        met.vote_batch_size.observe(len(batch))
        chain_id = self.state.chain_id
        with tracing.TRACER.span(tracing.CONSENSUS_VOTE_BATCH,
                                 lanes=len(batch)):
            if len(batch) > 1:
                # Device (or host-oracle) verify OFF the event loop:
                # gossip, RPC and timeouts keep running during a
                # 10k-lane burst. TRACER.wrap carries the vote-batch
                # span into the executor thread so the crypto spans
                # recorded there keep their consensus lineage.
                verdicts = await loop.run_in_executor(
                    None, tracing.TRACER.wrap(self._batch_verdicts),
                    batch, chain_id)
            else:
                verdicts = self._batch_verdicts(batch, chain_id)
        per_peer: dict[str, list[int]] = {}  # peer -> [good, bad]
        for (vote, peer_id, _, _), ok in zip(batch, verdicts):
            if peer_id:
                counts = per_peer.setdefault(peer_id, [0, 0])
                counts[0 if ok else 1] += 1
            if not ok:
                self.logger.debug(
                    "batch-verify rejected vote from %r (val %s)",
                    peer_id, vote.validator_address.hex(),
                )
                continue
            # Per-vote containment: once tallying has begun, one
            # vote's commit failure must not throw the WHOLE batch to
            # the degraded fallback — that would re-verify and
            # re-report trust for votes already processed here.
            try:
                async with self._state_mtx:
                    await self._try_add_vote(vote, peer_id,
                                             preverified=True)
            except Exception:
                self.logger.exception(
                    "dropping unprocessable vote from %r", peer_id)
        # Trust metric feedback on VERIFIED outcomes: credit good
        # lanes, debit rejected ones, disconnect on collapsed trust
        # (behaviour.py; a peer streaming well-formed-but-invalid
        # votes decays to a stop instead of farming reputation).
        rep = self.reporter_fn()
        if rep is not None:
            for peer_id, (good, bad) in per_peer.items():
                try:
                    rep.observe(peer_id, good=good, bad=bad)
                    if bad:
                        await rep.enforce(peer_id,
                                          "invalid vote signature")
                except Exception:
                    self.logger.exception(
                        "trust feedback failed for %r", peer_id)

    async def _try_add_vote(self, vote: Vote, peer_id: str,
                            preverified: bool = False) -> bool:
        """reference tryAddVote (state.go:1845): conflicting votes
        become evidence; late precommits for the last height extend
        rs.last_commit."""
        try:
            return await self._add_vote(vote, peer_id, preverified)
        except ConflictingVoteError as e:
            if self.priv_validator_address == vote.validator_address:
                self.logger.error(
                    "found conflicting vote from ourselves; height %d",
                    vote.height,
                )
                return False
            if self.evpool is not None and e.existing is not None:
                from ..state import median_time
                from ..types.evidence import DuplicateVoteEvidence

                # The evidence timestamp must equal the header time of
                # the block at the EVIDENCE height — peers' pools reject
                # any other timestamp (reference state.go:1868-76 uses
                # the LastCommit median; we additionally handle the
                # late-vote case, where the conflicting vote is for the
                # already-committed height and that block's time is
                # simply state.last_block_time).
                if vote.height == self.state.last_block_height or \
                        self.rs.last_commit is None:
                    ts = self.state.last_block_time
                    vals = self.rs.last_validators
                else:
                    ts = median_time(self.rs.last_commit.make_commit(),
                                     self.rs.last_validators)
                    vals = self.rs.validators
                ev = DuplicateVoteEvidence.from_votes(
                    e.existing, vote, ts, vals,
                )
                self.evpool.add_evidence_from_consensus(ev)
            return False
        except VoteSetError as e:
            self.logger.debug("vote rejected: %s", e)
            return False

    async def _add_vote(self, vote: Vote, peer_id: str,
                        preverified: bool = False) -> bool:
        rs = self.rs
        verify = not preverified
        # late precommit for the previous height (state.go:1901)
        if vote.height + 1 == rs.height and vote.type == VoteType.PRECOMMIT:
            if rs.step != RoundStep.NEW_HEIGHT or rs.last_commit is None:
                return False
            added = rs.last_commit.add_vote(vote, verify=verify)
            if added:
                self._publish_vote(vote)
            return added
        if vote.height != rs.height:
            return False

        added = rs.votes.add_vote(vote, peer_id, verify=verify)
        if not added:
            return False
        if self.speculation is not None and \
                vote.type == VoteType.PRECOMMIT:
            # patch the verify-ahead lane (conflicting/nil votes are
            # handled inside: they poison the lane, never serve)
            self.speculation.observe_precommit(vote)
        self._publish_vote(vote)
        self._broadcast("has_vote", m.HasVoteMessage(
            vote.height, vote.round, int(vote.type), vote.validator_index
        ))

        if vote.type == VoteType.PREVOTE:
            await self._on_prevote_added(vote)
        else:
            await self._on_precommit_added(vote)
        return True

    def _publish_vote(self, vote: Vote) -> None:
        if self.event_bus is not None:
            self.event_bus.publish_vote(EventDataVote(vote))
        self._broadcast("vote", vote)

    async def _on_prevote_added(self, vote: Vote) -> None:
        """reference addVote prevote handling (state.go:1950-2032)."""
        rs = self.rs
        prevotes = rs.votes.prevotes(vote.round)
        bid, has_maj = prevotes.two_thirds_majority()

        if has_maj and bid is not None and not bid.is_nil():
            # unlock if a later polka contradicts our lock (state.go:1965)
            if (rs.locked_block is not None
                    and rs.locked_round < vote.round <= rs.round
                    and rs.locked_block.hash() != bid.hash):
                rs.locked_round = -1
                rs.locked_block = None
                rs.locked_block_parts = None
                if self.event_bus is not None:  # state.go:1987
                    self.event_bus.publish_unlock(EventDataRoundState(
                        rs.height, rs.round, rs.step.name))
            # track valid block (state.go:1984)
            if rs.valid_round < vote.round <= rs.round:
                if rs.proposal_block is not None and rs.proposal_block.hash() == bid.hash:
                    rs.valid_round = vote.round
                    rs.valid_block = rs.proposal_block
                    rs.valid_block_parts = rs.proposal_block_parts
                    if self.event_bus is not None:  # state.go:2013
                        self.event_bus.publish_valid_block(
                            EventDataRoundState(rs.height, rs.round,
                                                rs.step.name))
                elif rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
                    bid.part_set_header
                ):
                    # polka for a block we don't have: start fetching it
                    rs.proposal_block = None
                    rs.proposal_block_parts = PartSet(
                        bid.part_set_header.total, bid.part_set_header.hash
                    )

        if rs.round < vote.round and prevotes.has_two_thirds_any():
            await self._enter_new_round(rs.height, vote.round)
        elif rs.round == vote.round and rs.step >= RoundStep.PREVOTE:
            if has_maj and (rs.proposal_complete() or bid is None or bid.is_nil()):
                await self._enter_precommit(rs.height, vote.round)
            elif prevotes.has_two_thirds_any() and rs.step == RoundStep.PREVOTE:
                await self._enter_prevote_wait(rs.height, vote.round)
        elif (rs.proposal is not None
              and 0 <= rs.proposal.pol_round == vote.round
              and rs.step == RoundStep.PROPOSE
              and rs.proposal_complete()):
            await self._enter_prevote(rs.height, rs.round)

    async def _on_precommit_added(self, vote: Vote) -> None:
        """reference addVote precommit handling (state.go:2034-2067)."""
        rs = self.rs
        precommits = rs.votes.precommits(vote.round)
        bid, has_maj = precommits.two_thirds_majority()
        if has_maj:
            if bid is None or bid.is_nil():
                # +2/3 precommitted nil: straight to the next round
                await self._enter_new_round(rs.height, vote.round + 1)
            else:
                await self._enter_new_round(rs.height, vote.round)
                await self._enter_precommit(rs.height, vote.round)
                await self._enter_commit(rs.height, vote.round)
                if self.config.skip_timeout_commit and precommits.has_all():
                    await self._enter_new_round(self.rs.height, 0)
        elif rs.round <= vote.round and precommits.has_two_thirds_any():
            await self._enter_new_round(rs.height, vote.round)
            await self._enter_precommit_wait(rs.height, vote.round)

    async def _sign_add_vote(self, type_: VoteType, hash_: bytes,
                             part_set_header) -> Vote | None:
        """reference signAddVote (state.go:2139)."""
        if self.priv_validator is None or self.rs.validators is None:
            return None
        if not self.rs.validators.has_address(self.priv_validator_address):
            return None
        idx, _ = self.rs.validators.get_by_address(self.priv_validator_address)
        block_id = (
            BlockID(hash_, part_set_header) if hash_ else None
        )
        vote = Vote(
            type=type_,
            height=self.rs.height,
            round=self.rs.round,
            block_id=block_id,
            timestamp=self._vote_time(),
            validator_address=self.priv_validator_address,
            validator_index=idx,
        )
        try:
            res = self.priv_validator.sign_vote(self.state.chain_id, vote)
            if asyncio.iscoroutine(res):
                await res  # remote signer round-trip
        except Exception as e:
            self.logger.error("failed to sign vote: %r", e)
            return None
        self._send_internal(m.VoteMessage(vote))
        return vote

    def _vote_time(self) -> int:
        """now, but strictly after the block we're voting on
        (reference voteTime, state.go:2120)."""
        now = _clock.time_ns()
        time_iota = max(
            self.state.consensus_params.block.time_iota_ms, 1
        ) * 1_000_000
        min_time = 0
        if self.rs.locked_block is not None:
            min_time = self.rs.locked_block.header.time + time_iota
        elif self.rs.proposal_block is not None:
            min_time = self.rs.proposal_block.header.time + time_iota
        return max(now, min_time)

    # -- WAL catchup replay (reference consensus/replay.go:94) --

    async def _catchup_replay(self) -> None:
        assert self.wal is not None
        self.wal.repair()
        height = self.state.last_block_height
        msgs, found = self.wal.search_for_end_height(height)
        if not found and height > 0:
            return  # nothing in-flight
        self._replay_mode = True
        try:
            for tm in msgs:
                inner = tm.msg
                if isinstance(inner, EndHeightMessage):
                    break
                if isinstance(inner, MsgInfo):
                    try:
                        cmsg = m.decode_consensus_msg(inner.msg_bytes)
                    except ValueError:
                        continue
                    await self._handle_msg(_QueuedMsg(cmsg, inner.peer_id))
                elif isinstance(inner, TimeoutInfo):
                    # timeouts are re-derived live, not replayed
                    pass
        finally:
            self._replay_mode = False
        self.logger.info("replayed %d WAL messages for height %d",
                         len(msgs), self.rs.height)

    # -- public API (reactor / rpc) --

    def _funnel_class(self, msg) -> bool:
        """True = high class (round-critical: votes, proposals — the
        messages that move steps); False = low class (bulk data that
        is re-gossiped on demand and may be shed under flood)."""
        return isinstance(msg, (m.VoteMessage, m.ProposalMessage))

    def _shed_duplicate_vote(self, msg) -> bool:
        """Under funnel pressure, a vote already tallied is the first
        thing to shed: it would burn a funnel slot and a device lane
        to change nothing. Only consulted once the funnel is half
        full — the normal path stays probe-free."""
        if not isinstance(msg, m.VoteMessage) or \
                not self.peer_funnel.pressured():
            return False
        vs = self._target_vote_set(msg.vote)
        if vs is not None and vs.is_duplicate(msg.vote):
            # advisory: the drop is counted, but losing an ALREADY-
            # TALLIED duplicate is not information loss — it must not
            # flip the process-wide level to "shedding" during the
            # ordinary multi-peer gossip redundancy of a busy round
            CONTROLLER.shed("consensus.funnel.votes", advisory=True)
            return True
        return False

    async def add_peer_msg(self, msg, peer_id: str) -> None:
        """Priority-aware admission into the bounded funnel. High
        class blocks when full — backpressure onto the calling peer's
        recv loop, matching the reference's `cs.peerMsgQueue <-
        msgInfo` channel send (state.go:456; the 10k-validator scale
        test pinned that a burst must slow the sender, not raise).
        Low class (block parts / catchup) sheds when full instead:
        missing parts are re-requested by gossip, and a data flood
        must never wedge votes behind it."""
        qm = _QueuedMsg(msg, peer_id)
        if self._funnel_class(msg):
            if self._shed_duplicate_vote(msg):
                return
            await self.peer_funnel.put_high(qm)
        else:
            self.peer_funnel.put_low(qm)

    def add_peer_msg_nowait(self, msg, peer_id: str) -> None:
        """Non-blocking variant for sync call sites (test hooks);
        raises QueueFull for the high class instead of applying
        backpressure (the low class sheds, as in add_peer_msg)."""
        qm = _QueuedMsg(msg, peer_id)
        if self._funnel_class(msg):
            if self._shed_duplicate_vote(msg):
                return
            self.peer_funnel.put_high_nowait(qm)
        else:
            self.peer_funnel.put_low(qm)

    def get_round_state(self) -> RoundState:
        return self.rs

    async def wait_for_height(self, height: int, timeout: float = 60.0) -> None:
        deadline = _clock.monotonic() + timeout
        while self.rs.height <= height:
            remaining = deadline - _clock.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"height {height} not reached (at {self.rs.height})"
                )
            try:
                await asyncio.wait_for(
                    asyncio.shield(self._height_done.wait()), remaining
                )
            except asyncio.TimeoutError:
                raise TimeoutError(
                    f"height {height} not reached (at {self.rs.height})"
                )
