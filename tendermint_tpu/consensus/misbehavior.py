"""Pluggable per-height consensus misbehavior — the "maverick" node
(reference: test/maverick/consensus/misbehavior.go, test/maverick/README).

A Misbehavior overrides individual state-machine steps for heights it
is scheduled at (`ConsensusState.misbehaviors: {height: Misbehavior}`).
Hook methods return True when they fully handled the step (the default
logic is skipped), False to fall through — so one misbehavior can
override a single step and inherit the rest.

Unlike the reference (which forks the whole consensus package to embed
hooks), the hooks live in the ONE state machine behind two `if` lines
— the production step logic stays the only implementation, and a
maverick node is just a node with a non-empty schedule. Signing of the
conflicting artifact bypasses the PrivValidator's double-sign
protection by signing with the raw key — exactly what real byzantine
hardware would do; the protection exists to stop honest mistakes, not
attackers.
"""

from __future__ import annotations

from ..libs import clock as _clock

from ..types.block import BlockID
from ..types.proposal import Proposal
from ..types.vote import Vote, VoteType
from . import messages as m

MISBEHAVIORS: dict[str, type] = {}


def register(cls):
    MISBEHAVIORS[cls.name] = cls
    return cls


class Misbehavior:
    """Default: every hook falls through to the honest implementation."""

    name = "default"

    async def enter_propose(self, cs, height: int, round_: int) -> bool:
        return False

    async def enter_prevote(self, cs, height: int, round_: int) -> bool:
        return False

    async def enter_precommit(self, cs, height: int, round_: int) -> bool:
        return False


def _raw_sign_vote(cs, vote: Vote) -> Vote:
    """Sign a vote with the validator's raw key, bypassing the
    PrivValidator's last-sign-state double-sign protection (a byzantine
    signer is not constrained by its own safety belt)."""
    priv = cs.priv_validator.priv_key  # MockPV/FilePV both expose it
    vote.signature = priv.sign(vote.sign_bytes(cs.state.chain_id))
    return vote


def _make_vote(cs, type_: VoteType, hash_: bytes, psh) -> Vote:
    idx, _ = cs.rs.validators.get_by_address(cs.priv_validator_address)
    return Vote(
        type=type_,
        height=cs.rs.height,
        round=cs.rs.round,
        block_id=BlockID(hash_, psh) if hash_ else None,
        timestamp=_clock.time_ns(),
        validator_address=cs.priv_validator_address,
        validator_index=idx,
    )


@register
class DoublePrevote(Misbehavior):
    """Prevote BOTH the proposal block and nil in the same round
    (reference DoublePrevoteMisbehavior): half the peers see each, and
    honest nodes that gossip them to each other assemble
    DuplicateVoteEvidence from the conflict."""

    name = "double-prevote"

    async def enter_prevote(self, cs, height: int, round_: int) -> bool:
        rs = cs.rs
        if cs.priv_validator is None or rs.validators is None or \
                not rs.validators.has_address(cs.priv_validator_address):
            return False
        if rs.locked_block is not None or rs.proposal_block is None:
            return False  # behave honestly without a target block
        block_vote = _raw_sign_vote(cs, _make_vote(
            cs, VoteType.PREVOTE, rs.proposal_block.hash(),
            rs.proposal_block_parts.header()))
        nil_vote = _raw_sign_vote(cs, _make_vote(
            cs, VoteType.PREVOTE, b"", None))
        # Count the block vote ourselves; split the conflict across
        # peers (even -> block, odd -> nil).
        cs._send_internal(m.VoteMessage(block_vote))
        cs._broadcast("vote_split", (m.VoteMessage(block_vote),
                                     m.VoteMessage(nil_vote)))
        cs.logger.warning("MAVERICK double-prevote at %d/%d",
                          height, round_)
        return True


@register
class DoublePropose(Misbehavior):
    """As proposer, sign TWO different proposals for the same
    height/round and send one to each half of the peers."""

    name = "double-propose"

    async def enter_propose(self, cs, height: int, round_: int) -> bool:
        # Round 0 only, one-shot: a split proposal usually fails its
        # round (half the peers hold each block, no polka), and if
        # EVERY round's rotating proposer re-equivocated the height
        # would livelock. One equivocation is the attack; all later
        # rounds/proposers proceed honestly and consensus recovers.
        if round_ != 0:
            cs.misbehaviors.pop(height, None)
            return False
        if not cs._is_proposer() or cs.priv_validator is None:
            return False
        cs.misbehaviors.pop(height, None)
        rs = cs.rs
        from ..types.block import Commit, NIL_BLOCK_ID

        if height == cs.state.initial_height:
            commit = Commit(0, 0, NIL_BLOCK_ID, [])
        elif rs.last_commit is not None and \
                rs.last_commit.has_two_thirds_majority():
            commit = rs.last_commit.make_commit()
        else:
            return False
        priv = cs.priv_validator.priv_key
        proposals = []
        for variant in (b"", b"\xfe maverick fork \xfe"):
            block = cs.block_exec.create_proposal_block(
                height, cs.state, commit, cs.priv_validator_address)
            if variant:
                block.data.txs = list(block.data.txs) + [variant]
                block.header.data_hash = block.data.hash()
            parts = block.make_part_set()
            prop = Proposal(
                height=height, round=round_, pol_round=rs.valid_round,
                block_id=BlockID(block.hash(), parts.header()),
                timestamp=_clock.time_ns(),
            )
            prop.signature = priv.sign(
                prop.sign_bytes(cs.state.chain_id))
            proposals.append((prop, parts))
        # Feed ourselves the first; split the two across peers.
        prop_a, parts_a = proposals[0]
        cs._send_internal(m.ProposalMessage(prop_a))
        for i in range(parts_a.total):
            cs._send_internal(m.BlockPartMessage(
                height, round_, parts_a.get_part(i)))
        cs._broadcast("proposal_split", (proposals[0], proposals[1]))
        cs.logger.warning("MAVERICK double-propose at %d/%d",
                          height, round_)
        return True
