"""SpeculationPlane: verify-ahead commit pre-verification.

Round-4 silicon left the kernel off the critical path (39.7 ms device
exec vs 169.5 ms end-to-end at 10,240 lanes): what remains is host
packing, per-launch transfer, and the strictly serial verify-then-use
sequence. This plane removes commit verification from the critical
path entirely by STARTING it before the commit is needed:

  1. As soon as height H's proposal BlockID is known
     (ConsensusState._set_proposal), the plane pre-packs the TEMPLATE
     precommit sign bytes for every validator — within one commit the
     canonical (pre, suf) halves are fixed (types/canonical.py
     vote_sign_parts); only the timestamp varint varies per vote.
  2. As precommits arrive via the vote scheduler, the matching lanes
     are patched in place — signature bytes + the <=24-byte timestamp
     patch — and verification launches AHEAD of commit assembly: on
     the device through a persistent donated-buffer ResidentArena
     (crypto/tpu/resident.py) carrying the known-answer sentinel lane
     per launch (PR-6 convention), or on the host below the device
     crossover / behind an open breaker.
  3. At commit time (state/validation.py validate_block verifying the
     block's LastCommit), `serve_commit` answers from the completed
     launch after a BYTE-EXACT template match per lane — the match is
     on the exact (timestamp, signature) the lane was verified
     against, which by the vote_sign_parts invariant equals byte
     equality of the full sign bytes. Any mismatched lane
     (equivocation, unexpected timestamp, nil vote, straggler) is
     re-verified through the existing breaker-aware BatchVerifier
     host/device path, so correctness NEVER depends on speculation: a
     full hit means zero verification launches post-commit; a miss
     means exactly the work the serial path would have done.

Chaos surface: the `consensus.speculate` failpoint wraps each lane's
observed-timestamp payload on its way into a launch — `corrupt` makes
every speculated lane mismatch at commit (the e2e `spec_mismatch`
perturbation's wrong-timestamp flood), `error` abandons the launch,
`delay` stalls it past the commit; all three degrade to the fallback
path and the net keeps committing.

Observability: the `speculation` metrics namespace (hits,
misses{reason}, patched_lanes, overlap_seconds, arena_bytes,
resident_reupload_bytes), the speculate/patch/reconcile span kinds,
and a /status `speculation` check via active_plane().
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from collections import deque

import numpy as np

from ..libs import failpoints, tracing
from ..types import canonical
from ..types.vote import VoteType

logger = logging.getLogger("consensus.speculation")

# Closed miss-reason label set of speculation_misses_total.
MISS_NO_PLAN = "no_plan"            # no speculation for that commit
MISS_UNPATCHED = "unpatched"        # lane's precommit never observed
MISS_NIL = "nil_vote"               # nil lane: never speculated
MISS_MISMATCH = "mismatch"          # timestamp/signature differ
MISS_EQUIVOCATION = "equivocation"  # conflicting votes seen for lane
MISS_NOT_LAUNCHED = "not_launched"  # patched but no launch completed
MISS_REASONS = (MISS_NO_PLAN, MISS_UNPATCHED, MISS_NIL, MISS_MISMATCH,
                MISS_EQUIVOCATION, MISS_NOT_LAUNCHED)

_ORPHAN_RING = 2048  # precommits buffered before their proposal arrives

_ACTIVE_PLANE: "SpeculationPlane | None" = None


def active_plane() -> "SpeculationPlane | None":
    """The process's most recently built plane (the /status hook; a
    process normally hosts one node)."""
    return _ACTIVE_PLANE


def _metrics():
    from ..libs.metrics import speculation_metrics

    return speculation_metrics()


class _Lane:
    """One validator's speculated precommit. `ts` is the timestamp the
    lane was actually VERIFIED against (it can differ from `ts_obs`
    only under an armed consensus.speculate corrupt) — serve matches
    on `ts`, so a corrupted lane can never serve its (wrong-bytes)
    verdict for the real vote."""

    __slots__ = ("ts_obs", "ts", "sig", "verdict", "poisoned")

    def __init__(self, ts_obs: int, sig: bytes):
        self.ts_obs = ts_obs
        self.ts: int | None = None
        self.sig = sig
        self.verdict: bool | None = None
        self.poisoned = False


class _HeightSpec:
    """Everything speculated for one (height, round, block_id)."""

    __slots__ = ("chain_id", "height", "round", "block_id", "valset",
                 "valset_hash", "pre", "suf", "lanes", "other",
                 "pending", "launch_done")

    def __init__(self, chain_id, height, round_, block_id, valset):
        self.chain_id = chain_id
        self.height = height
        self.round = round_
        self.block_id = block_id
        self.valset = valset
        self.valset_hash = valset.hash()
        self.pre, self.suf = canonical.vote_sign_parts(
            chain_id, int(VoteType.PRECOMMIT), height, round_, block_id)
        self.lanes: dict[int, _Lane] = {}
        self.other: set[int] = set()  # voted nil / a different block
        self.pending: list[tuple[int, int, bytes]] = []  # idx, ts, sig
        self.launch_done: float | None = None


class SpeculationPlane:
    """The verify-ahead plane one node owns (wired by node._build from
    the [speculation] config section; ConsensusState feeds it,
    BlockExecutor serves from it)."""

    def __init__(self, config=None, *, device_min: int | None = None):
        from ..crypto import batch as cbatch

        self.arena_lanes = getattr(config, "arena_lanes", 12288)
        self.max_heights_ahead = getattr(config, "max_heights_ahead", 2)
        self.flush_ms = getattr(config, "flush_ms", 2.0)
        self.device_min = (cbatch._DEVICE_THRESHOLD
                           if device_min is None else device_min)
        self._lock = threading.Lock()
        self._launch_lock = threading.Lock()  # serializes arena use
        self._heights: dict[int, _HeightSpec] = {}
        self._orphans: deque = deque(maxlen=_ORPHAN_RING)
        self._arena = None
        self._arena_keys_hash: bytes | None = None
        self._arena_entry: _HeightSpec | None = None
        self._flusher: asyncio.Task | None = None
        self._pending_evt: asyncio.Event | None = None
        # /status tallies (metric counters mirror these with labels)
        self.hits = 0
        self.misses: dict[str, int] = {r: 0 for r in MISS_REASONS}
        self.patched_lanes = 0
        global _ACTIVE_PLANE
        _ACTIVE_PLANE = self

    # -- lifecycle -----------------------------------------------------

    def close(self) -> None:
        if self._flusher is not None:
            self._flusher.cancel()
            self._flusher = None
        with self._lock:
            self._heights.clear()
            self._orphans.clear()
        global _ACTIVE_PLANE
        if _ACTIVE_PLANE is self:
            _ACTIVE_PLANE = None

    def _ensure_flusher(self) -> None:
        if self._flusher is not None and not self._flusher.done():
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # synchronous use (tests/bench drive flush_sync)
        if self._pending_evt is None:
            self._pending_evt = asyncio.Event()
        self._flusher = loop.create_task(self._flush_loop(),
                                         name="speculation-flusher")

    # -- consensus-side feeds ------------------------------------------

    def begin_height(self, chain_id: str, valset, height: int,
                     round_: int, block_id) -> None:
        """The proposal BlockID for `height` is known: pre-pack the
        precommit sign-byte template and start accepting patches.
        Idempotent per (height, round, block_id); a re-proposal at a
        later round replaces the entry (new sign bytes)."""
        if block_id is None or block_id.is_zero():
            return
        with self._lock:
            cur = self._heights.get(height)
            if cur is not None and cur.round == round_ and \
                    cur.block_id == block_id:
                return
            try:
                entry = _HeightSpec(chain_id, height, round_, block_id,
                                    valset)
            except Exception:
                logger.exception("speculation template build failed "
                                 "(h=%d r=%d)", height, round_)
                return
            self._heights[height] = entry
            while len(self._heights) > self.max_heights_ahead + 1:
                evicted = min(self._heights)
                if evicted == height:
                    break
                del self._heights[evicted]
            # precommits that raced ahead of the proposal
            for v in list(self._orphans):
                if v.height == height:
                    self._observe_locked(entry, v)
            replayed = bool(entry.pending)
        if replayed:
            self._ensure_flusher()
            if self._pending_evt is not None:
                self._pending_evt.set()

    def observe_precommit(self, vote) -> None:
        """A verified-or-about-to-verify precommit arrived (vote
        scheduler / sync add_vote path): patch its lane."""
        with self._lock:
            entry = self._heights.get(vote.height)
            if entry is None:
                self._orphans.append(vote)
                return
            self._observe_locked(entry, vote)
        self._ensure_flusher()
        if self._pending_evt is not None:
            self._pending_evt.set()

    def _observe_locked(self, entry: _HeightSpec, vote) -> None:
        if vote.round != entry.round or not vote.signature:
            return
        idx = vote.validator_index
        if not 0 <= idx < len(entry.valset.validators):
            return
        bid = vote.block_id
        matches = bid is not None and not bid.is_nil() \
            and bid == entry.block_id
        lane = entry.lanes.get(idx)
        if not matches:
            # nil or different block: never speculated — and it
            # poisons any for-block lane from the same validator
            # (equivocation must not serve a speculated verdict)
            if lane is not None:
                lane.poisoned = True
            else:
                entry.other.add(idx)
            return
        if lane is not None:
            if lane.ts_obs != vote.timestamp or \
                    lane.sig != vote.signature:
                lane.poisoned = True  # equivocation
            return  # gossip duplicate: already patched
        lane = _Lane(vote.timestamp, vote.signature)
        if idx in entry.other:
            lane.poisoned = True  # saw a conflicting vote earlier
        entry.lanes[idx] = lane
        entry.pending.append((idx, vote.timestamp, vote.signature))
        self.patched_lanes += 1
        try:
            _metrics().patched_lanes.inc()
        except Exception:  # pragma: no cover - metrics never fatal
            pass

    def retire_below(self, height: int) -> None:
        """Consensus moved to `height`: commits below height-1 can no
        longer be asked for (the block carrying them is validated
        during `height`)."""
        with self._lock:
            for h in [h for h in self._heights if h < height - 1]:
                del self._heights[h]

    # -- the verify-ahead launches -------------------------------------

    async def _flush_loop(self) -> None:
        loop = asyncio.get_running_loop()
        evt = self._pending_evt
        while True:
            await evt.wait()
            if self.flush_ms > 0:
                await asyncio.sleep(self.flush_ms / 1000.0)
            evt.clear()
            for entry, batch in self._drain():
                try:
                    await loop.run_in_executor(
                        None, tracing.TRACER.wrap(self._launch_batch),
                        entry, batch)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    # a failed speculative launch must never surface:
                    # the lanes simply stay verdict-less and the
                    # commit-time fallback verifies them
                    logger.exception("speculative launch died "
                                     "(%d lanes)", len(batch))

    def _drain(self) -> list[tuple[_HeightSpec, list]]:
        out = []
        with self._lock:
            for entry in self._heights.values():
                if entry.pending:
                    out.append((entry, entry.pending))
                    entry.pending = []
        return out

    def flush_sync(self) -> None:
        """Drain + launch inline (tests / bench drivers; the node path
        goes through the asyncio flusher)."""
        for entry, batch in self._drain():
            self._launch_batch(entry, batch)

    def _launch_batch(self, entry: _HeightSpec, batch: list) -> None:
        met = _metrics()
        with tracing.TRACER.span(tracing.SPECULATION_SPECULATE,
                                 lanes=len(batch), height=entry.height):
            kept: list[tuple[int, int, bytes]] = []
            for idx, ts, sig in batch:
                try:
                    raw = failpoints.hit("consensus.speculate",
                                         payload=ts.to_bytes(8, "big"))
                except failpoints.FailpointError:
                    logger.warning(
                        "speculative launch abandoned (injected "
                        "consensus.speculate); %d lanes fall back at "
                        "commit", len(batch))
                    return
                kept.append((idx, int.from_bytes(raw, "big"), sig))
            verdicts = self._verify_lanes(entry, kept, met)
            if verdicts is None:
                return
            with self._lock:
                for (idx, ts_used, _sig), ok in zip(kept, verdicts):
                    lane = entry.lanes.get(idx)
                    if lane is None:
                        continue
                    lane.ts = ts_used
                    lane.verdict = bool(ok)
                entry.launch_done = time.monotonic()

    def _verify_lanes(self, entry, kept, met):
        """Per-lane verdicts for a speculative batch: device via the
        ResidentArena (sentinel-checked, breaker-aware) when the batch
        clears the crossover, host otherwise. Returns None only when
        verification could not run at all (lanes stay verdict-less)."""
        from ..crypto import batch as cbatch

        n = len(kept)
        if n == 0:
            return []
        want_dev = n >= self.device_min and \
            all(0 <= ts < 1 << 63 for _, ts, _ in kept)
        if want_dev and cbatch.breaker("ed25519").acquire():
            try:
                out = self._device_verify(entry, kept, met)
                if out is not None:
                    return out
                # None = the arena cannot serve this entry BY DESIGN
                # (valset over capacity, mixed key types, oversized
                # template): a healthy device, so NOT a host_fallback
                # — that counter is the device-degradation signal
            except Exception:
                cbatch.mark_device_failed("ed25519")
                logger.exception(
                    "speculative device launch failed (%d lanes); "
                    "breaker open %.1fs, degrading to host", n,
                    cbatch.breaker("ed25519").cooldown_remaining())
                from ..libs.metrics import tpu_metrics

                tpu_metrics().host_fallbacks.inc()
        elif want_dev:
            # device wanted but the breaker refused (open/probing):
            # the same fallback signal BatchVerifier emits
            from ..libs.metrics import tpu_metrics

            tpu_metrics().host_fallbacks.inc()
        return self._host_verify(entry, kept, met)

    def _host_verify(self, entry, kept, met):
        met.launches.inc(backend="host")
        bv = None
        try:
            from ..crypto.batch import BatchVerifier

            bv = BatchVerifier(use_device=False)
            for idx, ts, sig in kept:
                bv.add(entry.valset.validators[idx].pub_key,
                       self._lane_sign_bytes(entry, ts), sig)
            _, verdicts = bv.verify()
            return verdicts
        except Exception:
            logger.exception("speculative host verify failed "
                             "(%d lanes)", len(kept))
            return None

    def _lane_sign_bytes(self, entry, ts: int) -> bytes:
        return canonical.vote_sign_bytes(
            entry.chain_id, int(VoteType.PRECOMMIT), entry.height,
            entry.round, entry.block_id, ts)

    def _device_verify(self, entry, kept, met):
        """One arena launch over the spliced lanes + sentinel. Returns
        verdicts aligned with `kept`, or None when the arena cannot
        serve this entry (templates too big, valset over capacity)."""
        from ..crypto import batch as cbatch
        from ..libs.metrics import crypto_metrics, tpu_metrics
        from ..types import sign_batch as sbm

        with self._launch_lock:
            arena = self._ensure_arena(entry)
            if arena is None:
                return None
            n = len(kept)
            ts_arr = np.asarray([ts for _, ts, _ in kept], np.int64)
            group = np.ones(n, np.int32)
            patch, split, patch_len = sbm._build_patches(
                arena.pre_len.astype(np.int64), arena.suf_len, group,
                ts_arr)
            mlen = int(patch_len.max()) + len(entry.pre) \
                + len(entry.suf)
            if mlen > arena.width - 17:
                return None
            # lane-0 self-check: the structured reassembly must equal
            # the independently-built canonical bytes (same guard as
            # expanded._prepare_structured)
            a0, p0 = int(split[0]), int(patch_len[0])
            got = (bytes(patch[0, :a0]) + entry.pre
                   + bytes(patch[0, a0:p0]) + entry.suf)
            if got != self._lane_sign_bytes(entry, int(ts_arr[0])):
                raise ValueError(
                    "speculative structured sign-bytes self-check "
                    "failed")
            from ..crypto.tpu import ledger as tpu_ledger

            failpoints.hit("device.verify")
            crypto_metrics().device_launches.inc()
            with tracing.TRACER.span(tracing.SPECULATION_PATCH,
                                     lanes=n):
                arena.splice([idx + 1 for idx, _, _ in kept],
                             np.frombuffer(
                                 b"".join(s for _, _, s in kept),
                                 np.uint8).reshape(n, 64),
                             patch, split, patch_len, group)
            with tpu_ledger.workload("speculation"):
                out = arena.launch()
            met.launches.inc(backend="device")
            crypto_metrics().batch_lanes.inc(n, backend="tpu")
            if not out[0]:
                # sentinel mismatch: wrong-verdict device — open a
                # breaker and re-verify on host rather than storing
                # garbage verdicts for later serving. A sharded arena
                # attributes the failure to the specific chip(s) whose
                # per-shard sentinel broke: ONLY those chips' per-
                # device breakers open (the fabric reshards over the
                # survivors); an unsharded arena can't attribute, so
                # the backend-wide breaker opens as before.
                failed = getattr(arena, "failed_shards", lambda: [])()
                devices = [dev for _, dev in failed]
                detail = ", ".join(
                    f"shard {i} ({dev})" for i, dev in failed) or None
                cbatch.mark_device_failed("ed25519",
                                          device=devices or None,
                                          reason="sentinel")
                logger.error(
                    "speculative launch (%d lanes) failed its "
                    "known-answer sentinel%s; re-verifying on host", n,
                    f" on {detail}" if detail else "")
                met.launches.inc(backend="host_recheck")
                tpu_metrics().host_fallbacks.inc()
                return self._host_verify(entry, kept, met)
            return [bool(out[idx + 1]) for idx, _, _ in kept]

    def _ensure_arena(self, entry: _HeightSpec):
        from ..crypto.tpu.resident import GROUPS, PRE_W, SUF_W, \
            make_arena

        if len(entry.valset.validators) + 1 > self.arena_lanes:
            return None
        if len(entry.pre) > PRE_W or len(entry.suf) > SUF_W or \
                GROUPS < 2:  # pragma: no cover - template guard
            return None
        if any(v.pub_key.type_name != "ed25519"
               for v in entry.valset.validators):
            # the arena kernel is ed25519-only; mixed sets go host-side
            return None
        if self._arena is None:
            # per-device shards when a mesh exists: steady-state
            # splices upload only each chip's ~1/N of the deltas, and
            # every shard carries its own known-answer sentinel
            self._arena = make_arena(self.arena_lanes)
        elif getattr(self._arena, "ensure_mesh", None) is not None:
            # per-device breaker evicted a chip (or re-admitted one):
            # the arena rebuilds over the effective mesh — installed
            # keys replay into the new layout, and this entry's lanes
            # re-splice below as they do every launch
            self._arena.ensure_mesh()
        if len(entry.valset.validators) + 1 > self._arena.capacity:
            return None
        if self._arena_keys_hash != entry.valset_hash:
            self._arena.install_keys(
                [v.pub_key.bytes() for v in entry.valset.validators])
            self._arena_keys_hash = entry.valset_hash
        if self._arena_entry is not entry:
            self._arena.deactivate_all()
            self._arena.set_template(1, entry.pre, entry.suf)
            self._arena_entry = entry
        return self._arena

    # -- the commit-time serve -----------------------------------------

    def serve_commit(self, valset, chain_id: str, block_id, height: int,
                     commit) -> bool:
        """verify_commit with speculated verdicts: byte-exact-matched
        lanes are served from the completed launch; every other lane
        re-verifies through the normal breaker-aware batch path.
        Returns False (caller runs the ordinary verify) only when
        nothing was speculated for this commit; True means the commit
        was fully checked here — with verify_commit's exact error
        behavior (VerificationError on bad signatures / insufficient
        power)."""
        from ..types.validator_set import VerificationError

        met = _metrics()
        with self._lock:
            entry = self._heights.get(height)
            if entry is None or entry.chain_id != chain_id \
                    or entry.round != commit.round \
                    or entry.block_id != commit.block_id \
                    or entry.valset_hash != valset.hash():
                met.misses.inc(reason=MISS_NO_PLAN)
                self.misses[MISS_NO_PLAN] += 1
                return False
            lanes = dict(entry.lanes)
            launch_done = entry.launch_done
        with tracing.TRACER.span(tracing.SPECULATION_RECONCILE,
                                 height=height):
            valset._check_commit_basics(block_id, height, commit)
            tallied = 0
            slots: list[int] = []
            verd: dict[int, bool] = {}
            miss: list[int] = []
            for idx, cs in enumerate(commit.signatures):
                if cs.is_absent():
                    continue
                val = valset.validators[idx]
                if cs.validator_address and \
                        cs.validator_address != val.address:
                    raise VerificationError(
                        f"wrong validator address in slot {idx}")
                slots.append(idx)
                if cs.for_block():
                    tallied += val.voting_power
                lane = lanes.get(idx)
                if (cs.for_block() and lane is not None
                        and not lane.poisoned
                        and lane.verdict is not None
                        and lane.ts == cs.timestamp
                        and lane.sig == cs.signature):
                    verd[idx] = lane.verdict
                else:
                    miss.append(idx)
                    reason = self._miss_reason(cs, lane)
                    met.misses.inc(reason=reason)
                    self.misses[reason] += 1
            if miss:
                # per-lane fallback batch: one mismatched lane costs
                # one lane of re-verification, its batchmates keep
                # their speculated verdicts (verdict scatter)
                msgs = [commit.vote_sign_bytes(chain_id, s)
                        for s in miss]
                sigs = [commit.signatures[s].signature for s in miss]
                _, fb = valset._batch_verify_lanes(miss, msgs, sigs)
                for s, ok in zip(miss, fb):
                    verd[s] = bool(ok)
            bad = [s for s in slots if not verd[s]]
            if bad:
                raise VerificationError(
                    f"invalid signature(s) at index(es) {bad}")
            if 3 * tallied <= 2 * valset.total_voting_power():
                raise VerificationError(
                    f"insufficient voting power: {tallied} of "
                    f"{valset.total_voting_power()}")
            if not miss:
                self.hits += 1
                met.hits.inc()
                if launch_done is not None:
                    met.overlap_seconds.observe(
                        time.monotonic() - launch_done)
            return True

    @staticmethod
    def _miss_reason(cs, lane) -> str:
        if not cs.for_block():
            return MISS_NIL
        if lane is None:
            return MISS_UNPATCHED
        if lane.poisoned:
            return MISS_EQUIVOCATION
        if lane.verdict is None:
            return MISS_NOT_LAUNCHED
        return MISS_MISMATCH

    # -- /status -------------------------------------------------------

    def status_check(self) -> dict:
        """The GET /status `speculation` check body. Speculation is an
        optimization: misses are designed behavior (the fallback path
        is the correctness story), so the check never degrades — an
        open breaker is noted, not escalated."""
        from ..crypto import batch as cbatch

        with self._lock:
            heights = sorted(self._heights)
            patched = {h: len(e.lanes)
                       for h, e in self._heights.items()}
        out: dict = {
            "status": "ok",
            "hits": self.hits,
            "misses": {r: n for r, n in self.misses.items() if n},
            "patched_lanes": self.patched_lanes,
            "heights": heights,
            "lanes_by_height": patched,
            "arena_bytes": (self._arena.arena_bytes()
                            if self._arena is not None else 0),
            "reupload_bytes": (self._arena.reupload_bytes
                               if self._arena is not None else 0),
        }
        if not cbatch.device_available("ed25519"):
            out["detail"] = ("ed25519 breaker open: speculating on "
                             "host")
        return out
