"""Counter example app (reference: abci/example/counter/counter.go).

Txs must be the big-endian encoding of the next counter value when
serial mode is on (toggled by a 'serial=on' tx); otherwise any tx
increments the counter. Exercises CheckTx rejection + recheck."""

from __future__ import annotations

import struct

from . import types as t


class CounterApp(t.Application):
    def __init__(self, serial: bool = False):
        self.serial = serial
        self.hash_count = 0
        self.tx_count = 0

    def info(self, req: t.RequestInfo) -> t.ResponseInfo:
        return t.ResponseInfo(
            data=f"hashes:{self.hash_count}, txs:{self.tx_count}",
            last_block_height=self.hash_count,
            last_block_app_hash=self._app_hash())

    def _app_hash(self) -> bytes:
        return struct.pack(">Q", self.tx_count) if self.tx_count else b""

    def _check(self, tx: bytes) -> int | None:
        """Returns an error code or None."""
        if tx == b"serial=on":
            return None
        if self.serial:
            if len(tx) > 8:
                return 1
            if int.from_bytes(tx, "big") != self.tx_count:
                return 2
        return None

    def check_tx(self, req: t.RequestCheckTx) -> t.ResponseCheckTx:
        code = self._check(req.tx)
        if code is not None:
            return t.ResponseCheckTx(code=code, log="bad counter tx")
        return t.ResponseCheckTx(code=t.CODE_TYPE_OK, gas_wanted=1)

    def deliver_tx(self, req: t.RequestDeliverTx) -> t.ResponseDeliverTx:
        if req.tx == b"serial=on":
            self.serial = True
            return t.ResponseDeliverTx(code=t.CODE_TYPE_OK)
        code = self._check(req.tx)
        if code is not None:
            return t.ResponseDeliverTx(code=code, log="bad counter tx")
        self.tx_count += 1
        return t.ResponseDeliverTx(code=t.CODE_TYPE_OK)

    def commit(self, req: t.RequestCommit) -> t.ResponseCommit:
        self.hash_count += 1
        return t.ResponseCommit(data=self._app_hash())

    def query(self, req: t.RequestQuery) -> t.ResponseQuery:
        if req.path == "hash":
            return t.ResponseQuery(value=str(self.hash_count).encode())
        if req.path == "tx":
            return t.ResponseQuery(value=str(self.tx_count).encode())
        return t.ResponseQuery(code=1, log=f"unknown path {req.path!r}")
