"""ABCI message types + Application interface (reference: abci/types/).

Messages are dataclasses with a generic JSON wire form (bytes fields
wrapped as {"__b": base64}) — the ABCI link connects OUR node to OUR
apps, so the only requirements are framing robustness and round-trip
fidelity, not consensus-critical canonical encoding (which lives in
types/canonical.py). Each message knows its wire name; the codec
registry maps names back to classes.
"""

from __future__ import annotations

import base64
import json
from dataclasses import dataclass, field, fields, is_dataclass

CODE_TYPE_OK = 0


class CheckTxType:
    NEW = 0
    RECHECK = 1


# --- auxiliary structures ----------------------------------------------------


@dataclass
class ValidatorUpdate:
    """Valset delta returned by InitChain/EndBlock (abci/types/types.pb.go
    ValidatorUpdate): pub_key + new absolute power (0 = remove)."""

    pub_key_type: str
    pub_key: bytes
    power: int


@dataclass
class VoteInfo:
    """Who signed the last block (BeginBlock.LastCommitInfo entry)."""

    address: bytes
    power: int
    signed_last_block: bool


@dataclass
class LastCommitInfo:
    round: int = 0
    votes: list[VoteInfo] = field(default_factory=list)


@dataclass
class Misbehavior:
    """Evidence forwarded to the app in BeginBlock (abci Evidence msg)."""

    type: str  # "DUPLICATE_VOTE" | "LIGHT_CLIENT_ATTACK"
    validator_address: bytes
    validator_power: int
    height: int
    time: int
    total_voting_power: int


@dataclass
class Snapshot:
    height: int
    format: int
    chunks: int
    hash: bytes
    metadata: bytes = b""


# --- requests ----------------------------------------------------------------


@dataclass
class RequestEcho:
    message: str = ""


@dataclass
class RequestFlush:
    pass


@dataclass
class RequestInfo:
    version: str = ""
    block_version: int = 0
    p2p_version: int = 0


@dataclass
class RequestInitChain:
    time: int = 0
    chain_id: str = ""
    consensus_params: dict | None = None
    validators: list[ValidatorUpdate] = field(default_factory=list)
    app_state_bytes: bytes = b""
    initial_height: int = 1


@dataclass
class RequestQuery:
    data: bytes = b""
    path: str = ""
    height: int = 0
    prove: bool = False


@dataclass
class RequestBeginBlock:
    hash: bytes = b""
    header: dict = field(default_factory=dict)
    last_commit_info: LastCommitInfo = field(default_factory=LastCommitInfo)
    byzantine_validators: list[Misbehavior] = field(default_factory=list)


@dataclass
class RequestCheckTx:
    tx: bytes = b""
    type: int = CheckTxType.NEW


@dataclass
class RequestDeliverTx:
    tx: bytes = b""


@dataclass
class RequestEndBlock:
    height: int = 0


@dataclass
class RequestCommit:
    pass


@dataclass
class RequestListSnapshots:
    pass


@dataclass
class RequestOfferSnapshot:
    snapshot: Snapshot | None = None
    app_hash: bytes = b""


@dataclass
class RequestLoadSnapshotChunk:
    height: int = 0
    format: int = 0
    chunk: int = 0


@dataclass
class RequestApplySnapshotChunk:
    index: int = 0
    chunk: bytes = b""
    sender: str = ""


# --- responses ---------------------------------------------------------------


@dataclass
class ResponseEcho:
    message: str = ""


@dataclass
class ResponseFlush:
    pass


@dataclass
class ResponseException:
    error: str = ""


@dataclass
class ResponseInfo:
    data: str = ""
    version: str = ""
    app_version: int = 0
    last_block_height: int = 0
    last_block_app_hash: bytes = b""


@dataclass
class ResponseInitChain:
    consensus_params: dict | None = None
    validators: list[ValidatorUpdate] = field(default_factory=list)
    app_hash: bytes = b""


@dataclass
class ResponseQuery:
    code: int = CODE_TYPE_OK
    log: str = ""
    info: str = ""
    index: int = 0
    key: bytes = b""
    value: bytes = b""
    proof_ops: list = field(default_factory=list)
    height: int = 0
    codespace: str = ""


@dataclass
class ResponseBeginBlock:
    events: list = field(default_factory=list)


@dataclass
class ResponseCheckTx:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: list = field(default_factory=list)
    codespace: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass
class ResponseDeliverTx:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: list = field(default_factory=list)
    codespace: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass
class ResponseEndBlock:
    validator_updates: list[ValidatorUpdate] = field(default_factory=list)
    consensus_param_updates: dict | None = None
    events: list = field(default_factory=list)


@dataclass
class ResponseCommit:
    data: bytes = b""  # the app hash
    retain_height: int = 0


@dataclass
class ResponseListSnapshots:
    snapshots: list[Snapshot] = field(default_factory=list)


class OfferSnapshotResult:
    UNKNOWN = 0
    ACCEPT = 1
    ABORT = 2
    REJECT = 3
    REJECT_FORMAT = 4
    REJECT_SENDER = 5


@dataclass
class ResponseOfferSnapshot:
    result: int = OfferSnapshotResult.UNKNOWN


@dataclass
class ResponseLoadSnapshotChunk:
    chunk: bytes = b""


class ApplySnapshotChunkResult:
    UNKNOWN = 0
    ACCEPT = 1
    ABORT = 2
    RETRY = 3
    RETRY_SNAPSHOT = 4
    REJECT_SNAPSHOT = 5


@dataclass
class ResponseApplySnapshotChunk:
    result: int = ApplySnapshotChunkResult.UNKNOWN
    refetch_chunks: list[int] = field(default_factory=list)
    reject_senders: list[str] = field(default_factory=list)


# --- wire codec --------------------------------------------------------------

_REGISTRY: dict[str, type] = {}
_NESTED = {
    "validators": ValidatorUpdate,
    "validator_updates": ValidatorUpdate,
    "votes": VoteInfo,
    "byzantine_validators": Misbehavior,
    "snapshots": Snapshot,
    "last_commit_info": LastCommitInfo,
    "snapshot": Snapshot,
}


def _wire_name(cls: type) -> str:
    return cls.__name__


for _cls in list(globals().values()):
    if is_dataclass(_cls) and isinstance(_cls, type):
        _REGISTRY[_wire_name(_cls)] = _cls


def _jsonable(v):
    if isinstance(v, bytes):
        return {"__b": base64.b64encode(v).decode()}
    if is_dataclass(v) and not isinstance(v, type):
        return {f.name: _jsonable(getattr(v, f.name)) for f in fields(v)}
    if isinstance(v, list):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    return v


def _unjson(v, hint: type | None = None):
    if isinstance(v, dict) and set(v) == {"__b"}:
        return base64.b64decode(v["__b"])
    if hint is not None and isinstance(v, dict):
        kw = {}
        hints = {f.name: f for f in fields(hint)}
        for k, x in v.items():
            if k in hints:
                kw[k] = _unjson(x, _NESTED.get(k))
        return hint(**kw)
    if isinstance(v, list):
        return [_unjson(x, hint) for x in v]
    if isinstance(v, dict):
        return {k: _unjson(x) for k, x in v.items()}
    return v


def encode_msg(obj) -> bytes:
    return json.dumps(
        {"@": _wire_name(type(obj)), **_jsonable(obj)},
        separators=(",", ":"),
    ).encode()


def decode_msg(data: bytes):
    d = json.loads(data)
    name = d.pop("@")
    cls = _REGISTRY[name]
    kw = {}
    hints = {f.name: f for f in fields(cls)}
    for k, v in d.items():
        if k in hints:
            kw[k] = _unjson(v, _NESTED.get(k))
    return cls(**kw)


# --- the Application interface (reference: abci/types/application.go:11-31) --


class Application:
    """Synchronous app contract; transports call these serially per
    connection. Defaults are no-ops so apps override what they need
    (reference: abci/types/application.go BaseApplication)."""

    # group 1: info/query
    def info(self, req: RequestInfo) -> ResponseInfo:
        return ResponseInfo()

    def query(self, req: RequestQuery) -> ResponseQuery:
        return ResponseQuery()

    # group 2: mempool
    def check_tx(self, req: RequestCheckTx) -> ResponseCheckTx:
        return ResponseCheckTx()

    # group 3: consensus
    def init_chain(self, req: RequestInitChain) -> ResponseInitChain:
        return ResponseInitChain()

    def begin_block(self, req: RequestBeginBlock) -> ResponseBeginBlock:
        return ResponseBeginBlock()

    def deliver_tx(self, req: RequestDeliverTx) -> ResponseDeliverTx:
        return ResponseDeliverTx()

    def end_block(self, req: RequestEndBlock) -> ResponseEndBlock:
        return ResponseEndBlock()

    def commit(self, req: RequestCommit) -> ResponseCommit:
        return ResponseCommit()

    # group 4: state sync
    def list_snapshots(self, req: RequestListSnapshots) -> ResponseListSnapshots:
        return ResponseListSnapshots()

    def offer_snapshot(self, req: RequestOfferSnapshot) -> ResponseOfferSnapshot:
        return ResponseOfferSnapshot()

    def load_snapshot_chunk(
        self, req: RequestLoadSnapshotChunk
    ) -> ResponseLoadSnapshotChunk:
        return ResponseLoadSnapshotChunk()

    def apply_snapshot_chunk(
        self, req: RequestApplySnapshotChunk
    ) -> ResponseApplySnapshotChunk:
        return ResponseApplySnapshotChunk()


# request type -> (app method name, response class); Echo/Flush are
# handled by the transports themselves.
HANDLERS: dict[type, str] = {
    RequestInfo: "info",
    RequestQuery: "query",
    RequestCheckTx: "check_tx",
    RequestInitChain: "init_chain",
    RequestBeginBlock: "begin_block",
    RequestDeliverTx: "deliver_tx",
    RequestEndBlock: "end_block",
    RequestCommit: "commit",
    RequestListSnapshots: "list_snapshots",
    RequestOfferSnapshot: "offer_snapshot",
    RequestLoadSnapshotChunk: "load_snapshot_chunk",
    RequestApplySnapshotChunk: "apply_snapshot_chunk",
}
