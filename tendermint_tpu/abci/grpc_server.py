"""ABCI gRPC server for out-of-process apps (reference:
abci/server/grpc_server.go).

One unary RPC per ABCI method on the `tendermint.abci.ABCIApplication`
service. Messages ride the same self-describing codec the socket
transport uses (types.encode_msg/decode_msg), registered as the
per-method (de)serializers, so both transports are byte-level
interchangeable above the framing. App calls are serialized under one
lock, matching the socket server (the reference's gRPC server relies
on the app's own locking; ours keeps the stronger guarantee both our
transports already give).
"""

from __future__ import annotations

import asyncio

import grpc
from grpc import aio

from ..libs.service import Service
from . import types as t

SERVICE_NAME = "tendermint.abci.ABCIApplication"

# RPC method name -> request type (Echo/Flush are transport-level).
METHODS: dict[str, type] = {
    "Echo": t.RequestEcho,
    "Flush": t.RequestFlush,
    "Info": t.RequestInfo,
    "Query": t.RequestQuery,
    "CheckTx": t.RequestCheckTx,
    "InitChain": t.RequestInitChain,
    "BeginBlock": t.RequestBeginBlock,
    "DeliverTx": t.RequestDeliverTx,
    "EndBlock": t.RequestEndBlock,
    "Commit": t.RequestCommit,
    "ListSnapshots": t.RequestListSnapshots,
    "OfferSnapshot": t.RequestOfferSnapshot,
    "LoadSnapshotChunk": t.RequestLoadSnapshotChunk,
    "ApplySnapshotChunk": t.RequestApplySnapshotChunk,
}
METHOD_BY_TYPE: dict[type, str] = {v: k for k, v in METHODS.items()}


class GRPCServer(Service):
    def __init__(self, app: t.Application, host: str = "127.0.0.1",
                 port: int = 26658):
        super().__init__(name="abci.GRPCServer")
        self.app = app
        self.host, self.port = host, port
        self._server: aio.Server | None = None
        self._app_lock = asyncio.Lock()

    def _make_handler(self, name: str):
        async def unary(request, context):
            if isinstance(request, t.RequestEcho):
                return t.ResponseEcho(request.message)
            if isinstance(request, t.RequestFlush):
                return t.ResponseFlush()
            method = t.HANDLERS[type(request)]
            try:
                async with self._app_lock:
                    return getattr(self.app, method)(request)
            except Exception as e:  # app bug -> RPC error, not dead server
                self.logger.error("app %s failed: %r", method, e)
                await context.abort(grpc.StatusCode.INTERNAL, repr(e))

        return grpc.unary_unary_rpc_method_handler(
            unary,
            request_deserializer=t.decode_msg,
            response_serializer=t.encode_msg,
        )

    async def on_start(self) -> None:
        self._server = aio.server()
        handlers = {name: self._make_handler(name) for name in METHODS}
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
        )
        self.port = self._server.add_insecure_port(
            f"{self.host}:{self.port}")
        await self._server.start()
        self.logger.info("abci grpc server on %s:%d", self.host, self.port)

    async def on_stop(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=1.0)
