"""Merkle proof format for the proof-capable kvstore app.

The reference's light RPC client verifies every abci_query response
against the light-verified app hash via a ProofRuntime
(light/rpc/client.go:104-151); the proof FORMAT itself is app-defined
(cosmos uses iavl ops). This module defines the format for this
repo's MerkleKVStoreApp (abci/kvstore.py): the app hash is an
RFC-6962 merkle root over the store's kv pairs sorted by key, and a
query response carries either

  kv:v  — a value (existence) proof: the merkle branch for the
          (key, value) leaf; the value rides the args chain so a
          tampered value changes the recomputed root.
  kv:a  — an absence proof: the merkle branches of the key's sorted
          NEIGHBORS. Adjacent indices whose keys straddle the queried
          key prove no leaf between them; boundary cases prove the
          first/last leaf instead. Sound because honest nodes build
          the tree over sorted unique keys — any pair of adjacent
          leaves proving into the trusted root leaves no room for the
          queried key.

Wire shape: ProofOp.data is JSON (matching the repo's ABCI codec);
ops decode through the registry from kv_proof_runtime().
"""

from __future__ import annotations

import json
import struct

from ..crypto import merkle


def kv_leaf(key: bytes, value: bytes) -> bytes:
    """Injective (key, value) leaf encoding: 4-byte BE length prefixes."""
    return struct.pack(">I", len(key)) + key + \
        struct.pack(">I", len(value)) + value


def _branch_json(p: merkle.Proof) -> dict:
    return {"index": p.index, "aunts": [a.hex() for a in p.aunts]}


def _branch_root(total: int, index: int, leaf: bytes,
                 aunts_hex: list) -> bytes | None:
    p = merkle.Proof(total=total, index=int(index),
                     leaf_hash=merkle.leaf_hash(leaf),
                     aunts=[bytes.fromhex(a) for a in aunts_hex])
    return p.compute_root()


class KVValueOp(merkle.ProofOperator):
    """Existence: recompute the root from (key, args[0]) at the proved
    position. data = {"total", "index", "aunts"}."""

    OP_TYPE = "kv:v"

    def __init__(self, key: bytes, d: dict):
        self.key = key
        self.d = d

    def get_key(self) -> bytes:
        return self.key

    def run(self, args: list[bytes]) -> list[bytes]:
        if len(args) != 1:
            raise ValueError("kv:v expects exactly the value")
        root = _branch_root(int(self.d["total"]), self.d["index"],
                            kv_leaf(self.key, args[0]), self.d["aunts"])
        if root is None:
            raise ValueError("invalid value proof shape")
        return [root]

    @classmethod
    def encode(cls, key: bytes, total: int, proof: merkle.Proof) -> dict:
        return {"type": cls.OP_TYPE, "key": key,
                "data": json.dumps({"total": total,
                                    **_branch_json(proof)}).encode()}


class KVAbsenceOp(merkle.ProofOperator):
    """Absence: the sorted neighbors of the (missing) key prove into
    the root with adjacent indices. data = {"total", "left"?,
    "right"?} where each side is {"key", "value", "index", "aunts"}
    (hex keys/values)."""

    OP_TYPE = "kv:a"

    def __init__(self, key: bytes, d: dict):
        self.key = key
        self.d = d

    def get_key(self) -> bytes:
        return self.key

    def run(self, args: list[bytes]) -> list[bytes]:
        if args:
            raise ValueError("kv:a takes no value")
        total = int(self.d["total"])
        left, right = self.d.get("left"), self.d.get("right")
        if total == 0:
            if left or right:
                raise ValueError("empty tree takes no neighbors")
            return [merkle.empty_hash()]

        def side_root(s) -> tuple[bytes, bytes, int]:
            k = bytes.fromhex(s["key"])
            root = _branch_root(total, s["index"],
                                kv_leaf(k, bytes.fromhex(s["value"])),
                                s["aunts"])
            if root is None:
                raise ValueError("invalid neighbor proof shape")
            return root, k, int(s["index"])

        if left and right:
            root_l, k_l, i_l = side_root(left)
            root_r, k_r, i_r = side_root(right)
            if not (k_l < self.key < k_r):
                raise ValueError("neighbors do not straddle the key")
            if i_r != i_l + 1 or root_l != root_r:
                raise ValueError("neighbors not adjacent in one tree")
            return [root_l]
        if left:
            root_l, k_l, i_l = side_root(left)
            if not (k_l < self.key and i_l == total - 1):
                raise ValueError("left neighbor must be the last leaf")
            return [root_l]
        if right:
            root_r, k_r, i_r = side_root(right)
            if not (self.key < k_r and i_r == 0):
                raise ValueError("right neighbor must be the first leaf")
            return [root_r]
        raise ValueError("non-empty tree needs at least one neighbor")

    @classmethod
    def encode(cls, key: bytes, total: int,
               left: tuple[bytes, bytes, merkle.Proof] | None,
               right: tuple[bytes, bytes, merkle.Proof] | None) -> dict:
        def side(t):
            if t is None:
                return None
            k, v, p = t
            return {"key": k.hex(), "value": v.hex(), **_branch_json(p)}

        return {"type": cls.OP_TYPE, "key": key,
                "data": json.dumps({"total": total, "left": side(left),
                                    "right": side(right)}).encode()}


def _decode(cls):
    def dec(op: merkle.ProofOp):
        return cls(op.key, json.loads(op.data))
    return dec


def kv_proof_runtime() -> merkle.ProofRuntime:
    """Default runtime knowing the kvstore proof formats (reference:
    merkle.DefaultProofRuntime with ValueOp registered)."""
    rt = merkle.ProofRuntime()
    rt.register(KVValueOp.OP_TYPE, _decode(KVValueOp))
    rt.register(KVAbsenceOp.OP_TYPE, _decode(KVAbsenceOp))
    return rt
