"""Example apps (reference: abci/example/kvstore/kvstore.go:66,
persistent_kvstore.go:27,108).

KVStoreApp: in-memory "key=value" store; app hash = 8-byte big-endian
tx count (matching the reference's size-as-apphash trick).
PersistentKVStoreApp adds durable state, height tracking for crash
replay (the Handshaker relies on Info.last_block_height), validator
updates via "val:<pubkey-hex>!<power>" txs, and statesync snapshots.
"""

from __future__ import annotations

import json
import struct

from ..crypto import merkle
from ..libs.db import DB, MemDB
from . import types as t

VALIDATOR_TX_PREFIX = b"val:"
_STATE_KEY = b"__appstate__"


def encode_validator_tx(pub_key_hex: str, power: int) -> bytes:
    return VALIDATOR_TX_PREFIX + f"{pub_key_hex}!{power}".encode()


class KVStoreApp(t.Application):
    """DeliverTx applies immediately (reference kvstore.go behavior —
    queries see uncommitted writes, as the abci-cli goldens capture)
    but every write is journaled, and BeginBlock ROLLS BACK any
    journal left by a block that never reached Commit. This makes
    block replay idempotent: if a node dies mid-block while its
    external app process lives on (observed: a graceful restart
    interrupting delivery — randomized campaign seed 131), the
    handshake's BeginBlock for the same height undoes the
    half-applied writes instead of double-applying them — the
    deliverState-reset semantics production ABCI apps implement."""

    def __init__(self):
        self.db: DB = MemDB()
        self.size = 0
        self.height = 0
        self.app_hash = b""
        self._undo: list[tuple[bytes, bytes | None]] = []
        self._committed_size = 0

    def info(self, req: t.RequestInfo) -> t.ResponseInfo:
        return t.ResponseInfo(
            data=json.dumps({"size": self.size}),
            version="kvstore/1",
            app_version=1,
            last_block_height=self.height,
            last_block_app_hash=self.app_hash,
        )

    def check_tx(self, req: t.RequestCheckTx) -> t.ResponseCheckTx:
        return t.ResponseCheckTx(code=t.CODE_TYPE_OK, gas_wanted=1)

    def _rollback_partial(self) -> None:
        if not self._undo:
            return
        for k, old in reversed(self._undo):
            if old is None:
                self.db.delete(k)
            else:
                self.db.set(k, old)
        self._undo.clear()
        self.size = self._committed_size

    def begin_block(self, req: t.RequestBeginBlock) -> t.ResponseBeginBlock:
        self._rollback_partial()
        return t.ResponseBeginBlock()

    def deliver_tx(self, req: t.RequestDeliverTx) -> t.ResponseDeliverTx:
        key, sep, value = req.tx.partition(b"=")
        if not sep:
            key = value = req.tx
        k = b"kv:" + key
        self._undo.append((k, self.db.get(k)))
        self.db.set(k, value)
        self.size += 1
        return t.ResponseDeliverTx(
            code=t.CODE_TYPE_OK,
            events=[{
                "type": "app",
                "attributes": [
                    {"key": "creator", "value": "kvstore"},
                    {"key": "key", "value": key.decode(errors="replace")},
                ],
            }],
        )

    def _mark_committed(self) -> None:
        """Seal the journal: current state is now the rollback point.
        Called at Commit AND after a statesync restore (a stale
        journal replayed into freshly restored state would corrupt
        it)."""
        self._undo.clear()
        self._committed_size = self.size

    def commit(self, req: t.RequestCommit) -> t.ResponseCommit:
        self._mark_committed()
        self.app_hash = struct.pack(">Q", self.size)
        self.height += 1
        return t.ResponseCommit(data=self.app_hash)

    def query(self, req: t.RequestQuery) -> t.ResponseQuery:
        v = self.db.get(b"kv:" + req.data)
        return t.ResponseQuery(
            key=req.data,
            value=v or b"",
            log="exists" if v is not None else "does not exist",
            height=self.height,
        )


class PersistentKVStoreApp(KVStoreApp):
    """Adds persistence + validator-update txs + snapshots."""

    SNAPSHOT_CHUNK_SIZE = 1 << 16

    def __init__(self, db: DB | None = None, snapshot_interval: int = 0,
                 keep_snapshots: int = 4):
        super().__init__()
        self.db = db or MemDB()
        self.val_updates: list[t.ValidatorUpdate] = []
        self._undo_vals: list[tuple[str, int | None]] = []
        self.validators: dict[str, int] = {}  # pubkey hex -> power
        self.retain_blocks = 0
        # taken every snapshot_interval heights, last keep_snapshots
        # retained (reference: test/e2e/app snapshot_interval); 0 =
        # advertise only the live head state
        self.snapshot_interval = snapshot_interval
        self.keep_snapshots = keep_snapshots
        st = self.db.get(_STATE_KEY)
        if st is not None:
            d = json.loads(st)
            self.size = d["size"]
            self.height = d["height"]
            self.app_hash = bytes.fromhex(d["app_hash"])
            self.validators = d.get("validators", {})
            self._mark_committed()

    def init_chain(self, req: t.RequestInitChain) -> t.ResponseInitChain:
        for vu in req.validators:
            self._update_validator(vu)
        return t.ResponseInitChain()

    def begin_block(self, req: t.RequestBeginBlock) -> t.ResponseBeginBlock:
        super().begin_block(req)  # roll back any half-applied kv block
        for hx, old in reversed(self._undo_vals):
            if old is None:
                self.validators.pop(hx, None)
            else:
                self.validators[hx] = old
        self._undo_vals.clear()
        self.val_updates = []
        return t.ResponseBeginBlock()

    def deliver_tx(self, req: t.RequestDeliverTx) -> t.ResponseDeliverTx:
        if req.tx.startswith(VALIDATOR_TX_PREFIX):
            return self._deliver_validator_tx(req.tx)
        return super().deliver_tx(req)

    def _deliver_validator_tx(self, tx: bytes) -> t.ResponseDeliverTx:
        body = tx[len(VALIDATOR_TX_PREFIX):]
        pk_hex, _, power_s = body.partition(b"!")
        try:
            pub_key = bytes.fromhex(pk_hex.decode())
            power = int(power_s)
            if len(pub_key) != 32 or power < 0:
                raise ValueError
        except ValueError:
            return t.ResponseDeliverTx(
                code=1, log=f"invalid validator tx {tx!r}"
            )
        vu = t.ValidatorUpdate("ed25519", pub_key, power)
        # journaled like the kv writes: a replayed half-block rolls
        # the set back before re-applying
        self._undo_vals.append(
            (pub_key.hex(), self.validators.get(pub_key.hex())))
        self._update_validator(vu)
        self.val_updates.append(vu)
        return t.ResponseDeliverTx(code=t.CODE_TYPE_OK)

    def _update_validator(self, vu: t.ValidatorUpdate) -> None:
        hx = vu.pub_key.hex()
        if vu.power == 0:
            self.validators.pop(hx, None)
        else:
            self.validators[hx] = vu.power

    def end_block(self, req: t.RequestEndBlock) -> t.ResponseEndBlock:
        return t.ResponseEndBlock(validator_updates=self.val_updates)

    def _compute_app_hash(self) -> bytes:
        return struct.pack(">Q", self.size)

    def _mark_committed(self) -> None:
        super()._mark_committed()
        self._undo_vals.clear()

    def commit(self, req: t.RequestCommit) -> t.ResponseCommit:
        self._mark_committed()
        self.app_hash = self._compute_app_hash()
        self.height += 1
        self.db.set(_STATE_KEY, json.dumps({
            "size": self.size,
            "height": self.height,
            "app_hash": self.app_hash.hex(),
            "validators": self.validators,
        }).encode())
        if self.snapshot_interval and \
                self.height % self.snapshot_interval == 0:
            self.db.set(b"snap:%016x" % self.height,
                        self._snapshot_payload())
            snaps = [k for k, _ in self.db.iterate_prefix(b"snap:")]
            for k in snaps[:-self.keep_snapshots]:
                self.db.delete(k)
        resp = t.ResponseCommit(data=self.app_hash)
        if self.retain_blocks > 0 and self.height > self.retain_blocks:
            resp.retain_height = self.height - self.retain_blocks
        return resp

    def query(self, req: t.RequestQuery) -> t.ResponseQuery:
        if req.path == "/val":
            hx = req.data.decode()
            power = self.validators.get(hx, 0)
            return t.ResponseQuery(key=req.data, value=str(power).encode())
        return super().query(req)

    # -- snapshots: one snapshot of the full kv state per height kept --

    def _snapshot_payload(self) -> bytes:
        kvs = {
            k.hex(): v.hex()
            for k, v in self.db.iterate_prefix(b"kv:")
        }
        return json.dumps({
            "kvs": kvs, "size": self.size, "height": self.height,
            "app_hash": self.app_hash.hex(), "validators": self.validators,
        }, sort_keys=True).encode()

    def _stored_snapshots(self) -> list[tuple[int, bytes]]:
        out = [(int(k[len(b"snap:"):], 16), v)
               for k, v in self.db.iterate_prefix(b"snap:")]
        if not out and self.height > 0:
            out = [(self.height, self._snapshot_payload())]
        return out

    def list_snapshots(self, req: t.RequestListSnapshots) -> t.ResponseListSnapshots:
        from ..crypto import tmhash

        snaps = []
        for height, payload in self._stored_snapshots():
            n = max(1, -(-len(payload) // self.SNAPSHOT_CHUNK_SIZE))
            snaps.append(t.Snapshot(height, 1, n, tmhash.sum256(payload)))
        return t.ResponseListSnapshots(snaps)

    def load_snapshot_chunk(
        self, req: t.RequestLoadSnapshotChunk
    ) -> t.ResponseLoadSnapshotChunk:
        payload = None
        for height, p in self._stored_snapshots():
            if height == req.height:
                payload = p
                break
        if payload is None:
            return t.ResponseLoadSnapshotChunk(b"")
        start = req.chunk * self.SNAPSHOT_CHUNK_SIZE
        return t.ResponseLoadSnapshotChunk(
            payload[start : start + self.SNAPSHOT_CHUNK_SIZE]
        )

    def offer_snapshot(self, req: t.RequestOfferSnapshot) -> t.ResponseOfferSnapshot:
        if req.snapshot is None or req.snapshot.format != 1:
            return t.ResponseOfferSnapshot(t.OfferSnapshotResult.REJECT_FORMAT)
        self._restore_chunks: list[bytes] = []
        self._restore_senders: list[str] = []
        self._restore_snapshot = req.snapshot
        return t.ResponseOfferSnapshot(t.OfferSnapshotResult.ACCEPT)

    def apply_snapshot_chunk(
        self, req: t.RequestApplySnapshotChunk
    ) -> t.ResponseApplySnapshotChunk:
        from ..crypto import tmhash

        self._restore_chunks.append(req.chunk)
        self._restore_senders.append(req.sender)
        if len(self._restore_chunks) < self._restore_snapshot.chunks:
            return t.ResponseApplySnapshotChunk(t.ApplySnapshotChunkResult.ACCEPT)
        payload = b"".join(self._restore_chunks)
        if tmhash.sum256(payload) != self._restore_snapshot.hash:
            # The assembled payload is not what the advertised hash
            # promised: at least one chunk is poisoned. Never parse it.
            # When every chunk came from ONE sender the app can convict
            # it by name (reject_senders); otherwise attribution is the
            # syncer's job (single-source retries) and the app just
            # asks for a snapshot retry with its partial state cleared.
            senders = {s for s in self._restore_senders if s}
            self._restore_chunks = []
            self._restore_senders = []
            return t.ResponseApplySnapshotChunk(
                t.ApplySnapshotChunkResult.RETRY_SNAPSHOT,
                reject_senders=sorted(senders) if len(senders) == 1
                else [])
        d = json.loads(payload)
        ops: list[tuple[bytes, bytes | None]] = [
            (bytes.fromhex(k), bytes.fromhex(v)) for k, v in d["kvs"].items()
        ]
        self.size = d["size"]
        self.height = d["height"]
        self.app_hash = bytes.fromhex(d["app_hash"])
        self.validators = d["validators"]
        # restored state is the new rollback point; a stale journal
        # from a block interrupted before the restore must never
        # replay into it
        self._mark_committed()
        ops.append((_STATE_KEY, json.dumps({
            "size": self.size, "height": self.height,
            "app_hash": self.app_hash.hex(), "validators": self.validators,
        }).encode()))
        self.db.write_batch(ops)
        return t.ResponseApplySnapshotChunk(t.ApplySnapshotChunkResult.ACCEPT)


class MerkleKVStoreApp(PersistentKVStoreApp):
    """Proof-capable kvstore: the app hash is an RFC-6962 merkle root
    over the kv pairs sorted by key, and `query(prove=True)` returns
    value/absence proof ops verifiable against a light-verified
    header's app_hash (the capability the reference's light RPC
    client consumes, light/rpc/client.go:104-151 — its example apps
    delegate the proof format to the application, as here; formats in
    abci/kv_proofs.py). Rebuilds the tree per commit — O(n log n) per
    block, fine for an example app."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # Snapshot at construction: nothing is mid-block yet, so the
        # db IS the committed state (a lazy first-query rebuild could
        # race a half-applied block and cache an unprovable tree).
        self._snapshot_committed()

    def _sorted_pairs(self) -> list[tuple[bytes, bytes]]:
        return sorted(
            (k[len(b"kv:"):], v) for k, v in self.db.iterate_prefix(b"kv:")
        )

    def _snapshot_committed(self) -> bytes:
        """Queries must prove against the last COMMITTED state —
        deliver_tx writes the live db mid-block, and a proof over
        half-applied state matches no header's app_hash. The proof
        tree is built once here, not per query."""
        from . import kv_proofs

        self._committed_pairs = self._sorted_pairs()
        root, proofs = merkle.proofs_from_byte_slices(
            [kv_proofs.kv_leaf(k, v) for k, v in self._committed_pairs])
        self._committed_proofs = proofs
        return root

    def _compute_app_hash(self) -> bytes:
        return self._snapshot_committed()

    def query(self, req: t.RequestQuery) -> t.ResponseQuery:
        if req.path == "/val" or not req.prove:
            return super().query(req)
        from . import kv_proofs

        pairs, proofs = self._committed_pairs, self._committed_proofs
        keys = [k for k, _ in pairs]
        import bisect

        j = bisect.bisect_left(keys, req.data)
        total = len(pairs)
        if j < total and keys[j] == req.data:
            op = kv_proofs.KVValueOp.encode(req.data, total, proofs[j])
            value, log = pairs[j][1], "exists"
        else:
            left = (pairs[j - 1][0], pairs[j - 1][1], proofs[j - 1]) \
                if j > 0 else None
            right = (pairs[j][0], pairs[j][1], proofs[j]) \
                if j < total else None
            op = kv_proofs.KVAbsenceOp.encode(req.data, total, left, right)
            value, log = b"", "does not exist"
        return t.ResponseQuery(
            key=req.data, value=value, log=log, height=self.height,
            proof_ops=[op],
        )

    def apply_snapshot_chunk(
        self, req: t.RequestApplySnapshotChunk
    ) -> t.ResponseApplySnapshotChunk:
        resp = super().apply_snapshot_chunk(req)
        if len(self._restore_chunks) >= self._restore_snapshot.chunks:
            self._snapshot_committed()  # restored db is the new state
        return resp
