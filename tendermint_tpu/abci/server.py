"""ABCI socket server for out-of-process apps (reference:
abci/server/socket_server.go).

One handler task per accepted connection; requests on a connection are
dispatched to the app serially under a server-wide lock (the reference
guards the app with one mutex across its 4 logical connections)."""

from __future__ import annotations

import asyncio

from ..libs.service import Service
from . import types as t
from .client import read_frame, write_frame


class SocketServer(Service):
    def __init__(self, app: t.Application, host: str = "127.0.0.1",
                 port: int = 26658, unix_path: str | None = None):
        super().__init__(name="abci.SocketServer")
        self.app = app
        self.host, self.port, self.unix_path = host, port, unix_path
        self._server: asyncio.AbstractServer | None = None
        self._app_lock = asyncio.Lock()

    async def on_start(self) -> None:
        if self.unix_path:
            self._server = await asyncio.start_unix_server(
                self._handle, self.unix_path
            )
        else:
            self._server = await asyncio.start_server(
                self._handle, self.host, self.port
            )
            # pick up the OS-assigned port when port=0 was requested
            self.port = self._server.sockets[0].getsockname()[1]

    async def on_stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                req = await read_frame(reader)
                resp = await self._dispatch(req)
                write_frame(writer, resp)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            writer.close()

    async def _dispatch(self, req):
        if isinstance(req, t.RequestEcho):
            return t.ResponseEcho(req.message)
        if isinstance(req, t.RequestFlush):
            return t.ResponseFlush()
        method = t.HANDLERS.get(type(req))
        if method is None:
            return t.ResponseException(f"unknown request {type(req).__name__}")
        try:
            async with self._app_lock:
                return getattr(self.app, method)(req)
        except Exception as e:  # app bug -> error frame, not dead conn
            self.logger.error("app %s failed: %r", method, e)
            return t.ResponseException(repr(e))
