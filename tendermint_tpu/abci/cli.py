"""abci-cli — protocol test driver (reference: abci/cmd/abci-cli).

Run an example app as a server:
    python -m tendermint_tpu.abci.cli kvstore --address tcp://127.0.0.1:26658 --abci socket|grpc
Drive any ABCI server interactively or from a script:
    python -m tendermint_tpu.abci.cli console --address ... --abci ...
    python -m tendermint_tpu.abci.cli batch < script.abci
    python -m tendermint_tpu.abci.cli echo hello / info / deliver_tx "abc" / ...

Output format mirrors the reference's printResponse (abci/cmd/abci-cli
/abci-cli.go): `-> code: OK`, `-> data: ...`, `-> data.hex: 0x...`,
query extras — so golden files diff the same way the reference's
abci/tests/test_cli goldens do.
"""

from __future__ import annotations

import argparse
import asyncio
import shlex
import sys

from . import types as t
from .client import Client, SocketClient
from .server import SocketServer


def _parse_bytes(arg: str) -> bytes:
    """Reference semantics: quoted strings are raw; 0x... is hex."""
    if arg.startswith("0x"):
        return bytes.fromhex(arg[2:])
    if len(arg) >= 2 and arg[0] == '"' and arg[-1] == '"':
        arg = arg[1:-1]
    return arg.encode()


def _printable(b: bytes) -> bool:
    return all(0x20 <= c < 0x7F for c in b)


def _print_response(res, out=sys.stdout) -> None:
    code = getattr(res, "code", 0)
    out.write(f"-> code: {'OK' if code == 0 else code}\n")
    if isinstance(res, t.ResponseEcho):
        data = res.message.encode()
    else:
        data = getattr(res, "data", b"")
        if isinstance(data, str):
            data = data.encode()
    log = getattr(res, "log", "")
    if data:
        if _printable(data):
            out.write(f"-> data: {data.decode()}\n")
        out.write(f"-> data.hex: 0x{data.hex().upper()}\n")
    if log:
        out.write(f"-> log: {log}\n")
    if isinstance(res, t.ResponseQuery):
        out.write(f"-> height: {res.height}\n")
        if res.key:
            if _printable(res.key):
                out.write(f"-> key: {res.key.decode()}\n")
            out.write(f"-> key.hex: {res.key.hex().upper()}\n")
        if res.value:
            if _printable(res.value):
                out.write(f"-> value: {res.value.decode()}\n")
            out.write(f"-> value.hex: {res.value.hex().upper()}\n")


async def _exec_line(client: Client, line: str, out=sys.stdout) -> bool:
    """Run one command line; returns False on unknown command."""
    parts = shlex.split(line, posix=False)
    if not parts:
        return True
    cmd, args = parts[0], parts[1:]
    if cmd == "echo":
        res = await client.echo(args[0] if args else "")
    elif cmd == "info":
        res = await client.info(t.RequestInfo(version="abci-cli"))
    elif cmd == "deliver_tx":
        res = await client.deliver_tx(
            t.RequestDeliverTx(_parse_bytes(args[0] if args else "")))
    elif cmd == "check_tx":
        res = await client.check_tx(
            t.RequestCheckTx(_parse_bytes(args[0] if args else "")))
    elif cmd == "commit":
        res = await client.commit()
    elif cmd == "query":
        res = await client.query(
            t.RequestQuery(data=_parse_bytes(args[0] if args else "")))
    else:
        out.write(f"-> error: unknown command {cmd!r}\n")
        return False
    _print_response(res, out)
    return True


def _addr(s: str) -> tuple[str, int]:
    from ..libs.net import split_laddr

    return split_laddr(s, default_host="127.0.0.1")


def _new_client(args) -> Client:
    host, port = _addr(args.address)
    if args.abci == "grpc":
        from .grpc_client import GRPCClient

        return GRPCClient(host, port)
    return SocketClient(host, port)


async def _run_lines(args, lines, echo_input: bool) -> int:
    client = _new_client(args)
    await client.start()
    ok = True
    try:
        first = True
        for line in lines:
            line = line.strip()
            if not line:
                continue
            if echo_input:
                if not first:
                    sys.stdout.write("\n")
                sys.stdout.write(f"> {line}\n")
            first = False
            ok = await _exec_line(client, line) and ok
        # nonzero on any unknown command, like the reference abci-cli
        return 0 if ok else 1
    finally:
        await client.stop()


async def _console(args) -> int:
    client = _new_client(args)
    await client.start()
    try:
        loop = asyncio.get_running_loop()
        while True:
            sys.stdout.write("> ")
            sys.stdout.flush()
            line = await loop.run_in_executor(None, sys.stdin.readline)
            if not line:
                return 0
            await _exec_line(client, line.strip())
    finally:
        await client.stop()


async def _serve(args, app) -> int:
    host, port = _addr(args.address)
    if args.abci == "grpc":
        from .grpc_server import GRPCServer

        server = GRPCServer(app, host, port)
    else:
        server = SocketServer(app, host, port)
    await server.start()
    print(f"serving {type(app).__name__} abci={args.abci} "
          f"on {host}:{server.port}", flush=True)
    stop = asyncio.Event()
    import signal

    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # pragma: no cover
            pass
    await stop.wait()
    await server.stop()
    return 0


def main(argv=None) -> int:
    # Flags accepted both before and after the subcommand. SUPPRESS
    # keeps a subparser from clobbering a value parsed at the top
    # level; real defaults are set once via set_defaults below.
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--address", default=argparse.SUPPRESS)
    common.add_argument("--abci", choices=("socket", "grpc"),
                        default=argparse.SUPPRESS)
    p = argparse.ArgumentParser(prog="abci-cli", description=__doc__,
                                parents=[common])
    sub = p.add_subparsers(dest="command", required=True)
    for name in ("echo", "info", "deliver_tx", "check_tx", "commit",
                 "query"):
        sp = sub.add_parser(name, parents=[common])
        sp.add_argument("arg", nargs="?", default="")
    sub.add_parser("batch", parents=[common],
                   help="read commands from stdin")
    sub.add_parser("console", parents=[common],
                   help="interactive prompt")
    sub.add_parser("kvstore", parents=[common],
                   help="serve the in-memory kvstore app")
    sub.add_parser("counter", parents=[common],
                   help="serve the counter app")
    args = p.parse_args(argv)
    # Defaults applied AFTER parsing: with parents, the action objects
    # are shared between the top parser and every subparser, so a
    # parser-level default would let the subparser clobber a value
    # given before the subcommand.
    if not hasattr(args, "address"):
        args.address = "tcp://127.0.0.1:26658"
    if not hasattr(args, "abci"):
        args.abci = "socket"

    if args.command == "batch":
        return asyncio.run(
            _run_lines(args, sys.stdin.readlines(), echo_input=True))
    if args.command == "console":
        return asyncio.run(_console(args))
    if args.command == "kvstore":
        from .kvstore import KVStoreApp

        return asyncio.run(_serve(args, KVStoreApp()))
    if args.command == "counter":
        from .counter import CounterApp

        return asyncio.run(_serve(args, CounterApp()))
    line = args.command
    if args.arg:
        line += " " + args.arg
    return asyncio.run(_run_lines(args, [line], echo_input=False))


if __name__ == "__main__":
    sys.exit(main())
