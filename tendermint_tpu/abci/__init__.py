"""ABCI — the application blockchain interface (reference: abci/).

The 12-method Application contract (abci/types/application.go:11-31) in
four connection groups: Info/Query, CheckTx (mempool), InitChain/
BeginBlock/DeliverTx/EndBlock/Commit (consensus), and the four
snapshot methods (statesync). Echo/Flush are transport-level.

Messages are plain dataclasses (types.py); transports are in-process
(client.LocalClient) and varint-framed socket (client.SocketClient /
server.SocketServer).
"""

from .types import (  # noqa: F401
    Application,
    CheckTxType,
    CODE_TYPE_OK,
    RequestBeginBlock,
    RequestCheckTx,
    RequestCommit,
    RequestDeliverTx,
    RequestEcho,
    RequestEndBlock,
    RequestInfo,
    RequestInitChain,
    RequestQuery,
    ResponseBeginBlock,
    ResponseCheckTx,
    ResponseCommit,
    ResponseDeliverTx,
    ResponseEcho,
    ResponseEndBlock,
    ResponseInfo,
    ResponseInitChain,
    ResponseQuery,
    Snapshot,
    ValidatorUpdate,
)
