"""ABCI clients (reference: abci/client/).

LocalClient wraps an in-process Application behind a per-connection
asyncio.Lock (reference: local_client.go's mutex). SocketClient speaks
the varint-length-framed message protocol to an out-of-process app and
pipelines requests: callers get futures resolved in strict FIFO order
by the response reader (reference: socket_client.go:36,128,167 —
same pipelining model, asyncio-native instead of goroutines+reqQueue).
"""

from __future__ import annotations

import asyncio

from ..encoding.proto import encode_varint
from ..libs.service import Service
from . import types as t


class ABCIClientError(Exception):
    pass


class Client(Service):
    """Interface: deliver(req) -> response; flush() drains the pipe."""

    async def deliver(self, req):
        raise NotImplementedError

    async def flush(self) -> None:
        pass

    # typed sugar
    async def echo(self, msg: str) -> t.ResponseEcho:
        return await self.deliver(t.RequestEcho(msg))

    async def info(self, req: t.RequestInfo) -> t.ResponseInfo:
        return await self.deliver(req)

    async def query(self, req: t.RequestQuery) -> t.ResponseQuery:
        return await self.deliver(req)

    async def check_tx(self, req: t.RequestCheckTx) -> t.ResponseCheckTx:
        return await self.deliver(req)

    async def init_chain(self, req: t.RequestInitChain) -> t.ResponseInitChain:
        return await self.deliver(req)

    async def begin_block(self, req: t.RequestBeginBlock) -> t.ResponseBeginBlock:
        return await self.deliver(req)

    async def deliver_tx(self, req: t.RequestDeliverTx) -> t.ResponseDeliverTx:
        return await self.deliver(req)

    async def end_block(self, req: t.RequestEndBlock) -> t.ResponseEndBlock:
        return await self.deliver(req)

    async def commit(self) -> t.ResponseCommit:
        return await self.deliver(t.RequestCommit())

    async def list_snapshots(self) -> t.ResponseListSnapshots:
        return await self.deliver(t.RequestListSnapshots())

    async def offer_snapshot(
        self, req: t.RequestOfferSnapshot
    ) -> t.ResponseOfferSnapshot:
        return await self.deliver(req)

    async def load_snapshot_chunk(
        self, req: t.RequestLoadSnapshotChunk
    ) -> t.ResponseLoadSnapshotChunk:
        return await self.deliver(req)

    async def apply_snapshot_chunk(
        self, req: t.RequestApplySnapshotChunk
    ) -> t.ResponseApplySnapshotChunk:
        return await self.deliver(req)

    def submit(self, req) -> asyncio.Task:
        """Fire a request without awaiting — the async-pipelined
        DeliverTx path (reference: socket_client.go DeliverTxAsync)."""
        return asyncio.get_running_loop().create_task(self.deliver(req))

    def in_flight(self) -> int:
        """Requests accepted but not yet answered on this connection —
        the admission-control window the mempool's busy check reads
        (mempool/clist_mempool.py): a saturated app must shed new
        CheckTx work, not queue it unboundedly."""
        return 0


class LocalClient(Client):
    """In-process client; one lock per connection serializes app calls
    (the app itself may be shared by several LocalClients, matching the
    reference where one mutex guards the app across connections)."""

    def __init__(self, app: t.Application, lock: asyncio.Lock | None = None):
        super().__init__(name="abci.LocalClient")
        self.app = app
        self._lock = lock or asyncio.Lock()
        self._in_flight = 0

    async def deliver(self, req):
        if isinstance(req, t.RequestEcho):
            return t.ResponseEcho(req.message)
        if isinstance(req, t.RequestFlush):
            return t.ResponseFlush()
        method = t.HANDLERS[type(req)]
        # waiting on the shared app lock counts as in flight: that IS
        # the saturated-app condition admission control sheds on
        self._in_flight += 1
        try:
            async with self._lock:
                return getattr(self.app, method)(req)
        finally:
            self._in_flight -= 1

    def in_flight(self) -> int:
        return self._in_flight


# --- socket framing: varint length prefix + JSON message ---------------------


def write_frame(writer: asyncio.StreamWriter, msg) -> None:
    data = t.encode_msg(msg)
    writer.write(encode_varint(len(data)) + data)


async def read_frame(reader: asyncio.StreamReader):
    # read varint byte-by-byte, then the payload
    ln = shift = 0
    while True:
        b = await reader.readexactly(1)
        ln |= (b[0] & 0x7F) << shift
        if not b[0] & 0x80:
            break
        shift += 7
        if shift > 35:
            raise ABCIClientError("frame length varint too long")
    if ln > 64 << 20:
        raise ABCIClientError("frame too large")
    return t.decode_msg(await reader.readexactly(ln))


class SocketClient(Client):
    """Pipelined socket client. Responses arrive strictly in request
    order, so a FIFO of futures pairs them back up.

    A lost connection no longer kills the client for good: in-flight
    requests fail fast (they may or may not have been executed — the
    caller's replay/handshake logic owns that ambiguity, so NOTHING is
    silently retried here), and a background task re-dials the app
    with capped jittered exponential backoff. Once the transport is
    back, new requests flow again — a restarted ABCI app server no
    longer requires restarting the node (reference behavior was to
    die with the connection)."""

    RECONNECT_BASE_S = 0.5
    RECONNECT_MAX_S = 15.0

    def __init__(self, host: str = "127.0.0.1", port: int = 26658,
                 unix_path: str | None = None):
        super().__init__(name="abci.SocketClient")
        self.host, self.port, self.unix_path = host, port, unix_path
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._pending: asyncio.Queue[asyncio.Future] = asyncio.Queue()
        self._conn_err: Exception | None = None

    async def _connect(self) -> None:
        if self.unix_path:
            self._reader, self._writer = await asyncio.open_unix_connection(
                self.unix_path
            )
        else:
            self._reader, self._writer = await asyncio.open_connection(
                self.host, self.port
            )

    async def on_start(self) -> None:
        await self._connect()
        self.spawn(self._recv_loop(), name="abci-recv")

    async def on_stop(self) -> None:
        if self._writer is not None:
            self._writer.close()

    async def _recv_loop(self) -> None:
        assert self._reader is not None
        try:
            while True:
                resp = await read_frame(self._reader)
                fut = await self._pending.get()
                if fut.done():  # caller gave up (e.g. wait_for timeout)
                    continue
                if isinstance(resp, t.ResponseException):
                    fut.set_exception(ABCIClientError(resp.error))
                else:
                    fut.set_result(resp)
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
            self._conn_err = e
            while not self._pending.empty():
                fut = self._pending.get_nowait()
                if not fut.done():
                    fut.set_exception(ABCIClientError(f"connection lost: {e}"))
            if self.is_running:
                self.spawn(self._reconnect_loop(), name="abci-reconnect")

    async def _reconnect_loop(self) -> None:
        import logging

        from ..libs.metrics import abci_metrics
        from ..libs.net import jittered_backoff

        log = logging.getLogger("abci.client")
        attempt = 0
        while self.is_running:
            await asyncio.sleep(jittered_backoff(
                attempt, self.RECONNECT_BASE_S, self.RECONNECT_MAX_S))
            if not self.is_running:
                return
            attempt += 1
            try:
                if self._writer is not None:
                    self._writer.close()
                await self._connect()
            except (ConnectionError, OSError) as e:
                abci_metrics().client_reconnects.inc(result="failed")
                log.warning("ABCI app reconnect attempt %d failed: %s",
                            attempt, e)
                continue
            # any future that raced into the queue after the recv
            # loop's drain must not mispair with responses on the NEW
            # connection (the FIFO would be off by one forever)
            while not self._pending.empty():
                fut = self._pending.get_nowait()
                if not fut.done():
                    fut.set_exception(ABCIClientError(
                        "connection replaced during reconnect"))
            self._conn_err = None
            abci_metrics().client_reconnects.inc(result="ok")
            log.warning("ABCI app connection re-established after "
                        "%d attempts", attempt)
            self.spawn(self._recv_loop(), name="abci-recv")
            return

    async def deliver(self, req):
        if self._conn_err is not None:
            raise ABCIClientError(f"connection lost: {self._conn_err}")
        assert self._writer is not None, "client not started"
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._pending.put(fut)
        write_frame(self._writer, req)
        await self._writer.drain()
        return await fut

    async def flush(self) -> None:
        await self.deliver(t.RequestFlush())

    def in_flight(self) -> int:
        return self._pending.qsize()


class ClientCreator:
    """Factory handed to proxy.AppConns: local app, socket addr, or
    gRPC addr (reference: proxy/client.go NewLocalClientCreator/
    NewRemoteClientCreator with transport "socket"|"grpc")."""

    def __init__(self, app: t.Application | None = None,
                 addr: tuple[str, int] | None = None,
                 unix_path: str | None = None,
                 grpc_addr: tuple[str, int] | None = None,
                 shared_lock: bool = True):
        self.app = app
        self.addr = addr
        self.unix_path = unix_path
        self.grpc_addr = grpc_addr
        self._lock = asyncio.Lock() if (app is not None and shared_lock) else None

    def new_client(self) -> Client:
        if self.app is not None:
            return LocalClient(self.app, self._lock)
        if self.unix_path is not None:
            return SocketClient(unix_path=self.unix_path)
        if self.grpc_addr is not None:
            from .grpc_client import GRPCClient

            return GRPCClient(self.grpc_addr[0], self.grpc_addr[1])
        assert self.addr is not None
        return SocketClient(self.addr[0], self.addr[1])
