"""ABCI gRPC client (reference: abci/client/grpc_client.go).

Each `deliver` is one unary RPC on the ABCIApplication service; gRPC
does its own multiplexing/flow control, so unlike the socket client
there is no FIFO future queue and `flush` degenerates to the Flush RPC
(reference grpc_client.go keeps Flush for interface parity too).
"""

from __future__ import annotations

import grpc
from grpc import aio

from . import types as t
from .client import ABCIClientError, Client
from .grpc_server import METHOD_BY_TYPE, SERVICE_NAME


class GRPCClient(Client):
    """gRPC channels reconnect transparently (built-in backoff), so a
    restarted app server is usually picked up without help. The one
    hole: a channel that has collapsed into a terminal/broken state
    keeps failing every RPC with UNAVAILABLE. After a few consecutive
    UNAVAILABLEs the channel is torn down and recreated so the client
    recovers instead of dying with the app connection (each failed RPC
    still fails fast — nothing is silently retried)."""

    RECREATE_AFTER_UNAVAILABLE = 3

    def __init__(self, host: str = "127.0.0.1", port: int = 26658):
        super().__init__(name="abci.GRPCClient")
        self.host, self.port = host, port
        self._channel: aio.Channel | None = None
        self._stubs: dict[str, object] = {}
        self._unavailable_streak = 0

    async def _recreate_channel(self) -> None:
        from ..libs.metrics import abci_metrics

        # Swap atomically BEFORE any await: pipelined delivers run
        # concurrently, and a window where _channel is None would turn
        # their failures into bare AssertionErrors (which consensus
        # does not handle) instead of ABCIClientError. Resetting the
        # streak in the same synchronous block also keeps a second
        # concurrent UNAVAILABLE from recreating (and closing) the
        # fresh channel out from under callers already using it.
        old = self._channel
        self._channel = aio.insecure_channel(f"{self.host}:{self.port}")
        self._stubs.clear()
        self._unavailable_streak = 0
        abci_metrics().client_reconnects.inc(result="grpc_recreate")
        if old is not None:
            try:
                await old.close()
            except Exception:
                pass

    async def on_start(self) -> None:
        self._channel = aio.insecure_channel(f"{self.host}:{self.port}")

    async def on_stop(self) -> None:
        if self._channel is not None:
            await self._channel.close()

    def _stub(self, method: str):
        stub = self._stubs.get(method)
        if stub is None:
            assert self._channel is not None, "client not started"
            stub = self._channel.unary_unary(
                f"/{SERVICE_NAME}/{method}",
                request_serializer=t.encode_msg,
                response_deserializer=t.decode_msg,
            )
            self._stubs[method] = stub
        return stub

    async def deliver(self, req):
        method = METHOD_BY_TYPE.get(type(req))
        if method is None:
            raise ABCIClientError(f"unknown request {type(req).__name__}")
        self._in_flight = getattr(self, "_in_flight", 0) + 1
        try:
            return await self._deliver_rpc(method, req)
        finally:
            self._in_flight -= 1

    def in_flight(self) -> int:
        return getattr(self, "_in_flight", 0)

    async def _deliver_rpc(self, method, req):
        try:
            resp = await self._stub(method)(req)
            self._unavailable_streak = 0
            return resp
        except aio.AioRpcError as e:
            if e.code() == grpc.StatusCode.UNAVAILABLE and self.is_running:
                self._unavailable_streak += 1
                if self._unavailable_streak >= \
                        self.RECREATE_AFTER_UNAVAILABLE:
                    await self._recreate_channel()
            raise ABCIClientError(
                f"{method}: {e.code().name}: {e.details()}") from e

    async def flush(self) -> None:
        await self.deliver(t.RequestFlush())
