"""ABCI gRPC client (reference: abci/client/grpc_client.go).

Each `deliver` is one unary RPC on the ABCIApplication service; gRPC
does its own multiplexing/flow control, so unlike the socket client
there is no FIFO future queue and `flush` degenerates to the Flush RPC
(reference grpc_client.go keeps Flush for interface parity too).
"""

from __future__ import annotations

import grpc
from grpc import aio

from . import types as t
from .client import ABCIClientError, Client
from .grpc_server import METHOD_BY_TYPE, SERVICE_NAME


class GRPCClient(Client):
    def __init__(self, host: str = "127.0.0.1", port: int = 26658):
        super().__init__(name="abci.GRPCClient")
        self.host, self.port = host, port
        self._channel: aio.Channel | None = None
        self._stubs: dict[str, object] = {}

    async def on_start(self) -> None:
        self._channel = aio.insecure_channel(f"{self.host}:{self.port}")

    async def on_stop(self) -> None:
        if self._channel is not None:
            await self._channel.close()

    def _stub(self, method: str):
        stub = self._stubs.get(method)
        if stub is None:
            assert self._channel is not None, "client not started"
            stub = self._channel.unary_unary(
                f"/{SERVICE_NAME}/{method}",
                request_serializer=t.encode_msg,
                response_deserializer=t.decode_msg,
            )
            self._stubs[method] = stub
        return stub

    async def deliver(self, req):
        method = METHOD_BY_TYPE.get(type(req))
        if method is None:
            raise ABCIClientError(f"unknown request {type(req).__name__}")
        try:
            return await self._stub(method)(req)
        except aio.AioRpcError as e:
            raise ABCIClientError(
                f"{method}: {e.code().name}: {e.details()}") from e

    async def flush(self) -> None:
        await self.deliver(t.RequestFlush())
