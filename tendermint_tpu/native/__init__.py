"""Native (C) runtime components, loaded via ctypes.

Build-on-first-use: cc -O3 -shared compiles the sibling .c into a
cached .so (atomic rename, concurrent-build safe). Everything here is
OPTIONAL — callers keep a pure-numpy fallback, so a box without a C
compiler still runs, just with more host time per batch."""

from __future__ import annotations

import ctypes
import logging
import os
import shutil
import subprocess
import tempfile

import numpy as np
from numpy.ctypeslib import ndpointer

logger = logging.getLogger("native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_lib = None
_tried = False


def _build_so() -> str | None:
    src = os.path.join(_DIR, "pack.c")
    so = os.path.join(_DIR, "_pack.so")
    try:
        if os.path.exists(so) and \
                os.path.getmtime(so) >= os.path.getmtime(src):
            return so
    except OSError:
        # .so present but source missing (prebuilt deployment):
        # the cached binary is all we need
        return so if os.path.exists(so) else None
    if not os.path.exists(src):
        return None
    cc = shutil.which("cc") or shutil.which("gcc") or shutil.which("clang")
    if cc is None:
        return None
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
    os.close(fd)
    try:
        subprocess.run([cc, "-O3", "-shared", "-fPIC", src, "-o", tmp],
                       check=True, capture_output=True, timeout=60)
        os.replace(tmp, so)  # atomic; concurrent builders all win
        return so
    except Exception as e:  # compiler missing/broken: numpy fallback
        logger.warning("native build failed (%s); using numpy paths", e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def lib():
    """The loaded native library, or None (fallback to numpy)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    so = _build_so()
    if so is None:
        return None
    try:
        L = ctypes.CDLL(so)
        L.tm_pack_pad.restype = None
        L.tm_pack_pad.argtypes = [
            ndpointer(np.uint8, flags="C_CONTIGUOUS"),   # flat
            ndpointer(np.int64, flags="C_CONTIGUOUS"),   # starts
            ndpointer(np.int64, flags="C_CONTIGUOUS"),   # lens
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ndpointer(np.uint8, flags="C_CONTIGUOUS"),   # out
            ndpointer(np.int64, flags="C_CONTIGUOUS"),   # nblocks
        ]
        _lib = L
    except OSError as e:  # pragma: no cover
        logger.warning("native load failed (%s); using numpy paths", e)
    return _lib
