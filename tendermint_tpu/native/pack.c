/* Native host-packing hot path (SURVEY §2.10: native runtime
 * components around the JAX compute path).
 *
 * SHA-512 message padding for the batch-verify launch: one pass over
 * the flattened messages, memcpy per lane + 0x80 terminator + 128-bit
 * big-endian bit length at the end of each lane's final block,
 * assuming `prefix_len` fixed bytes (R||A = 64) are prepended on
 * device. Replaces ~2.5 ms of numpy fancy-indexing at 10,240 lanes
 * with a ~0.2 ms C loop — host packing serializes ahead of the device
 * launch in a cold verify, so it sits on the <5 ms commit budget
 * (docs/PERF_NOTES.md).
 *
 * Caller contract (see tendermint_tpu/native/__init__.py):
 *   - out is zero-initialized, n rows of `width` bytes
 *   - width >= max(nblocks)*128 - prefix_len
 *   - bit lengths fit 64 bits (messages far below 2^61 bytes)
 */

#include <stdint.h>
#include <string.h>

void tm_pack_pad(const uint8_t *flat, const int64_t *starts,
                 const int64_t *lens, int64_t n, int64_t width,
                 int64_t prefix_len, uint8_t *out, int64_t *nblocks)
{
    for (int64_t i = 0; i < n; i++) {
        int64_t len = lens[i];
        uint8_t *row = out + i * width;
        memcpy(row, flat + starts[i], (size_t)len);
        row[len] = 0x80;
        int64_t total = len + prefix_len;
        int64_t nb = (total + 1 + 16 + 127) / 128;
        nblocks[i] = nb;
        uint64_t bitlen = (uint64_t)total * 8u;
        int64_t end = nb * 128 - prefix_len;
        for (int k = 0; k < 8; k++)
            row[end - 1 - k] = (uint8_t)(bitlen >> (8 * k));
    }
}
