"""State sync: bootstrap a fresh node from an application snapshot
instead of replaying the whole chain (reference: statesync/).

Flow (reference syncer.go:141 SyncAny): discover snapshots from peers
→ rank them → offer the best to the app over the snapshot ABCI conn →
fetch chunks from all peers that have the snapshot → apply → confirm
the restored app hash against a LIGHT-CLIENT-verified header → hand a
trusted sm.State to the node, which bootstraps its stores and drops
into fast sync for the tail."""

from .messages import (
    ChunkRequestMessage,
    ChunkResponseMessage,
    SnapshotsRequestMessage,
    SnapshotsResponseMessage,
    decode_ss_msg,
    encode_ss_msg,
)
from .snapshots import SnapshotPool
from .stateprovider import LightClientStateProvider, StateProvider
from .syncer import StateSyncError, Syncer


def __getattr__(name: str):
    # The reactor is the only submodule that pulls in the p2p stack
    # (and its optional `cryptography` dependency); loading it lazily
    # keeps the pure-ish core (Syncer, SnapshotPool, messages) — and
    # its chaos/unit tests — importable without transport deps.
    if name in ("StateSyncReactor", "SNAPSHOT_CHANNEL", "CHUNK_CHANNEL"):
        from . import reactor

        return getattr(reactor, name)
    raise AttributeError(name)

__all__ = [
    "StateSyncReactor", "SNAPSHOT_CHANNEL", "CHUNK_CHANNEL",
    "Syncer", "StateSyncError", "SnapshotPool",
    "StateProvider", "LightClientStateProvider",
    "SnapshotsRequestMessage", "SnapshotsResponseMessage",
    "ChunkRequestMessage", "ChunkResponseMessage",
    "encode_ss_msg", "decode_ss_msg",
]
