"""Trusted-state provider for statesync
(reference: statesync/stateprovider.go:28).

Builds the sm.State a node resumes from after restoring a snapshot at
height h — every field comes from LIGHT-CLIENT-VERIFIED headers, so a
lying snapshot peer can at worst waste bandwidth, never forge state:

  h   → last block (the snapshotted height)
  h+1 → current block: its app_hash is what the restored app must match
  h+2 → next block: carries the valset that takes effect after h+1
"""

from __future__ import annotations

from ..state import State
from ..types.params import ConsensusParams


class StateProvider:
    async def app_hash(self, height: int) -> bytes:
        raise NotImplementedError

    async def commit(self, height: int):
        raise NotImplementedError

    async def state(self, height: int) -> State:
        raise NotImplementedError


class LightClientStateProvider(StateProvider):
    def __init__(self, light_client, initial_height: int = 1,
                 consensus_params: ConsensusParams | None = None):
        self.lc = light_client
        self.initial_height = initial_height or 1
        # params can't be light-verified in the reference either (they
        # aren't in the header); taken from config/genesis
        self.consensus_params = consensus_params or ConsensusParams()

    async def app_hash(self, height: int) -> bytes:
        """App hash the restored snapshot must reproduce — lives in the
        NEXT header (reference stateprovider.go:90 AppHash; it also
        probes h+2 so State() can't fail later)."""
        # verify h FIRST: the client only walks forward, so later
        # State()/Commit() calls for h must find it already trusted
        await self.lc.verify_light_block_at_height(height)
        nxt = await self.lc.verify_light_block_at_height(height + 1)
        await self.lc.verify_light_block_at_height(height + 2)
        return nxt.signed_header.header.app_hash

    async def commit(self, height: int):
        lb = await self.lc.verify_light_block_at_height(height)
        return lb.signed_header.commit

    async def state(self, height: int) -> State:
        last = await self.lc.verify_light_block_at_height(height)
        cur = await self.lc.verify_light_block_at_height(height + 1)
        nxt = await self.lc.verify_light_block_at_height(height + 2)
        return State(
            chain_id=self.lc.chain_id,
            initial_height=self.initial_height,
            last_block_height=last.height(),
            last_block_id=last.signed_header.commit.block_id,
            last_block_time=last.time(),
            validators=cur.validator_set.copy(),
            next_validators=nxt.validator_set.copy(),
            last_validators=last.validator_set.copy(),
            last_height_validators_changed=nxt.height(),
            consensus_params=self.consensus_params,
            last_height_consensus_params_changed=self.initial_height,
            last_results_hash=cur.signed_header.header.last_results_hash,
            app_hash=cur.signed_header.header.app_hash,
        )
