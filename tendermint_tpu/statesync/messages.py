"""State-sync wire messages, channels 0x60/0x61
(reference: statesync/messages.go)."""

from __future__ import annotations

from dataclasses import dataclass

from ..encoding.proto import Reader, Writer

MAX_MSG_SIZE = 16_777_216 + 1024  # 16MB chunks (reference chunks.go)


@dataclass
class SnapshotsRequestMessage:
    pass


@dataclass
class SnapshotsResponseMessage:
    height: int
    format: int
    chunks: int
    hash: bytes
    metadata: bytes = b""


@dataclass
class ChunkRequestMessage:
    height: int
    format: int
    index: int


@dataclass
class ChunkResponseMessage:
    height: int
    format: int
    index: int
    chunk: bytes = b""
    missing: bool = False


_TAG = {
    SnapshotsRequestMessage: 1,
    SnapshotsResponseMessage: 2,
    ChunkRequestMessage: 3,
    ChunkResponseMessage: 4,
}
_BY_TAG = {v: k for k, v in _TAG.items()}


def encode_ss_msg(msg) -> bytes:
    w = Writer()
    if isinstance(msg, SnapshotsResponseMessage):
        w.varint(1, msg.height)
        w.varint(2, msg.format)
        w.varint(3, msg.chunks)
        w.bytes(4, msg.hash)
        w.bytes(5, msg.metadata)
    elif isinstance(msg, ChunkRequestMessage):
        w.varint(1, msg.height)
        w.varint(2, msg.format)
        w.varint(3, msg.index, skip_zero=False)
    elif isinstance(msg, ChunkResponseMessage):
        w.varint(1, msg.height)
        w.varint(2, msg.format)
        w.varint(3, msg.index, skip_zero=False)
        w.bytes(4, msg.chunk)
        w.bool(5, msg.missing)
    elif not isinstance(msg, SnapshotsRequestMessage):
        raise ValueError(f"unknown statesync message {type(msg)}")
    return bytes([_TAG[type(msg)]]) + w.finish()


def decode_ss_msg(data: bytes):
    if not data:
        raise ValueError("empty statesync message")
    if len(data) > MAX_MSG_SIZE:
        raise ValueError("statesync message exceeds max size")
    cls = _BY_TAG.get(data[0])
    if cls is None:
        raise ValueError(f"unknown statesync message tag {data[0]}")
    r = Reader(data[1:])
    if cls is SnapshotsRequestMessage:
        return cls()
    fields: dict[int, object] = {}
    while not r.at_end():
        f, wt = r.field()
        if wt == 0:
            fields[f] = r.varint()
        elif wt == 2:
            fields[f] = r.bytes()
        else:
            r.skip(wt)
    if cls is SnapshotsResponseMessage:
        msg = cls(height=int(fields.get(1, 0)), format=int(fields.get(2, 0)),
                  chunks=int(fields.get(3, 0)), hash=fields.get(4, b""),
                  metadata=fields.get(5, b""))
        if msg.height < 1 or msg.chunks < 1 or not msg.hash:
            raise ValueError("invalid snapshots response")
        return msg
    if cls is ChunkRequestMessage:
        msg = cls(height=int(fields.get(1, 0)), format=int(fields.get(2, 0)),
                  index=int(fields.get(3, 0)))
        if msg.height < 1 or msg.index < 0:
            raise ValueError("invalid chunk request")
        return msg
    msg = ChunkResponseMessage(
        height=int(fields.get(1, 0)), format=int(fields.get(2, 0)),
        index=int(fields.get(3, 0)), chunk=fields.get(4, b""),
        missing=bool(fields.get(5, 0)))
    if msg.height < 1 or msg.index < 0:
        raise ValueError("invalid chunk response")
    return msg
