"""Snapshot pool: collect snapshot advertisements from peers and rank
them (reference: statesync/snapshots.go:45 snapshotPool).

Ranking (reference :176 Best): higher height first, then lower format
... then most peers. Rejected snapshots/formats/peers are remembered
so SyncAny never retries them."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Snapshot:
    height: int
    format: int
    chunks: int
    hash: bytes
    metadata: bytes = b""

    def key(self) -> tuple:
        return (self.height, self.format, self.chunks, self.hash)


class SnapshotPool:
    def __init__(self):
        self._snapshots: dict[tuple, Snapshot] = {}
        self._peers: dict[tuple, set[str]] = {}
        self._rejected_snapshots: set[tuple] = set()
        self._rejected_formats: set[int] = set()
        self._rejected_peers: set[str] = set()

    def add(self, peer_id: str, snapshot: Snapshot) -> bool:
        """Returns True if this snapshot is new to the pool."""
        k = snapshot.key()
        if k in self._rejected_snapshots or \
                snapshot.format in self._rejected_formats or \
                peer_id in self._rejected_peers:
            return False
        new = k not in self._snapshots
        self._snapshots[k] = snapshot
        self._peers.setdefault(k, set()).add(peer_id)
        return new

    def best(self) -> Snapshot | None:
        ranked = sorted(
            self._snapshots.values(),
            key=lambda s: (-s.height, s.format,
                           -len(self._peers.get(s.key(), ()))))
        return ranked[0] if ranked else None

    def peers_of(self, snapshot: Snapshot) -> list[str]:
        return sorted(self._peers.get(snapshot.key(), set()))

    def reject(self, snapshot: Snapshot) -> None:
        self._rejected_snapshots.add(snapshot.key())
        self._snapshots.pop(snapshot.key(), None)

    def reject_format(self, format_: int) -> None:
        self._rejected_formats.add(format_)
        for k in [k for k, s in self._snapshots.items()
                  if s.format == format_]:
            del self._snapshots[k]

    def reject_peer(self, peer_id: str) -> None:
        self._rejected_peers.add(peer_id)
        self.remove_peer(peer_id)

    def remove_peer(self, peer_id: str) -> None:
        for k, peers in list(self._peers.items()):
            peers.discard(peer_id)
            if not peers:
                del self._peers[k]
                self._snapshots.pop(k, None)

    def remove_peer_snapshot(self, peer_id: str, snapshot: Snapshot) -> None:
        """Dissociate ONE peer from ONE snapshot (it answered 'missing'
        for a chunk); other peers holding the snapshot keep serving it."""
        k = snapshot.key()
        peers = self._peers.get(k)
        if peers is None:
            return
        peers.discard(peer_id)
        if not peers:
            del self._peers[k]
            self._snapshots.pop(k, None)

    def __len__(self) -> int:
        return len(self._snapshots)
