"""Snapshot pool: collect snapshot advertisements from peers and rank
them (reference: statesync/snapshots.go:45 snapshotPool).

Ranking (reference :176 Best): higher height first, then lower format
... then most peers. Rejected snapshots/formats/peers are remembered
so SyncAny never retries them.

The pool is BOUNDED: a per-peer advertisement cap (an advertisement
flood from one peer is refused and surfaced via `on_peer_overflow` so
the reactor can strike its trust score) and a global cap under which
the DETERMINISTICALLY lowest-ranked snapshot is evicted first — an
advertisement flood costs the flooder its trust, never this node's
memory."""

from __future__ import annotations

from dataclasses import dataclass

# Bounds (no reference equivalent — snapshots.go grows without bound):
# a peer legitimately advertises at most recentSnapshots (10) entries
# per request, and the pool only needs enough depth to survive a few
# stale/rejected heads.
MAX_SNAPSHOTS_PER_PEER = 16
MAX_SNAPSHOTS = 64


@dataclass(frozen=True)
class Snapshot:
    height: int
    format: int
    chunks: int
    hash: bytes
    metadata: bytes = b""

    def key(self) -> tuple:
        return (self.height, self.format, self.chunks, self.hash)


class SnapshotPool:
    def __init__(self, per_peer_cap: int = MAX_SNAPSHOTS_PER_PEER,
                 global_cap: int = MAX_SNAPSHOTS,
                 on_peer_overflow=None):
        self.per_peer_cap = per_peer_cap
        self.global_cap = global_cap
        # sync callable (peer_id): the peer exceeded its advertisement
        # cap — the reactor routes this to a behaviour strike
        self.on_peer_overflow = on_peer_overflow
        self._snapshots: dict[tuple, Snapshot] = {}
        self._peers: dict[tuple, set[str]] = {}
        self._rejected_snapshots: set[tuple] = set()
        self._rejected_formats: set[int] = set()
        self._rejected_peers: set[str] = set()

    def _rank_key(self, s: Snapshot) -> tuple:
        # smaller sorts better; snapshot key is the deterministic
        # tiebreaker so eviction order never depends on dict order
        return (-s.height, s.format,
                -len(self._peers.get(s.key(), ())), s.key())

    def add(self, peer_id: str, snapshot: Snapshot) -> bool:
        """Returns True if this snapshot is new to the pool."""
        k = snapshot.key()
        if k in self._rejected_snapshots or \
                snapshot.format in self._rejected_formats or \
                peer_id in self._rejected_peers:
            return False
        if peer_id not in self._peers.get(k, ()):
            held = sum(1 for peers in self._peers.values()
                       if peer_id in peers)
            if held >= self.per_peer_cap:
                if self.on_peer_overflow is not None:
                    self.on_peer_overflow(peer_id)
                return False
        new = k not in self._snapshots
        if new and len(self._snapshots) >= self.global_cap:
            # evict the deterministically lowest-ranked entry; if the
            # newcomer would itself rank last, refuse it instead
            worst_k = max(self._snapshots,
                          key=lambda kk: self._rank_key(self._snapshots[kk]))
            new_rank = (-snapshot.height, snapshot.format, -1, k)
            if new_rank >= self._rank_key(self._snapshots[worst_k]):
                return False
            del self._snapshots[worst_k]
            self._peers.pop(worst_k, None)
        self._snapshots[k] = snapshot
        self._peers.setdefault(k, set()).add(peer_id)
        return new

    def best(self) -> Snapshot | None:
        ranked = sorted(self._snapshots.values(), key=self._rank_key)
        return ranked[0] if ranked else None

    def peers_of(self, snapshot: Snapshot) -> list[str]:
        return sorted(self._peers.get(snapshot.key(), set()))

    def reject(self, snapshot: Snapshot) -> None:
        self._rejected_snapshots.add(snapshot.key())
        self._snapshots.pop(snapshot.key(), None)

    def reject_format(self, format_: int) -> None:
        self._rejected_formats.add(format_)
        for k in [k for k, s in self._snapshots.items()
                  if s.format == format_]:
            del self._snapshots[k]

    def reject_peer(self, peer_id: str) -> None:
        self._rejected_peers.add(peer_id)
        self.remove_peer(peer_id)

    def is_rejected_peer(self, peer_id: str) -> bool:
        return peer_id in self._rejected_peers

    def rejected_peers(self) -> list[str]:
        return sorted(self._rejected_peers)

    def remove_peer(self, peer_id: str) -> None:
        for k, peers in list(self._peers.items()):
            peers.discard(peer_id)
            if not peers:
                del self._peers[k]
                self._snapshots.pop(k, None)

    def remove_peer_snapshot(self, peer_id: str, snapshot: Snapshot) -> None:
        """Dissociate ONE peer from ONE snapshot (it answered 'missing'
        for a chunk); other peers holding the snapshot keep serving it."""
        k = snapshot.key()
        peers = self._peers.get(k)
        if peers is None:
            return
        peers.discard(peer_id)
        if not peers:
            del self._peers[k]
            self._snapshots.pop(k, None)

    def __len__(self) -> int:
        return len(self._snapshots)
