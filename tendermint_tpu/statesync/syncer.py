"""The state-sync driver (reference: statesync/syncer.go).

Pure-ish core: peer IO goes through two callables the reactor wires in
(`request_snapshots(peer)` and `request_chunk(peer_id, snapshot, idx)`)
so the whole flow is unit-testable without sockets. Chunks are held in
memory (a redesign of the reference's temp-file chunkQueue — snapshot
chunks are bounded at 16MB and restore is transient).

Byzantine peers are ATTRIBUTABLE: every chunk records the peer that
supplied it (the provenance feeds `sender=` on ApplySnapshotChunk, so
the app's `reject_senders` channel is live), and a restored-app-hash
mismatch does not reject the snapshot the honest peers are also
serving. Instead the restore RETRIES: the first attempt fetches
round-robin for throughput; after a poisoned attempt, each retry
fetches the full chunk set from ONE peer (rotating deterministically
over the non-quarantined holders), so a failing attempt convicts its
single source by name and a succeeding attempt convicts the original
poisoners by byte-diffing their recorded chunks against the verified
set. Convicted peers are quarantined (pool-banned + behaviour strike);
the snapshot itself is rejected only once RESTORE_ATTEMPTS are
exhausted or no untried peer mix remains — a poisoner costs bandwidth,
never liveness."""

from __future__ import annotations

import asyncio
import logging

from ..abci import types as abci
from ..libs import failpoints
from ..libs.net import jittered_backoff
from ..light.errors import LightClientError
from .snapshots import Snapshot, SnapshotPool

logger = logging.getLogger("statesync")

CHUNK_TIMEOUT = 10.0       # reference chunkTimeout (10s)
DISCOVERY_TIME = 2.0       # reference defaultDiscoveryTime scaled for tests
CHUNK_FETCHERS = 4         # reference cfg.ChunkFetchers
# Per-chunk retry policy: requeued/re-requested chunks back off
# (capped, jittered) instead of re-dialing the instant a peer says
# "missing" — the old immediate retry was a hot request loop against
# peers that just pruned the snapshot. A chunk that exhausts its
# attempts fails the SNAPSHOT (sync_any moves on to a fresher one)
# instead of spinning forever.
CHUNK_RETRIES = 8
CHUNK_BACKOFF_BASE = 0.2
CHUNK_BACKOFF_MAX = 5.0
# Restore attempts per snapshot: the round-robin first try plus up to
# three single-source retries. With one poisoner among >= 2 honest
# holders the second attempt already has a 1/2 chance of an honest
# source and the third is certain (the failing source is quarantined
# between attempts).
RESTORE_ATTEMPTS = 4


def _chunk_backoff(attempt: int) -> float:
    """Capped exponential backoff with jitter for chunk re-requests."""
    return jittered_backoff(max(attempt - 1, 0), CHUNK_BACKOFF_BASE,
                            CHUNK_BACKOFF_MAX)


class StateSyncError(Exception):
    pass


class _AbortSync(StateSyncError):
    pass


class _RejectSnapshot(StateSyncError):
    pass


class _RejectFormat(StateSyncError):
    pass


class _PoisonedRestore(StateSyncError):
    """A restore attempt produced state the trusted app hash refutes
    (or the app itself refused the assembled payload): retryable with
    a different peer mix, never a verdict on the snapshot."""


# Process-global registry for the /status statesync check
# (libs/debugsrv.py consults it via sys.modules.get, so nodes that
# never state-sync pay nothing).
_ACTIVE_SYNCER: "Syncer | None" = None


def active_syncer() -> "Syncer | None":
    return _ACTIVE_SYNCER


class Syncer:
    def __init__(self, app_snapshot_conn, state_provider,
                 request_chunk, discovery_time: float = DISCOVERY_TIME,
                 request_snapshots=None, on_strike=None):
        self.app = app_snapshot_conn
        self.state_provider = state_provider
        self.request_chunk = request_chunk  # async (peer_id, snapshot, idx)
        # sync callable: re-broadcast SnapshotsRequest (re-discovery
        # after a snapshot goes stale under us)
        self.request_snapshots = request_snapshots
        # sync callable (peer_id, reason): route a provable fault to
        # the behaviour reporter (trust strike); wired by the reactor
        self.on_strike = on_strike
        self.discovery_time = discovery_time
        self.pool = SnapshotPool(on_peer_overflow=self._on_pool_overflow)
        self._chunks: dict[int, bytes] = {}
        self._chunk_senders: dict[int, str] = {}
        self._chunk_event = asyncio.Event()
        self._active: Snapshot | None = None
        self._requeue: set[int] = set()  # chunks whose peer said "missing"
        self._quarantined: set[str] = set()
        self._restore_attempt = 0
        self._applied_count = 0

    # -- inbound from reactor --

    def add_snapshot(self, peer_id: str, snapshot: Snapshot) -> bool:
        new = self.pool.add(peer_id, snapshot)
        if new:
            logger.info("discovered snapshot h=%d format=%d from %s",
                        snapshot.height, snapshot.format, peer_id[:8])
        return new

    def add_chunk(self, msg, peer_id: str = "") -> None:
        if self._active is None or msg.height != self._active.height or \
                msg.format != self._active.format:
            return
        if peer_id and peer_id in self._quarantined:
            return  # a quarantined peer's late chunks are dead on arrival
        if msg.missing:
            # THIS peer advertised the snapshot but no longer has it
            # (pruned while we were verifying/offering — common when
            # the chain outpaces the fetch). Drop only the peer's
            # association; other peers keep serving the snapshot, and
            # the fetch loop re-requests the chunk from them at once.
            # When no peers remain, _fetch_and_apply fails the snapshot
            # and sync_any moves on to a fresher one.
            if peer_id:
                self.pool.remove_peer_snapshot(peer_id, self._active)
            self._requeue.add(msg.index)
            self._chunk_event.set()
            return
        if msg.index in self._chunks:
            return
        if not 0 <= msg.index < self._active.chunks:
            return
        # chaos: `corrupt` delivers garbled chunk bytes — restore must
        # end in a poisoned-attempt retry, never in silently applied
        # garbage
        self._chunks[msg.index] = failpoints.hit("statesync.chunk",
                                                 payload=msg.chunk)
        self._chunk_senders[msg.index] = peer_id
        self._chunk_event.set()

    def remove_peer(self, peer_id: str) -> None:
        self.pool.remove_peer(peer_id)

    # -- quarantine --

    def _on_pool_overflow(self, peer_id: str) -> None:
        self._strike(peer_id, "snapshot advertisement flood")

    def _strike(self, peer_id: str, reason: str) -> None:
        if self.on_strike is None or not peer_id:
            return
        try:
            self.on_strike(peer_id, reason)
        except Exception:  # a broken reporter must not fail the sync
            logger.exception("statesync behaviour strike failed")

    def _quarantine(self, peer_id: str, reason: str) -> None:
        """Ban a provably-lying snapshot peer: evict it from the pool
        (its advertisements and chunks are dead from here) and strike
        its trust score. Quarantine is BY NAME and permanent for this
        syncer's life — visible in /status and the quarantine metric."""
        if not peer_id or peer_id in self._quarantined:
            return
        self._quarantined.add(peer_id)
        self.pool.reject_peer(peer_id)
        from ..libs.metrics import statesync_metrics

        statesync_metrics().peers_quarantined.inc()
        logger.warning("statesync peer %s quarantined: %s",
                       peer_id[:8], reason)
        self._strike(peer_id, f"quarantined: {reason}")

    def quarantined_peers(self) -> list[str]:
        return sorted(self._quarantined)

    def status_check(self) -> dict:
        """The /status `statesync` check body (libs/debugsrv.py):
        restore progress + the quarantine ledger. Quarantined peers
        mark the check degraded — the restore is healthy, but an
        active poisoning attempt is something an operator must see."""
        snap = self._active
        c: dict = {
            "status": "ok",
            "height": snap.height if snap is not None else 0,
            "chunks_applied": self._applied_count,
            "chunks_total": snap.chunks if snap is not None else 0,
            "restore_attempt": self._restore_attempt,
            "quarantined_peers": sorted(self._quarantined),
        }
        if self._quarantined:
            c["status"] = "degraded"
            c["detail"] = (f"{len(self._quarantined)} snapshot peer(s) "
                           "quarantined for serving bad data")
        return c

    # -- main flow --

    async def sync_any(self):
        """Try snapshots best-first until one restores and verifies.
        Returns (state, commit) for node bootstrap
        (reference: syncer.go:141 SyncAny)."""
        global _ACTIVE_SYNCER
        _ACTIVE_SYNCER = self
        deadline = asyncio.get_running_loop().time() + self.discovery_time
        while True:
            snapshot = self.pool.best()
            if snapshot is None:
                if asyncio.get_running_loop().time() > deadline:
                    raise StateSyncError("no viable snapshots discovered")
                await asyncio.sleep(0.1)
                continue
            try:
                return await self._sync(snapshot)
            except _AbortSync:
                raise StateSyncError("app aborted state sync")
            except _RejectFormat:
                logger.info("app rejected snapshot format %d",
                            snapshot.format)
                self.pool.reject_format(snapshot.format)
            except _RejectSnapshot:
                logger.info("snapshot h=%d rejected", snapshot.height)
                self.pool.reject(snapshot)
            except (StateSyncError, LightClientError) as e:
                # StateSyncError: chunk fetch/restore failed (e.g. the
                # peer pruned the snapshot under us). LightClientError:
                # the state provider could not — or will no longer,
                # once the trusted head moved past a stale snapshot's
                # height — verify its state. Both are snapshot-local.
                logger.warning("snapshot h=%d failed: %s; trying next",
                               snapshot.height, e)
                self.pool.reject(snapshot)
                if self.request_snapshots is not None:
                    # Peers may have taken fresher snapshots since the
                    # initial discovery; ask again so the pool does not
                    # drain to stale entries.
                    self.request_snapshots()
                    deadline = (asyncio.get_running_loop().time()
                                + self.discovery_time)

    async def _sync(self, snapshot: Snapshot):
        # 1) the app hash we must end up with — light-verified FIRST so
        # an unverifiable height fails before any restore work
        app_hash = await self.state_provider.app_hash(snapshot.height)

        # 2/3) offer + restore, retrying with a rotated peer mix after
        # a poisoned attempt (each re-offer resets the app's partial
        # restore state, so no attempt leaks into the next)
        # failed attempts' provenance: [{index: (bytes, sender)}]
        failed: list[dict[int, tuple[bytes, str]]] = []
        tried_sources: set[str] = set()
        source: str | None = None  # None = round-robin first attempt
        for attempt in range(1, RESTORE_ATTEMPTS + 1):
            self._restore_attempt = attempt
            from ..libs.metrics import statesync_metrics

            statesync_metrics().restore_attempts.inc()
            # chaos: a crash here (between discovery and the app
            # accepting the offer) must restart into clean discovery
            failpoints.hit("statesync.offer")
            res = await self.app.offer_snapshot(abci.RequestOfferSnapshot(
                snapshot=abci.Snapshot(
                    height=snapshot.height, format=snapshot.format,
                    chunks=snapshot.chunks, hash=snapshot.hash,
                    metadata=snapshot.metadata),
                app_hash=app_hash))
            self._dispatch_offer_result(res.result)

            self._active = snapshot
            self._chunks = {}
            self._chunk_senders = {}
            self._requeue = set()
            self._applied_count = 0
            try:
                await self._fetch_and_apply(snapshot, source)
                # 4) confirm the restored app
                info = await self.app.info(abci.RequestInfo())
                if info.last_block_height != snapshot.height:
                    raise StateSyncError(
                        f"restored app height {info.last_block_height} "
                        f"!= snapshot height {snapshot.height}")
                if info.last_block_app_hash != app_hash:
                    raise _PoisonedRestore(
                        f"restored app hash "
                        f"{info.last_block_app_hash.hex()} != trusted "
                        f"{app_hash.hex()}")
            except _PoisonedRestore as e:
                failed.append({
                    i: (self._chunks[i], self._chunk_senders.get(i, ""))
                    for i in self._chunks})
                statesync_metrics().chunks_refetched.inc(
                    len(self._chunks), reason="poisoned")
                if source is not None:
                    # single-source attempt: every chunk came from this
                    # one peer and the trusted app hash refutes the
                    # result — conviction by name
                    self._quarantine(source,
                                     "single-source restore attempt "
                                     "refuted by trusted app hash")
                logger.warning(
                    "restore attempt %d/%d for snapshot h=%d poisoned "
                    "(%s); rotating peer mix", attempt, RESTORE_ATTEMPTS,
                    snapshot.height, e)
                if attempt >= RESTORE_ATTEMPTS:
                    raise _RejectSnapshot(
                        f"{RESTORE_ATTEMPTS} restore attempts exhausted")
                candidates = [p for p in self.pool.peers_of(snapshot)
                              if p not in tried_sources]
                if not candidates:
                    raise _RejectSnapshot(
                        "no untried peer mix left for snapshot")
                source = candidates[0]
                tried_sources.add(source)
                continue
            finally:
                self._active = None
            break

        # a succeeding attempt convicts the original poisoners: any
        # sender whose recorded chunk bytes differ from the verified
        # set provably served garbage
        if failed:
            for rec in failed:
                for idx, (bad_bytes, sender) in rec.items():
                    if sender and self._chunks.get(idx) != bad_bytes:
                        self._quarantine(
                            sender,
                            f"chunk {idx} diverges from the verified "
                            "restore")

        state = await self.state_provider.state(snapshot.height)
        commit = await self.state_provider.commit(snapshot.height)
        logger.info("snapshot restored and verified at height %d",
                    snapshot.height)
        return state, commit

    def _dispatch_offer_result(self, result: int) -> None:
        R = abci.OfferSnapshotResult
        if result == R.ACCEPT:
            return
        if result == R.ABORT:
            raise _AbortSync()
        if result == R.REJECT_FORMAT:
            raise _RejectFormat()
        if result in (R.REJECT, R.REJECT_SENDER, R.UNKNOWN):
            raise _RejectSnapshot()
        raise StateSyncError(f"unknown offer result {result}")

    async def _fetch_and_apply(self, snapshot: Snapshot,
                               source: str | None = None) -> None:
        """Fetch + apply the chunk set. `source=None` round-robins over
        every holder (throughput); a named `source` fetches EVERY chunk
        from that one peer (the attribution mode after a poisoned
        attempt — see _sync)."""
        applied = 0
        requested: dict[int, float] = {}
        attempts: dict[int, int] = {}    # fetch attempts per chunk
        not_before: dict[int, float] = {}  # backoff gate per chunk
        loop = asyncio.get_running_loop()
        while applied < snapshot.chunks:
            while self._requeue:
                # the serving peer said "missing": retry WITH backoff
                # (capped, jittered) — the old immediate retry was a
                # hot loop against peers that just pruned the snapshot
                idx = self._requeue.pop()
                requested[idx] = 0.0
                not_before[idx] = loop.time() + _chunk_backoff(
                    attempts.get(idx, 0))
            peers = self.pool.peers_of(snapshot)
            if source is not None:
                peers = [p for p in peers if p == source]
            if not peers:
                raise StateSyncError("no peers hold the snapshot")
            # (re-)request missing chunks, round-robin over peers
            now = loop.time()
            outstanding = 0
            for idx in range(applied, snapshot.chunks):
                if idx in self._chunks:
                    continue
                if outstanding >= CHUNK_FETCHERS:
                    break
                last = requested.get(idx, 0.0)
                due = last == 0.0 or now - last > CHUNK_TIMEOUT
                if due and now >= not_before.get(idx, 0.0):
                    n = attempts.get(idx, 0)
                    if n >= CHUNK_RETRIES:
                        # exhausted: a fetch FAILURE for the whole
                        # snapshot, surfaced to sync_any (which moves
                        # on / re-discovers) — never a silent spin
                        raise StateSyncError(
                            f"chunk {idx} exhausted after {n} fetch "
                            "attempts")
                    attempts[idx] = n + 1
                    if n:
                        from ..libs.metrics import statesync_metrics

                        statesync_metrics().chunk_retries.inc()
                    peer = peers[idx % len(peers)] if last == 0.0 else \
                        peers[(idx + 1) % len(peers)]
                    await self.request_chunk(peer, snapshot, idx)
                    requested[idx] = now
                outstanding += 1
            # apply whatever is ready, in order
            progressed = False
            while applied in self._chunks:
                # chaos: `corrupt` garbles the chunk AT the apply
                # boundary (poisoned-peer shape), `crash` dies
                # mid-restore — the restart must re-enter discovery
                # with no partial state served
                chunk = failpoints.hit("statesync.apply",
                                       payload=self._chunks[applied])
                res = await self.app.apply_snapshot_chunk(
                    abci.RequestApplySnapshotChunk(
                        index=applied, chunk=chunk,
                        sender=self._chunk_senders.get(applied, "")))
                applied = self._dispatch_apply_result(res, applied,
                                                      requested)
                self._applied_count = applied
                progressed = True
            if applied >= snapshot.chunks:
                return
            if not progressed:
                self._chunk_event.clear()
                if applied in self._chunks or self._requeue:
                    continue  # work arrived before the clear: no wait
                # wake early if a backed-off chunk comes due before the
                # fetch timeout — backoff must not turn into a stall
                wait = CHUNK_TIMEOUT
                now = loop.time()
                for idx, nb in not_before.items():
                    if idx not in self._chunks and nb > now:
                        wait = min(wait, max(nb - now, 0.05))
                try:
                    await asyncio.wait_for(self._chunk_event.wait(),
                                           wait)
                except asyncio.TimeoutError:
                    # force re-requests next loop
                    for idx in list(requested):
                        if idx not in self._chunks:
                            requested[idx] = 0.0

    def _drop_chunk(self, idx: int, requested: dict, reason: str) -> None:
        self._chunks.pop(idx, None)
        self._chunk_senders.pop(idx, None)
        requested[idx] = 0.0
        from ..libs.metrics import statesync_metrics

        statesync_metrics().chunks_refetched.inc(reason=reason)

    def _dispatch_apply_result(self, res, applied: int,
                               requested: dict) -> int:
        # the app's sender ban channel (reference syncer.go:352): a
        # named sender is quarantined and every unapplied chunk it
        # supplied is discarded for re-fetch from surviving peers
        for sender in res.reject_senders:
            self._quarantine(sender, "app rejected sender")
            for idx in [i for i, s in self._chunk_senders.items()
                        if s == sender and i > applied]:
                self._drop_chunk(idx, requested, "rejected_sender")
        R = abci.ApplySnapshotChunkResult
        if res.result == R.ACCEPT:
            for idx in res.refetch_chunks:
                self._drop_chunk(idx, requested, "app_refetch")
            return applied + 1
        if res.result == R.RETRY:
            self._drop_chunk(applied, requested, "app_retry")
            return applied
        if res.result == R.ABORT:
            raise _AbortSync()
        if res.result == R.RETRY_SNAPSHOT:
            # the app refused the assembled payload (e.g. its hash
            # check failed): a poisoned attempt, retried with a new
            # peer mix — NOT a verdict on the snapshot
            raise _PoisonedRestore("app requested snapshot retry")
        if res.result == R.REJECT_SNAPSHOT:
            raise _RejectSnapshot()
        raise StateSyncError(f"unknown apply result {res.result}")
