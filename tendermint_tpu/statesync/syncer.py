"""The state-sync driver (reference: statesync/syncer.go).

Pure-ish core: peer IO goes through two callables the reactor wires in
(`request_snapshots(peer)` and `request_chunk(peer_id, snapshot, idx)`)
so the whole flow is unit-testable without sockets. Chunks are held in
memory (a redesign of the reference's temp-file chunkQueue — snapshot
chunks are bounded at 16MB and restore is transient)."""

from __future__ import annotations

import asyncio
import logging

from ..abci import types as abci
from ..libs import failpoints
from ..libs.net import jittered_backoff
from ..light.errors import LightClientError
from .snapshots import Snapshot, SnapshotPool

logger = logging.getLogger("statesync")

CHUNK_TIMEOUT = 10.0       # reference chunkTimeout (10s)
DISCOVERY_TIME = 2.0       # reference defaultDiscoveryTime scaled for tests
CHUNK_FETCHERS = 4         # reference cfg.ChunkFetchers
# Per-chunk retry policy: requeued/re-requested chunks back off
# (capped, jittered) instead of re-dialing the instant a peer says
# "missing" — the old immediate retry was a hot request loop against
# peers that just pruned the snapshot. A chunk that exhausts its
# attempts fails the SNAPSHOT (sync_any moves on to a fresher one)
# instead of spinning forever.
CHUNK_RETRIES = 8
CHUNK_BACKOFF_BASE = 0.2
CHUNK_BACKOFF_MAX = 5.0


def _chunk_backoff(attempt: int) -> float:
    """Capped exponential backoff with jitter for chunk re-requests."""
    return jittered_backoff(max(attempt - 1, 0), CHUNK_BACKOFF_BASE,
                            CHUNK_BACKOFF_MAX)


class StateSyncError(Exception):
    pass


class _AbortSync(StateSyncError):
    pass


class _RejectSnapshot(StateSyncError):
    pass


class _RejectFormat(StateSyncError):
    pass


class Syncer:
    def __init__(self, app_snapshot_conn, state_provider,
                 request_chunk, discovery_time: float = DISCOVERY_TIME,
                 request_snapshots=None):
        self.app = app_snapshot_conn
        self.state_provider = state_provider
        self.request_chunk = request_chunk  # async (peer_id, snapshot, idx)
        # sync callable: re-broadcast SnapshotsRequest (re-discovery
        # after a snapshot goes stale under us)
        self.request_snapshots = request_snapshots
        self.discovery_time = discovery_time
        self.pool = SnapshotPool()
        self._chunks: dict[int, bytes] = {}
        self._chunk_event = asyncio.Event()
        self._active: Snapshot | None = None
        self._requeue: set[int] = set()  # chunks whose peer said "missing"

    # -- inbound from reactor --

    def add_snapshot(self, peer_id: str, snapshot: Snapshot) -> bool:
        new = self.pool.add(peer_id, snapshot)
        if new:
            logger.info("discovered snapshot h=%d format=%d from %s",
                        snapshot.height, snapshot.format, peer_id[:8])
        return new

    def add_chunk(self, msg, peer_id: str = "") -> None:
        if self._active is None or msg.height != self._active.height or \
                msg.format != self._active.format:
            return
        if msg.missing:
            # THIS peer advertised the snapshot but no longer has it
            # (pruned while we were verifying/offering — common when
            # the chain outpaces the fetch). Drop only the peer's
            # association; other peers keep serving the snapshot, and
            # the fetch loop re-requests the chunk from them at once.
            # When no peers remain, _fetch_and_apply fails the snapshot
            # and sync_any moves on to a fresher one.
            if peer_id:
                self.pool.remove_peer_snapshot(peer_id, self._active)
            self._requeue.add(msg.index)
            self._chunk_event.set()
            return
        if msg.index in self._chunks:
            return
        if not 0 <= msg.index < self._active.chunks:
            return
        # chaos: `corrupt` delivers garbled chunk bytes — restore must
        # end in an app-hash mismatch that fails the snapshot, never in
        # silently applied garbage
        self._chunks[msg.index] = failpoints.hit("statesync.chunk",
                                                 payload=msg.chunk)
        self._chunk_event.set()

    def remove_peer(self, peer_id: str) -> None:
        self.pool.remove_peer(peer_id)

    # -- main flow --

    async def sync_any(self):
        """Try snapshots best-first until one restores and verifies.
        Returns (state, commit) for node bootstrap
        (reference: syncer.go:141 SyncAny)."""
        deadline = asyncio.get_running_loop().time() + self.discovery_time
        while True:
            snapshot = self.pool.best()
            if snapshot is None:
                if asyncio.get_running_loop().time() > deadline:
                    raise StateSyncError("no viable snapshots discovered")
                await asyncio.sleep(0.1)
                continue
            try:
                return await self._sync(snapshot)
            except _AbortSync:
                raise StateSyncError("app aborted state sync")
            except _RejectFormat:
                logger.info("app rejected snapshot format %d",
                            snapshot.format)
                self.pool.reject_format(snapshot.format)
            except _RejectSnapshot:
                logger.info("snapshot h=%d rejected", snapshot.height)
                self.pool.reject(snapshot)
            except (StateSyncError, LightClientError) as e:
                # StateSyncError: chunk fetch/restore failed (e.g. the
                # peer pruned the snapshot under us). LightClientError:
                # the state provider could not — or will no longer,
                # once the trusted head moved past a stale snapshot's
                # height — verify its state. Both are snapshot-local.
                logger.warning("snapshot h=%d failed: %s; trying next",
                               snapshot.height, e)
                self.pool.reject(snapshot)
                if self.request_snapshots is not None:
                    # Peers may have taken fresher snapshots since the
                    # initial discovery; ask again so the pool does not
                    # drain to stale entries.
                    self.request_snapshots()
                    deadline = (asyncio.get_running_loop().time()
                                + self.discovery_time)

    async def _sync(self, snapshot: Snapshot):
        # 1) the app hash we must end up with — light-verified FIRST so
        # an unverifiable height fails before any restore work
        app_hash = await self.state_provider.app_hash(snapshot.height)

        # 2) offer to the app
        res = await self.app.offer_snapshot(abci.RequestOfferSnapshot(
            snapshot=abci.Snapshot(
                height=snapshot.height, format=snapshot.format,
                chunks=snapshot.chunks, hash=snapshot.hash,
                metadata=snapshot.metadata),
            app_hash=app_hash))
        self._dispatch_offer_result(res.result)

        # 3) fetch + apply chunks
        self._active = snapshot
        self._chunks = {}
        self._requeue = set()
        try:
            await self._fetch_and_apply(snapshot)
        finally:
            self._active = None

        # 4) confirm the restored app
        info = await self.app.info(abci.RequestInfo())
        if info.last_block_app_hash != app_hash:
            raise StateSyncError(
                f"restored app hash {info.last_block_app_hash.hex()} != "
                f"trusted {app_hash.hex()}")
        if info.last_block_height != snapshot.height:
            raise StateSyncError(
                f"restored app height {info.last_block_height} != "
                f"snapshot height {snapshot.height}")

        state = await self.state_provider.state(snapshot.height)
        commit = await self.state_provider.commit(snapshot.height)
        logger.info("snapshot restored and verified at height %d",
                    snapshot.height)
        return state, commit

    def _dispatch_offer_result(self, result: int) -> None:
        R = abci.OfferSnapshotResult
        if result == R.ACCEPT:
            return
        if result == R.ABORT:
            raise _AbortSync()
        if result == R.REJECT_FORMAT:
            raise _RejectFormat()
        if result in (R.REJECT, R.REJECT_SENDER, R.UNKNOWN):
            raise _RejectSnapshot()
        raise StateSyncError(f"unknown offer result {result}")

    async def _fetch_and_apply(self, snapshot: Snapshot) -> None:
        applied = 0
        requested: dict[int, float] = {}
        attempts: dict[int, int] = {}    # fetch attempts per chunk
        not_before: dict[int, float] = {}  # backoff gate per chunk
        loop = asyncio.get_running_loop()
        while applied < snapshot.chunks:
            while self._requeue:
                # the serving peer said "missing": retry WITH backoff
                # (capped, jittered) — the old immediate retry was a
                # hot loop against peers that just pruned the snapshot
                idx = self._requeue.pop()
                requested[idx] = 0.0
                not_before[idx] = loop.time() + _chunk_backoff(
                    attempts.get(idx, 0))
            peers = self.pool.peers_of(snapshot)
            if not peers:
                raise StateSyncError("no peers hold the snapshot")
            # (re-)request missing chunks, round-robin over peers
            now = loop.time()
            outstanding = 0
            for idx in range(applied, snapshot.chunks):
                if idx in self._chunks:
                    continue
                if outstanding >= CHUNK_FETCHERS:
                    break
                last = requested.get(idx, 0.0)
                due = last == 0.0 or now - last > CHUNK_TIMEOUT
                if due and now >= not_before.get(idx, 0.0):
                    n = attempts.get(idx, 0)
                    if n >= CHUNK_RETRIES:
                        # exhausted: a fetch FAILURE for the whole
                        # snapshot, surfaced to sync_any (which moves
                        # on / re-discovers) — never a silent spin
                        raise StateSyncError(
                            f"chunk {idx} exhausted after {n} fetch "
                            "attempts")
                    attempts[idx] = n + 1
                    if n:
                        from ..libs.metrics import statesync_metrics

                        statesync_metrics().chunk_retries.inc()
                    peer = peers[idx % len(peers)] if last == 0.0 else \
                        peers[(idx + 1) % len(peers)]
                    await self.request_chunk(peer, snapshot, idx)
                    requested[idx] = now
                outstanding += 1
            # apply whatever is ready, in order
            progressed = False
            while applied in self._chunks:
                chunk = self._chunks[applied]
                res = await self.app.apply_snapshot_chunk(
                    abci.RequestApplySnapshotChunk(
                        index=applied, chunk=chunk, sender=""))
                applied = self._dispatch_apply_result(res, applied,
                                                      requested)
                progressed = True
            if applied >= snapshot.chunks:
                return
            if not progressed:
                self._chunk_event.clear()
                if applied in self._chunks or self._requeue:
                    continue  # work arrived before the clear: no wait
                # wake early if a backed-off chunk comes due before the
                # fetch timeout — backoff must not turn into a stall
                wait = CHUNK_TIMEOUT
                now = loop.time()
                for idx, nb in not_before.items():
                    if idx not in self._chunks and nb > now:
                        wait = min(wait, max(nb - now, 0.05))
                try:
                    await asyncio.wait_for(self._chunk_event.wait(),
                                           wait)
                except asyncio.TimeoutError:
                    # force re-requests next loop
                    for idx in list(requested):
                        if idx not in self._chunks:
                            requested[idx] = 0.0

    def _dispatch_apply_result(self, res, applied: int,
                               requested: dict) -> int:
        R = abci.ApplySnapshotChunkResult
        if res.result == R.ACCEPT:
            for idx in res.refetch_chunks:
                self._chunks.pop(idx, None)
                requested[idx] = 0.0
            return applied + 1
        if res.result == R.RETRY:
            self._chunks.pop(applied, None)
            requested[applied] = 0.0
            return applied
        if res.result == R.ABORT:
            raise _AbortSync()
        if res.result == R.RETRY_SNAPSHOT:
            raise StateSyncError("app requested snapshot retry")
        if res.result == R.REJECT_SNAPSHOT:
            raise _RejectSnapshot()
        raise StateSyncError(f"unknown apply result {res.result}")
