"""State-sync reactor, channels 0x60 (snapshots) / 0x61 (chunks)
(reference: statesync/reactor.go:56).

Server side (always on): answers SnapshotsRequest from the app's
ListSnapshots and ChunkRequest from LoadSnapshotChunk. Client side
(when the node boots with state_sync enabled): feeds discovered
snapshots/chunks into the Syncer and runs sync()."""

from __future__ import annotations

import asyncio
import logging

from ..abci import types as abci
from ..libs import failpoints
from ..p2p.conn.connection import ChannelDescriptor
from ..p2p.switch import Reactor
from .messages import (
    MAX_MSG_SIZE,
    ChunkRequestMessage,
    ChunkResponseMessage,
    SnapshotsRequestMessage,
    SnapshotsResponseMessage,
    decode_ss_msg,
    encode_ss_msg,
)
from .snapshots import Snapshot
from .syncer import Syncer

logger = logging.getLogger("statesync.reactor")

SNAPSHOT_CHANNEL = 0x60
CHUNK_CHANNEL = 0x61
_MAX_ADVERTISED = 10  # reference recentSnapshots


class StateSyncReactor(Reactor):
    def __init__(self, app_snapshot_conn, state_provider=None,
                 discovery_time: float = 2.0):
        super().__init__("statesync")
        self.app = app_snapshot_conn
        self.syncer: Syncer | None = None
        if state_provider is not None:
            self.syncer = Syncer(app_snapshot_conn, state_provider,
                                 self._request_chunk, discovery_time,
                                 request_snapshots=self._request_snapshots,
                                 on_strike=self._strike_peer)

    def _strike_peer(self, peer_id: str, reason: str) -> None:
        """Route a syncer-detected fault (quarantined poisoner,
        advertisement flood) into the behaviour trust score. Soft
        strike: the quarantine already bans the peer from the pool;
        the trust metric accumulates toward a switch-level stop."""
        sw = self.switch
        reporter = getattr(sw, "reporter", None) if sw is not None \
            else None
        if reporter is None:
            return
        try:
            reporter.observe(peer_id, bad=1)
            logger.warning("statesync strike on %s: %s",
                           peer_id[:8], reason)
        except Exception:  # conduct accounting must not fail the sync
            logger.exception("statesync behaviour strike failed")

    def get_channels(self) -> list[ChannelDescriptor]:
        return [
            ChannelDescriptor(id=SNAPSHOT_CHANNEL, priority=5,
                              send_queue_capacity=10,
                              recv_message_capacity=MAX_MSG_SIZE,
                              name="snapshot"),
            ChannelDescriptor(id=CHUNK_CHANNEL, priority=3,
                              send_queue_capacity=16,
                              recv_message_capacity=MAX_MSG_SIZE,
                              name="chunk"),
        ]

    # -- client side --

    async def sync(self):
        """Discover + restore; returns (state, commit)
        (reference reactor.go:480 Sync via syncer.SyncAny)."""
        from ..libs.metrics import consensus_metrics, statesync_metrics

        assert self.syncer is not None, "no state provider wired"
        sw = self.switch
        if sw is not None:
            sw.broadcast(SNAPSHOT_CHANNEL,
                         encode_ss_msg(SnapshotsRequestMessage()))
        consensus_metrics().state_syncing.set(1)
        statesync_metrics().syncing.set(1)
        try:
            return await self.syncer.sync_any()
        finally:
            consensus_metrics().state_syncing.set(0)
            statesync_metrics().syncing.set(0)

    def _request_snapshots(self) -> None:
        sw = self.switch
        if sw is not None:
            sw.broadcast(SNAPSHOT_CHANNEL,
                         encode_ss_msg(SnapshotsRequestMessage()))

    async def _request_chunk(self, peer_id: str, snapshot, index: int
                             ) -> None:
        sw = self.switch
        peer = sw.peers.get(peer_id) if sw is not None else None
        if peer is None:
            if self.syncer is not None:
                self.syncer.remove_peer(peer_id)
            return
        await peer.send(CHUNK_CHANNEL, encode_ss_msg(ChunkRequestMessage(
            height=snapshot.height, format=snapshot.format, index=index)))

    # -- p2p --

    async def add_peer(self, peer) -> None:
        if self.syncer is not None:
            peer.try_send(SNAPSHOT_CHANNEL,
                          encode_ss_msg(SnapshotsRequestMessage()))

    async def remove_peer(self, peer, reason) -> None:
        if self.syncer is not None:
            self.syncer.remove_peer(peer.id)

    async def receive(self, chan_id: int, peer, msgb: bytes) -> None:
        msg = decode_ss_msg(msgb)
        if chan_id == SNAPSHOT_CHANNEL:
            if isinstance(msg, SnapshotsRequestMessage):
                for s in await self._recent_snapshots():
                    await peer.send(SNAPSHOT_CHANNEL, encode_ss_msg(
                        SnapshotsResponseMessage(
                            height=s.height, format=s.format,
                            chunks=s.chunks, hash=s.hash,
                            metadata=s.metadata)))
            elif isinstance(msg, SnapshotsResponseMessage):
                from ..libs.metrics import statesync_metrics

                statesync_metrics().snapshots_discovered.inc()
                if self.syncer is not None:
                    self.syncer.add_snapshot(peer.id, Snapshot(
                        height=msg.height, format=msg.format,
                        chunks=msg.chunks, hash=msg.hash,
                        metadata=msg.metadata))
            else:
                raise ValueError("bad msg on snapshot channel")
        elif chan_id == CHUNK_CHANNEL:
            if isinstance(msg, ChunkRequestMessage):
                res = await self.app.load_snapshot_chunk(
                    abci.RequestLoadSnapshotChunk(
                        height=msg.height, format=msg.format,
                        chunk=msg.index))
                from ..libs.metrics import statesync_metrics

                statesync_metrics().chunks_served.inc()
                # chaos: `corrupt` here turns THIS node into a chunk
                # poisoner (the e2e statesync_poison attack shape) —
                # syncing peers must quarantine it by name and finish
                # the restore from honest holders
                chunk = res.chunk
                if chunk:
                    chunk = failpoints.hit("statesync.serve",
                                           payload=chunk)
                await peer.send(CHUNK_CHANNEL, encode_ss_msg(
                    ChunkResponseMessage(
                        height=msg.height, format=msg.format,
                        index=msg.index, chunk=chunk,
                        missing=not res.chunk)))
            elif isinstance(msg, ChunkResponseMessage):
                from ..libs.metrics import statesync_metrics

                statesync_metrics().chunks_received.inc()
                if self.syncer is not None:
                    self.syncer.add_chunk(msg, peer.id)
            else:
                raise ValueError("bad msg on chunk channel")

    async def _recent_snapshots(self) -> list[Snapshot]:
        res = await self.app.list_snapshots()
        out = []
        for s in sorted(res.snapshots, key=lambda s: (-s.height, s.format)):
            out.append(Snapshot(height=s.height, format=s.format,
                                chunks=s.chunks, hash=s.hash,
                                metadata=s.metadata))
            if len(out) >= _MAX_ADVERTISED:
                break
        return out
