"""Structured sign-bytes: template + per-lane timestamp patch.

Within one commit — and across the votes of one (type, height, round,
block_id) — every canonical sign-byte blob shares all content except
the timestamp field and the outer length prefix (types/canonical.py
vote_sign_bytes; reference types/canonical.go). Shipping full
(N, ~190 B) sign-byte rows to the device per verify is therefore
~90% redundant — the dominant host->device transfer term — and
building them costs one Python protobuf Writer per lane.

The structured batches here capture the structure instead:

  sign_bytes[lane] = outer_varint ‖ pre[group] ‖ ts_field ‖ suf[group]

with a handful of (pre, suf) template groups and a <=20-byte per-lane
patch = outer_varint ‖ ts_field built by vectorized numpy (no per-lane
Python). The device kernel (crypto/tpu/expanded.py structured
front-end) reassembles the exact bytes on device; `materialize()`
yields the identical full bytes for host/fallback paths, and tests
enforce byte equality between the two.

Shapes:
  CommitSignBatch — one commit's slots (groups: for-block vs nil).
  MergedSignBatch — a fast-sync window: several commits, one group
                    per commit (blockchain/reactor.py).
  VoteSignBatch   — a live gossip vote micro-batch: one group per
                    distinct (type, height, round, block_id)
                    (consensus/state.py vote scheduler).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from . import canonical

PATCH_W = 24  # outer varint (<=2) + ts field (<=18), zero-padded

# Template groups the device kernel accepts per launch
# (crypto/tpu/expanded.py pads to exactly this many rows). Builders
# raise ValueError past it so call sites fall back to full bytes
# SILENTLY — overflow is an input property (e.g. a peer fabricating
# many distinct block_ids in one gossip burst), not a template bug.
MAX_GROUPS = 32


def _vlen(v: np.ndarray) -> np.ndarray:
    """Minimal varint byte length per element (v > 0)."""
    bits = np.zeros(v.shape, np.int64)
    x = v.astype(np.int64)
    for shift in (32, 16, 8, 4, 2, 1):
        hi = x >= (1 << shift)
        bits += np.where(hi, shift, 0)
        x = np.where(hi, x >> shift, x)
    return (bits // 7 + 1).astype(np.int64)


def _varint_digits(out: np.ndarray, col: int, v: np.ndarray, ln: int):
    """Write the ln-byte minimal varint of each v into out[:, col:]."""
    for j in range(ln):
        b = (v >> (7 * j)) & 0x7F
        if j < ln - 1:
            b = b | 0x80
        out[:, col + j] = b
    return col + ln


def _pack_templates(parts: list[tuple[bytes, bytes]]):
    """(pre, suf) template list -> padded arrays + lengths."""
    k = max(len(parts), 1)
    if not parts:
        parts = [(b"", b"")]
    pw = max(max(len(p) for p, _ in parts), 1)
    sw = max(max(len(s) for _, s in parts), 1)
    pre = np.zeros((k, pw), np.uint8)
    suf = np.zeros((k, sw), np.uint8)
    pre_len = np.zeros(k, np.int32)
    suf_len = np.zeros(k, np.int32)
    for g, (p, s) in enumerate(parts):
        pre[g, :len(p)] = np.frombuffer(p, np.uint8)
        suf[g, :len(s)] = np.frombuffer(s, np.uint8)
        pre_len[g] = len(p)
        suf_len[g] = len(s)
    return pre, pre_len, suf, suf_len


def _build_patches(pre_len, suf_len, group, ts):
    """Vectorized outer-varint + ts-field assembly, grouped by byte
    layout (within one batch there are only a handful: seconds share
    a varint width, nanos vary 1-5 bytes).

    Returns (patch, split, patch_len); raises ValueError when a blob
    would exceed the two-byte outer-varint range."""
    n = ts.shape[0]
    secs = ts // 1_000_000_000
    nanos = ts % 1_000_000_000
    ls = np.where(secs > 0, _vlen(np.maximum(secs, 1)), 0)
    ln = np.where(nanos > 0, _vlen(np.maximum(nanos, 1)), 0)
    pay = np.where(secs > 0, 1 + ls, 0) + np.where(nanos > 0, 1 + ln, 0)
    tsf_total = np.where(ts > 0, 2 + pay, 0)
    body = (pre_len[group].astype(np.int64) + tsf_total
            + suf_len[group])
    if body.size and body.max() >= 1 << 14:
        raise ValueError("sign bytes too long for structured batch")
    outer_len = np.where(body >= 128, 2, 1)

    patch = np.zeros((n, PATCH_W), np.uint8)
    split = outer_len.astype(np.int32)
    patch_len = (outer_len + tsf_total).astype(np.int32)
    # layout key: everything that fixes byte positions/constants
    key = (group.astype(np.int64) * 4 + (secs > 0) * 2
           + (nanos > 0)) * 1024 + ls * 64 + ln * 8 + outer_len
    for kv in np.unique(key):
        m = key == kv
        ol = int(outer_len[m][0])
        bd = int(body[m][0])
        if ol == 1:
            patch[m, 0] = bd
        else:
            patch[m, 0] = (bd & 0x7F) | 0x80
            patch[m, 1] = bd >> 7
        if int(tsf_total[m][0]) == 0:
            continue
        sub = np.zeros((int(m.sum()), PATCH_W - ol), np.uint8)
        sub[:, 0] = 0x2A  # field 5, wire type 2
        sub[:, 1] = pay[m]
        col = 2
        if int((secs > 0)[m][0]):
            sub[:, col] = 0x08
            col = _varint_digits(sub, col + 1, secs[m], int(ls[m][0]))
        if int((nanos > 0)[m][0]):
            sub[:, col] = 0x10
            col = _varint_digits(sub, col + 1, nanos[m], int(ln[m][0]))
        patch[m, ol:] = sub
    return patch, split, patch_len


def _check_ts(ts: int) -> int:
    if not 0 <= ts < 1 << 63:
        # Vectorized path is int64; a (hostile) timestamp past year
        # 2262 falls back to the full-bytes path instead.
        raise ValueError("timestamp out of int64 range")
    return ts


class StructuredSignBytes:
    """Base for structured sign-byte batches: the field layout the
    device kernel front-end consumes (pre/suf templates + per-lane
    group/patch/split/patch_len) plus the host-side reassembly the
    self-check and width selection need. ValidatorSet's batch verify
    dispatches on this type."""

    def _finish(self, parts, group, ts):
        self.pre, self.pre_len, self.suf, self.suf_len = \
            _pack_templates(parts)
        self.group = group
        self.patch, self.split, self.patch_len = _build_patches(
            self.pre_len, self.suf_len, group, ts)

    def host_assemble(self, i: int) -> bytes:
        """Reassemble lane i's sign bytes host-side with the SAME
        boundary math the device kernel uses — the runtime self-check
        anchor (compared against anchor_bytes()/materialize())."""
        g = int(self.group[i])
        a = int(self.split[i])
        pl = int(self.patch_len[i])
        return (bytes(self.patch[i, :a])
                + bytes(self.pre[g, :self.pre_len[g]])
                + bytes(self.patch[i, a:pl])
                + bytes(self.suf[g, :self.suf_len[g]]))

    def anchor_bytes(self) -> bytes:
        """Lane 0's canonical sign bytes, computed INDEPENDENTLY of
        the structured arrays — the runtime self-check compares
        host_assemble(0) against this before any launch."""
        raise NotImplementedError

    def msg_lens(self) -> np.ndarray:
        """Per-lane total sign-byte length (outer prefix included)."""
        return (self.patch_len + self.pre_len[self.group]
                + self.suf_len[self.group]).astype(np.int64)

    def max_msg_len(self) -> int:
        return int(self.msg_lens().max()) if len(self) else 0


@dataclass
class CommitSignBatch(StructuredSignBytes):
    """Sign bytes for a list of commit slots, in structured form."""

    chain_id: str
    commit: object
    slots: list[int]
    # templates, one row per group
    pre: np.ndarray = field(init=False)       # (K, PW) uint8
    pre_len: np.ndarray = field(init=False)   # (K,) int32
    suf: np.ndarray = field(init=False)       # (K, SW) uint8
    suf_len: np.ndarray = field(init=False)   # (K,) int32
    # per-lane
    group: np.ndarray = field(init=False)     # (N,) int32
    patch: np.ndarray = field(init=False)     # (N, PATCH_W) uint8
    split: np.ndarray = field(init=False)     # (N,) int32 outer-varint len
    patch_len: np.ndarray = field(init=False)  # (N,) int32

    def __post_init__(self):
        from .vote import VoteType

        commit, chain_id = self.commit, self.chain_id
        n = len(self.slots)
        parts: list[tuple[bytes, bytes]] = []   # group id -> (pre, suf)
        group_of: dict[bool, int] = {}          # keyed by for_block()
        group = np.zeros(n, np.int32)
        ts = np.zeros(n, np.int64)
        for i, slot in enumerate(self.slots):
            cs = commit.signatures[slot]
            ts[i] = _check_ts(cs.timestamp)
            fb = cs.for_block()
            g = group_of.get(fb)
            if g is None:
                g = len(parts)
                group_of[fb] = g
                parts.append(canonical.vote_sign_parts(
                    chain_id, int(VoteType.PRECOMMIT), commit.height,
                    commit.round, cs.block_id_for(commit.block_id)))
            group[i] = g
        self._finish(parts, group, ts)

    def __len__(self) -> int:
        return len(self.slots)

    def anchor_bytes(self) -> bytes:
        return self.commit.vote_sign_bytes(self.chain_id, self.slots[0])

    def materialize(self) -> list[bytes]:
        """Full canonical sign bytes per lane (host/fallback path)."""
        return [self.commit.vote_sign_bytes(self.chain_id, s)
                for s in self.slots]


class MergedSignBatch(StructuredSignBytes):
    """Several commits' CommitSignBatches as ONE structured batch —
    the fast-sync window shape (blockchain/reactor.py): a window of
    consecutive blocks, all signed by the same validator set, verifies
    in a single device launch with one template group per commit.
    Field layout is identical to CommitSignBatch (the kernel front-end
    treats both the same); group ids are offset per sub-batch."""

    def __init__(self, batches: list[CommitSignBatch]):
        assert batches
        if sum(b.pre.shape[0] for b in batches) > MAX_GROUPS:
            raise ValueError("too many commit groups for one "
                             "structured launch")
        self.batches = batches
        pw = max(b.pre.shape[1] for b in batches)
        sw = max(b.suf.shape[1] for b in batches)
        pres, sufs, groups = [], [], []
        off = 0
        for b in batches:
            k = b.pre.shape[0]
            pres.append(np.pad(b.pre, ((0, 0), (0, pw - b.pre.shape[1]))))
            sufs.append(np.pad(b.suf, ((0, 0), (0, sw - b.suf.shape[1]))))
            groups.append(b.group + off)
            off += k
        self.pre = np.concatenate(pres, axis=0)
        self.suf = np.concatenate(sufs, axis=0)
        self.pre_len = np.concatenate([b.pre_len for b in batches])
        self.suf_len = np.concatenate([b.suf_len for b in batches])
        self.group = np.concatenate(groups)
        self.patch = np.concatenate([b.patch for b in batches], axis=0)
        self.split = np.concatenate([b.split for b in batches])
        self.patch_len = np.concatenate([b.patch_len for b in batches])

    def __len__(self) -> int:
        return int(self.group.shape[0])

    def anchor_bytes(self) -> bytes:
        return self.batches[0].anchor_bytes()

    def materialize(self) -> list[bytes]:
        out: list[bytes] = []
        for b in self.batches:
            out.extend(b.materialize())
        return out


class VoteSignBatch(StructuredSignBytes):
    """A live gossip vote micro-batch (consensus/state.py scheduler)
    in structured form: one template group per distinct
    (type, height, round, block_id) — during one round's burst that is
    1-2 groups for thousands of votes, so the launch ships per-lane
    timestamp patches instead of full sign-byte rows, exactly like the
    commit path."""

    def __init__(self, chain_id: str, votes: list):
        self.chain_id = chain_id
        self.votes = votes
        n = len(votes)
        parts: list[tuple[bytes, bytes]] = []
        group_of: dict = {}
        group = np.zeros(n, np.int32)
        ts = np.zeros(n, np.int64)
        for i, v in enumerate(votes):
            ts[i] = _check_ts(v.timestamp)
            key = (int(v.type), v.height, v.round, v.block_id)
            g = group_of.get(key)
            if g is None:
                if len(parts) >= MAX_GROUPS:
                    raise ValueError("too many vote groups for one "
                                     "structured launch")
                g = len(parts)
                group_of[key] = g
                parts.append(canonical.vote_sign_parts(
                    chain_id, int(v.type), v.height, v.round,
                    v.block_id))
            group[i] = g
        self._finish(parts, group, ts)

    def __len__(self) -> int:
        return len(self.votes)

    def anchor_bytes(self) -> bytes:
        return self.votes[0].sign_bytes(self.chain_id)

    def materialize(self) -> list[bytes]:
        return [v.sign_bytes(self.chain_id) for v in self.votes]
