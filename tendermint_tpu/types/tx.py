"""Transactions (reference: types/tx.go)."""

from __future__ import annotations

from ..crypto import merkle, tmhash

Tx = bytes


def tx_hash(tx: Tx) -> bytes:
    return tmhash.sum256(tx)


def txs_hash(txs: list[Tx]) -> bytes:
    """Merkle root over raw txs (reference: types/tx.go Txs.Hash)."""
    return merkle.hash_from_byte_slices(list(txs))


def tx_proof(txs: list[Tx], i: int):
    root, proofs = merkle.proofs_from_byte_slices(list(txs))
    return root, proofs[i]
