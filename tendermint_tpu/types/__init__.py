"""Core consensus types (reference capability: types/ — ~12.7k LoC).

Block/Header/Commit, Vote/VoteSet, ValidatorSet, Evidence, genesis,
events, canonical sign-bytes. All signature verification funnels
through crypto.batch.BatchVerifier (the capability the reference
lacks — its call sites are one-at-a-time synchronous verifies at
types/vote_set.go:203 and types/validator_set.go:683-705).
"""

from .block import (
    Block,
    BlockID,
    BlockIDFlag,
    Commit,
    CommitSig,
    Data,
    Header,
    PartSetHeader,
)
from .evidence import DuplicateVoteEvidence, Evidence, EvidenceData
from .genesis import GenesisDoc
from .params import ConsensusParams
from .priv_validator import MockPV, PrivValidator
from .proposal import Proposal
from .tx import Tx, tx_hash, txs_hash
from .validator import Validator
from .validator_set import ValidatorSet
from .vote import Vote, VoteType
from .vote_set import VoteSet

__all__ = [
    "Block", "BlockID", "BlockIDFlag", "Commit", "CommitSig", "Data",
    "Header", "PartSetHeader", "DuplicateVoteEvidence", "Evidence",
    "EvidenceData", "GenesisDoc", "ConsensusParams", "MockPV",
    "PrivValidator", "Proposal", "Tx", "tx_hash", "txs_hash",
    "Validator", "ValidatorSet", "Vote", "VoteType", "VoteSet",
]
