"""VoteSet: vote accumulation toward +2/3 (reference: types/vote_set.go).

Semantics preserved from the reference: one vote slot per validator
index; duplicate identical votes are no-ops; conflicting votes (same
validator, different block) raise ConflictingVoteError carrying both
votes (the raw material for DuplicateVoteEvidence) — and are tracked if
a peer has claimed a +2/3 majority for that block.

The signature check supports two modes: the synchronous host path
(verify=True, matching vote_set.go:203) and a pre-verified path used by
the consensus micro-batching scheduler
(consensus/state.py:ConsensusState._vote_scheduler), which verifies
many votes in one TPU batch FIRST and then commits them here with
verify=False. Every non-signature check (duplicate, conflict, index,
address) re-runs at commit time in both modes.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..libs.bits import BitArray
from .block import BlockID
from .vote import MAX_VOTES_COUNT, Vote, VoteType


class VoteSetError(Exception):
    pass


@dataclass
class ConflictingVoteError(Exception):
    existing: Vote
    new: Vote

    def __str__(self) -> str:
        return (
            f"conflicting votes from validator "
            f"{self.new.validator_address.hex()}"
        )


@dataclass
class _BlockVotes:
    peer_maj23: bool
    bit_array: BitArray
    votes: list[Vote | None]
    sum: int

    @classmethod
    def new(cls, peer_maj23: bool, num_validators: int) -> "_BlockVotes":
        return cls(peer_maj23, BitArray(num_validators), [None] * num_validators, 0)

    def add_verified_vote(self, vote: Vote, power: int) -> None:
        i = vote.validator_index
        if self.votes[i] is None:
            self.bit_array.set(i, True)
            self.votes[i] = vote
            self.sum += power


def _block_key(block_id: BlockID | None) -> bytes:
    return b"" if block_id is None else block_id.key()


class VoteSet:
    def __init__(self, chain_id: str, height: int, round_: int,
                 type_: VoteType, val_set):
        if height == 0:
            raise ValueError("height must be positive")
        if len(val_set) > MAX_VOTES_COUNT:
            raise ValueError(
                f"validator set exceeds MAX_VOTES_COUNT ({MAX_VOTES_COUNT})"
            )
        self.chain_id = chain_id
        self.height = height
        self.round = round_
        self.type = type_
        self.val_set = val_set
        self.votes_bit_array = BitArray(len(val_set))
        self.votes: list[Vote | None] = [None] * len(val_set)
        self.sum = 0
        self.maj23: BlockID | None = None
        self.maj23_set = False  # distinguishes 'majority for nil' from 'none'
        self.votes_by_block: dict[bytes, _BlockVotes] = {}
        self.peer_maj23s: dict[str, BlockID] = {}

    def size(self) -> int:
        return len(self.val_set)

    def is_duplicate(self, vote: Vote) -> bool:
        """True if an identical vote (index, block, signature) is
        already tallied — used by the consensus micro-batch scheduler to
        skip re-verifying gossip duplicates before they reach a device
        lane (the in-set dup check in add_vote still runs at commit)."""
        i = vote.validator_index
        if not 0 <= i < len(self.votes):
            return False
        ex = self.votes[i]
        if ex is None:
            bv = self.votes_by_block.get(_block_key(vote.block_id))
            ex = bv.votes[i] if bv is not None else None
        return (
            ex is not None
            and _block_key(ex.block_id) == _block_key(vote.block_id)
            and ex.signature == vote.signature
        )

    def add_vote(self, vote: Vote | None, verify: bool = True) -> bool:
        """Returns True if the vote was added, False if it was a
        duplicate. Raises VoteSetError on invalid votes and
        ConflictingVoteError on equivocation."""
        if vote is None:
            raise VoteSetError("nil vote")
        val_index = vote.validator_index
        if val_index < 0:
            raise VoteSetError("negative validator index")
        if not vote.signature:
            raise VoteSetError("vote missing signature")
        if (vote.height != self.height or vote.round != self.round
                or vote.type != self.type):
            raise VoteSetError(
                f"expected {self.height}/{self.round}/{self.type}, got "
                f"{vote.height}/{vote.round}/{vote.type}"
            )
        if vote.block_id is not None:
            try:
                vote.block_id.validate_basic()
            except ValueError as e:
                raise VoteSetError(f"bad block_id in vote: {e}") from None
        val = self.val_set.get_by_index(val_index)
        if val is None:
            raise VoteSetError(f"no validator at index {val_index}")
        if vote.validator_address != val.address:
            raise VoteSetError("vote validator address mismatch")

        # Duplicate check before the expensive verify.
        existing = self.votes[val_index]
        if existing is not None:
            if _block_key(existing.block_id) == _block_key(vote.block_id):
                if existing.signature == vote.signature:
                    return False
                raise VoteSetError("same block, different signature")

        if verify and not vote.verify(self.chain_id, val.pub_key):
            raise VoteSetError(f"invalid signature from {val.address.hex()}")

        return self._add_verified(vote, val.voting_power)

    def _add_verified(self, vote: Vote, power: int) -> bool:
        val_index = vote.validator_index
        block_key = _block_key(vote.block_id)
        existing = self.votes[val_index]
        conflicting: Vote | None = None

        bv = self.votes_by_block.get(block_key)
        if existing is not None and _block_key(existing.block_id) != block_key:
            conflicting = existing
            # Only accept the new vote into a block's tally if a peer
            # claims +2/3 for that block (reference vote_set.go:231).
            if bv is None or not bv.peer_maj23:
                raise ConflictingVoteError(existing, vote)
        elif existing is None:
            self.votes[val_index] = vote
            self.votes_bit_array.set(val_index, True)
            self.sum += power

        if bv is None:
            bv = _BlockVotes.new(False, self.size())
            self.votes_by_block[block_key] = bv

        old_sum = bv.sum
        quorum = 2 * self.val_set.total_voting_power() // 3 + 1
        bv.add_verified_vote(vote, power)

        if old_sum < quorum <= bv.sum and not self.maj23_set:
            self.maj23_set = True
            self.maj23 = vote.block_id
            # Promote this block's votes into the main tracking.
            for i, v in enumerate(bv.votes):
                if v is not None:
                    self.votes[i] = v

        if conflicting is not None:
            raise ConflictingVoteError(conflicting, vote)
        return True

    def set_peer_maj23(self, peer_id: str, block_id: BlockID) -> None:
        """A peer claims +2/3 for block_id (reference vote_set.go:290)."""
        try:
            block_id.validate_basic()  # untrusted input: bound the hash
        except ValueError as e:
            raise VoteSetError(f"invalid peer maj23 block id: {e}") from e
        block_key = _block_key(block_id)
        existing = self.peer_maj23s.get(peer_id)
        if existing is not None:
            if existing.key() == block_key:
                return
            raise VoteSetError("peer changed its +2/3 claim")
        self.peer_maj23s[peer_id] = block_id
        bv = self.votes_by_block.get(block_key)
        if bv is not None:
            bv.peer_maj23 = True
        else:
            self.votes_by_block[block_key] = _BlockVotes.new(True, self.size())

    # -- queries --

    def get_vote(self, val_index: int, block_key: bytes) -> Vote | None:
        v = self.votes[val_index] if 0 <= val_index < len(self.votes) else None
        if v is not None and _block_key(v.block_id) == block_key:
            return v
        bv = self.votes_by_block.get(block_key)
        if bv is not None and 0 <= val_index < len(bv.votes):
            return bv.votes[val_index]
        return None

    def get_by_index(self, i: int) -> Vote | None:
        return self.votes[i] if 0 <= i < len(self.votes) else None

    def two_thirds_majority(self) -> tuple[BlockID | None, bool]:
        """(block_id, ok): ok=True with block_id=None means +2/3 for nil."""
        return self.maj23, self.maj23_set

    def has_two_thirds_majority(self) -> bool:
        return self.maj23_set

    def has_two_thirds_any(self) -> bool:
        return 3 * self.sum > 2 * self.val_set.total_voting_power()

    def has_all(self) -> bool:
        return self.sum == self.val_set.total_voting_power()

    def bit_array(self) -> BitArray:
        return self.votes_bit_array.copy()

    def bit_array_by_block_id(self, block_id: BlockID | None) -> BitArray | None:
        bv = self.votes_by_block.get(_block_key(block_id))
        return bv.bit_array.copy() if bv else None

    def make_commit(self):
        """Build a Commit from the +2/3 majority (reference
        vote_set.go:633). Requires a non-nil maj23."""
        from .block import BlockIDFlag, Commit, CommitSig

        if self.type != VoteType.PRECOMMIT:
            raise VoteSetError("cannot make commit from non-precommit set")
        if not self.maj23_set or self.maj23 is None or self.maj23.is_nil():
            raise VoteSetError("no +2/3 block majority")
        sigs = []
        for i, v in enumerate(self.votes):
            if v is None or v.is_nil():
                if v is None:
                    sigs.append(CommitSig.absent())
                else:
                    sigs.append(CommitSig(
                        BlockIDFlag.NIL, v.validator_address, v.timestamp,
                        v.signature,
                    ))
                continue
            if _block_key(v.block_id) != self.maj23.key():
                sigs.append(CommitSig.absent())
                continue
            sigs.append(CommitSig(
                BlockIDFlag.COMMIT, v.validator_address, v.timestamp,
                v.signature,
            ))
        return Commit(self.height, self.round, self.maj23, sigs)
