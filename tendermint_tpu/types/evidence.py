"""Evidence of Byzantine behavior (reference: types/evidence.go).

DuplicateVoteEvidence: two conflicting votes from one validator at the
same H/R/type. LightClientAttackEvidence: a conflicting light block
(handled in light/statesync flows). Verification lives in
evidence/verify.py and uses the BatchVerifier.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto import merkle, tmhash
from ..encoding.proto import Reader, Writer
from .vote import Vote


class Evidence:
    """Structural base: subclasses implement abci/hash/validate/wire."""

    def hash(self) -> bytes:
        raise NotImplementedError

    def height(self) -> int:
        raise NotImplementedError

    def validate_basic(self) -> None:
        raise NotImplementedError

    def to_bytes(self) -> bytes:
        raise NotImplementedError


@dataclass
class DuplicateVoteEvidence(Evidence):
    vote_a: Vote
    vote_b: Vote
    total_voting_power: int = 0
    validator_power: int = 0
    timestamp: int = 0

    @classmethod
    def from_votes(cls, vote1: Vote, vote2: Vote, block_time: int,
                   val_set) -> "DuplicateVoteEvidence":
        """Order votes lexicographically by BlockID key (deterministic),
        record powers (reference: types/evidence.go:36)."""
        if vote1 is None or vote2 is None or val_set is None:
            raise ValueError("missing vote or valset")
        from .vote_set import _block_key

        if _block_key(vote1.block_id) < _block_key(vote2.block_id):
            a, b = vote1, vote2
        else:
            a, b = vote2, vote1
        _, val = val_set.get_by_address(vote1.validator_address)
        if val is None:
            raise ValueError("validator not in set")
        return cls(
            vote_a=a,
            vote_b=b,
            total_voting_power=val_set.total_voting_power(),
            validator_power=val.voting_power,
            timestamp=block_time,
        )

    def height(self) -> int:
        return self.vote_a.height

    def hash(self) -> bytes:
        return tmhash.sum256(self.to_bytes())

    def to_abci(self) -> list:
        """BeginBlock byzantine_validators entries
        (reference: types/evidence.go ABCI())."""
        from ..abci.types import Misbehavior

        return [Misbehavior(
            type="DUPLICATE_VOTE",
            validator_address=self.vote_a.validator_address,
            validator_power=self.validator_power,
            height=self.vote_a.height,
            time=self.timestamp,
            total_voting_power=self.total_voting_power,
        )]

    def validate_basic(self) -> None:
        if self.vote_a is None or self.vote_b is None:
            raise ValueError("missing votes")
        self.vote_a.validate_basic()
        self.vote_b.validate_basic()
        from .vote_set import _block_key

        if _block_key(self.vote_a.block_id) >= _block_key(self.vote_b.block_id):
            raise ValueError("duplicate votes in wrong order or identical")

    def to_proto(self) -> Writer:
        w = Writer()
        w.message(1, self.vote_a.to_proto())
        w.message(2, self.vote_b.to_proto())
        w.varint(3, self.total_voting_power)
        w.varint(4, self.validator_power)
        w.varint(5, self.timestamp)
        return w

    def to_bytes(self) -> bytes:
        return Writer().message(1, self.to_proto()).finish()

    @classmethod
    def _from_inner(cls, data: bytes) -> "DuplicateVoteEvidence":
        r = Reader(data)
        va = vb = None
        tvp = vp = ts = 0
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                va = Vote.from_bytes(r.bytes())
            elif f == 2:
                vb = Vote.from_bytes(r.bytes())
            elif f == 3:
                tvp = r.varint()
            elif f == 4:
                vp = r.varint()
            elif f == 5:
                ts = r.varint()
            else:
                r.skip(wt)
        if va is None or vb is None:
            raise ValueError("duplicate-vote evidence missing votes")
        return cls(va, vb, tvp, vp, ts)


def evidence_from_bytes(data: bytes) -> Evidence:
    try:
        r = Reader(data)
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                return DuplicateVoteEvidence._from_inner(r.bytes())
            if f == 2:
                from ..light.types import LightClientAttackEvidence

                return LightClientAttackEvidence._from_inner(r.bytes())
            r.skip(wt)
    except ImportError:
        raise ValueError("unsupported evidence type") from None
    raise ValueError("unknown evidence encoding")


@dataclass
class EvidenceData:
    evidence: list[Evidence] = field(default_factory=list)

    def hash(self) -> bytes:
        return merkle.hash_from_byte_slices([e.hash() for e in self.evidence])

    def to_proto(self) -> Writer | None:
        if not self.evidence:
            return None
        w = Writer()
        for e in self.evidence:
            w.bytes(1, e.to_bytes(), skip_empty=False)
        return w

    @classmethod
    def from_bytes(cls, data: bytes) -> "EvidenceData":
        r = Reader(data)
        out = []
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                out.append(evidence_from_bytes(r.bytes()))
            else:
                r.skip(wt)
        return cls(out)
