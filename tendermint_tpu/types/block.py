"""Blocks, headers, commits, part sets (reference: types/block.go, part_set.go).

Hashing follows the reference's scheme: Header.hash() is the merkle
root of the deterministically-encoded header fields
(types/block.go:408-430); a block's wire form is split into fixed-size
parts whose merkle root (PartSetHeader) is what validators vote on.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto import merkle, tmhash
from ..encoding.proto import Reader, Writer
from . import canonical

BLOCK_PART_SIZE = 65536
MAX_SIGNATURE_SIZE = 96  # fits ed25519 (64) and sr25519 (64); headroom
MAX_HEADER_BYTES = 626


class BlockIDFlag:
    ABSENT = 1
    COMMIT = 2
    NIL = 3


@dataclass(frozen=True)
class PartSetHeader:
    total: int
    hash: bytes

    def is_zero(self) -> bool:
        return self.total == 0 and not self.hash

    def validate_basic(self) -> None:
        if not 0 <= self.total < 1 << 32:
            raise ValueError("part set total out of range")
        if self.hash and len(self.hash) != tmhash.SIZE:
            raise ValueError("bad part set hash size")

    def __repr__(self) -> str:
        return f"PartSetHeader({self.total}, {self.hash.hex()[:12]})"


@dataclass(frozen=True)
class BlockID:
    hash: bytes
    part_set_header: PartSetHeader | None = None

    def is_nil(self) -> bool:
        return not self.hash

    def is_zero(self) -> bool:
        """Reference BlockID.IsZero (types/block.go): empty hash AND
        zero part_set_header. This — not is_nil()'s hash-only check —
        is what gates canonical/proto omission: a BlockID carrying a
        part-set header with an empty hash must still encode, or its
        sign bytes diverge from the reference's."""
        return not self.hash and (
            self.part_set_header is None or self.part_set_header.is_zero()
        )

    def is_complete(self) -> bool:
        return (
            len(self.hash) == tmhash.SIZE
            and self.part_set_header is not None
            and self.part_set_header.total > 0
        )

    def validate_basic(self) -> None:
        if self.hash and len(self.hash) != tmhash.SIZE:
            raise ValueError("bad block hash size")
        if self.part_set_header is not None:
            self.part_set_header.validate_basic()

    def key(self) -> bytes:
        """Unambiguous map key: length-framed so no two distinct BlockIDs
        collide (an unframed concat would let a crafted 68-byte 'hash'
        impersonate hash+part_set_header). 4-byte frame: peer-supplied
        hashes can be oversized and must not crash the keyer."""
        psh = self.part_set_header
        out = len(self.hash).to_bytes(4, "big") + self.hash
        if psh is not None:
            if not 0 <= psh.total < 1 << 32:
                raise ValueError("part set total out of range")
            out += b"\x01" + psh.total.to_bytes(4, "big") + psh.hash
        return out

    def __repr__(self) -> str:
        return f"BlockID({self.hash.hex()[:12]})" if self.hash else "BlockID(nil)"


NIL_BLOCK_ID = BlockID(b"", None)


def block_id_writer(bid: BlockID | None) -> Writer | None:
    """tmproto.BlockID. part_set_header is gogoproto nullable=false in
    the reference (types.proto:98-99), so whenever a BlockID message is
    marshaled at all, field 2 is present — even as an empty submessage.
    Cross-validated against the reference MBT corpus header hashes
    (light/mbt_ref.py).

    Only the repo's None-psh nil sentinel omits here: an EXPLICIT zero
    part_set_header (what decoding reference-marshaled nil-vote bytes
    produces) still emits `field {psh: {}}` byte-identically with the
    gogo marshaler. Full IsZero() omission applies to CANONICAL sign
    bytes only (canonical.canonical_block_id_writer), where the
    reference's CanonicalizeBlockID nils out zero ids — this writer's
    behavior is deliberately UNCHANGED by that fix."""
    if bid is None or (bid.is_nil() and bid.part_set_header is None):
        return None
    w = Writer()
    w.bytes(1, bid.hash)
    pw = Writer()
    psh = bid.part_set_header
    if psh is not None:
        pw.varint(1, psh.total)
        pw.bytes(2, psh.hash)
    w.message(2, pw)
    return w


def zero_block_id_bytes() -> bytes:
    """Marshal of a ZERO tmproto.BlockID — not empty: the non-nullable
    part_set_header still emits (reference gogo semantics; the
    Header.hash leaf for a genesis last_block_id depends on this)."""
    return Writer().message(2, Writer()).finish()


def read_block_id(data: bytes) -> BlockID:
    r = Reader(data)
    h, psh = b"", None
    while not r.at_end():
        f, wt = r.field()
        if f == 1:
            h = r.bytes()
        elif f == 2:
            rr = Reader(r.bytes())
            total, ph = 0, b""
            while not rr.at_end():
                ff, wwt = rr.field()
                if ff == 1:
                    total = rr.varint()
                elif ff == 2:
                    ph = rr.bytes()
                else:
                    rr.skip(wwt)
            psh = PartSetHeader(total, ph)
        else:
            r.skip(wt)
    return BlockID(h, psh)


def read_timestamp(data: bytes) -> int:
    r = Reader(data)
    secs = nanos = 0
    while not r.at_end():
        f, wt = r.field()
        if f == 1:
            secs = r.varint()
        elif f == 2:
            nanos = r.varint()
        else:
            r.skip(wt)
    return secs * 1_000_000_000 + nanos


@dataclass
class CommitSig:
    """One validator's slot in a commit (reference: types/block.go:603)."""

    block_id_flag: int
    validator_address: bytes = b""
    timestamp: int = 0
    signature: bytes = b""

    @classmethod
    def absent(cls) -> "CommitSig":
        return cls(BlockIDFlag.ABSENT)

    def is_absent(self) -> bool:
        return self.block_id_flag == BlockIDFlag.ABSENT

    def for_block(self) -> bool:
        return self.block_id_flag == BlockIDFlag.COMMIT

    def validate_basic(self) -> None:
        if self.block_id_flag not in (
            BlockIDFlag.ABSENT, BlockIDFlag.COMMIT, BlockIDFlag.NIL,
        ):
            raise ValueError("unknown BlockIDFlag")
        if self.is_absent():
            if self.validator_address or self.signature or self.timestamp:
                raise ValueError("absent CommitSig must be empty")
        else:
            if len(self.validator_address) != 20:
                raise ValueError("bad validator address size")
            if not self.signature:
                raise ValueError("missing signature")
            if len(self.signature) > MAX_SIGNATURE_SIZE:
                raise ValueError("signature too big")

    def block_id_for(self, commit_block_id: BlockID) -> BlockID:
        if self.for_block():
            return commit_block_id
        return NIL_BLOCK_ID

    def to_proto(self) -> Writer:
        w = Writer()
        w.varint(1, self.block_id_flag)
        w.bytes(2, self.validator_address)
        w.message(3, canonical.timestamp_writer(self.timestamp))
        w.bytes(4, self.signature)
        return w

    @classmethod
    def from_reader(cls, data: bytes) -> "CommitSig":
        r = Reader(data)
        cs = cls(BlockIDFlag.ABSENT)
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                cs.block_id_flag = r.varint()
            elif f == 2:
                cs.validator_address = r.bytes()
            elif f == 3:
                cs.timestamp = read_timestamp(r.bytes())
            elif f == 4:
                cs.signature = r.bytes()
            else:
                r.skip(wt)
        return cs


@dataclass
class Commit:
    """+2/3 precommits for a block (reference: types/block.go:553)."""

    height: int
    round: int
    block_id: BlockID
    signatures: list[CommitSig]
    _hash: bytes | None = field(default=None, repr=False, compare=False, init=False)

    def validate_basic(self) -> None:
        if self.height < 0:
            raise ValueError("negative height")
        if self.round < 0:
            raise ValueError("negative round")
        if self.height >= 1:
            if self.block_id.is_nil():
                raise ValueError("commit cannot be for nil block")
            if not self.signatures:
                raise ValueError("no signatures in commit")
            from .vote import MAX_VOTES_COUNT

            if len(self.signatures) > MAX_VOTES_COUNT:
                raise ValueError("too many signatures in commit")
            for cs in self.signatures:
                cs.validate_basic()

    def hash(self) -> bytes:
        if self._hash is None:
            self._hash = merkle.hash_from_byte_slices(
                [cs.to_proto().finish() for cs in self.signatures]
            )
        return self._hash

    def vote_sign_bytes(self, chain_id: str, idx: int) -> bytes:
        """Sign bytes for the precommit in slot idx (reference:
        types/block.go Commit.VoteSignBytes)."""
        cs = self.signatures[idx]
        from .vote import VoteType

        return canonical.vote_sign_bytes(
            chain_id,
            int(VoteType.PRECOMMIT),
            self.height,
            self.round,
            cs.block_id_for(self.block_id),
            cs.timestamp,
        )

    def size(self) -> int:
        return len(self.signatures)

    def to_proto(self) -> Writer:
        w = Writer()
        w.varint(1, self.height)
        w.varint(2, self.round)
        w.message(3, block_id_writer(self.block_id))
        for cs in self.signatures:
            w.message(4, cs.to_proto())
        return w

    def to_bytes(self) -> bytes:
        return self.to_proto().finish()

    @classmethod
    def from_bytes(cls, data: bytes) -> "Commit":
        r = Reader(data)
        height = round_ = 0
        bid = NIL_BLOCK_ID
        sigs: list[CommitSig] = []
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                height = r.varint()
            elif f == 2:
                round_ = r.varint()
            elif f == 3:
                bid = read_block_id(r.bytes())
            elif f == 4:
                sigs.append(CommitSig.from_reader(r.bytes()))
            else:
                r.skip(wt)
        return cls(height, round_, bid, sigs)


@dataclass
class Header:
    """Block header (reference: types/block.go:334)."""

    version_block: int
    version_app: int
    chain_id: str
    height: int
    time: int  # ns
    last_block_id: BlockID
    last_commit_hash: bytes
    data_hash: bytes
    validators_hash: bytes
    next_validators_hash: bytes
    consensus_hash: bytes
    app_hash: bytes
    last_results_hash: bytes
    evidence_hash: bytes
    proposer_address: bytes
    _hash: bytes | None = field(default=None, repr=False, compare=False, init=False)

    def hash(self) -> bytes:
        """Merkle root of the deterministically-encoded fields
        (reference: types/block.go:408)."""
        if not self.validators_hash:
            return b""
        if self._hash is None:
            vw = Writer()
            vw.varint(1, self.version_block)
            vw.varint(2, self.version_app)

            def bv(b: bytes) -> bytes:
                # cdcEncode wraps byte fields in a BytesValue message
                # (field 1, length-delimited) before hashing
                return Writer().bytes(1, b).finish()

            lbid = block_id_writer(self.last_block_id)
            fields = [
                vw.finish(),
                Writer().string(1, self.chain_id).finish(),
                Writer().varint(1, self.height).finish(),
                (canonical.timestamp_writer(self.time) or Writer()).finish(),
                lbid.finish() if lbid is not None else zero_block_id_bytes(),
                bv(self.last_commit_hash),
                bv(self.data_hash),
                bv(self.validators_hash),
                bv(self.next_validators_hash),
                bv(self.consensus_hash),
                bv(self.app_hash),
                bv(self.last_results_hash),
                bv(self.evidence_hash),
                bv(self.proposer_address),
            ]
            self._hash = merkle.hash_from_byte_slices(fields)
        return self._hash

    def validate_basic(self) -> None:
        if not self.chain_id or len(self.chain_id) > 50:
            raise ValueError("bad chain id")
        if self.height < 0:
            raise ValueError("negative height")
        if self.last_block_id is not None:  # None = genesis (Go zero value)
            self.last_block_id.validate_basic()
        for name in (
            "last_commit_hash", "data_hash", "validators_hash",
            "next_validators_hash", "consensus_hash", "last_results_hash",
            "evidence_hash",
        ):
            h = getattr(self, name)
            if h and len(h) != tmhash.SIZE:
                raise ValueError(f"bad {name} size")
        if len(self.proposer_address) != 20:
            raise ValueError("bad proposer address size")

    def to_proto(self) -> Writer:
        w = Writer()
        vw = Writer()
        vw.varint(1, self.version_block)
        vw.varint(2, self.version_app)
        w.message(1, vw)
        w.string(2, self.chain_id)
        w.varint(3, self.height)
        w.message(4, canonical.timestamp_writer(self.time))
        w.message(5, block_id_writer(self.last_block_id))
        w.bytes(6, self.last_commit_hash)
        w.bytes(7, self.data_hash)
        w.bytes(8, self.validators_hash)
        w.bytes(9, self.next_validators_hash)
        w.bytes(10, self.consensus_hash)
        w.bytes(11, self.app_hash)
        w.bytes(12, self.last_results_hash)
        w.bytes(13, self.evidence_hash)
        w.bytes(14, self.proposer_address)
        return w

    @classmethod
    def from_bytes(cls, data: bytes) -> "Header":
        r = Reader(data)
        kw = dict(
            version_block=0, version_app=0, chain_id="", height=0, time=0,
            last_block_id=NIL_BLOCK_ID, last_commit_hash=b"", data_hash=b"",
            validators_hash=b"", next_validators_hash=b"", consensus_hash=b"",
            app_hash=b"", last_results_hash=b"", evidence_hash=b"",
            proposer_address=b"",
        )
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                rr = Reader(r.bytes())
                while not rr.at_end():
                    ff, wwt = rr.field()
                    if ff == 1:
                        kw["version_block"] = rr.varint()
                    elif ff == 2:
                        kw["version_app"] = rr.varint()
                    else:
                        rr.skip(wwt)
            elif f == 2:
                kw["chain_id"] = r.string()
            elif f == 3:
                kw["height"] = r.varint()
            elif f == 4:
                kw["time"] = read_timestamp(r.bytes())
            elif f == 5:
                kw["last_block_id"] = read_block_id(r.bytes())
            elif 6 <= f <= 14:
                names = [
                    "last_commit_hash", "data_hash", "validators_hash",
                    "next_validators_hash", "consensus_hash", "app_hash",
                    "last_results_hash", "evidence_hash", "proposer_address",
                ]
                kw[names[f - 6]] = r.bytes()
            else:
                r.skip(wt)
        return cls(**kw)


@dataclass
class Data:
    txs: list[bytes] = field(default_factory=list)

    def hash(self) -> bytes:
        return merkle.hash_from_byte_slices(self.txs)


@dataclass
class Block:
    header: Header
    data: Data
    evidence: "EvidenceData"
    last_commit: Commit | None

    def hash(self) -> bytes:
        return self.header.hash()

    def validate_basic(self) -> None:
        self.header.validate_basic()
        if self.header.height > 1:
            if self.last_commit is None:
                raise ValueError("nil LastCommit")
            self.last_commit.validate_basic()
            if self.header.last_commit_hash != self.last_commit.hash():
                raise ValueError("wrong LastCommitHash")
        if self.header.data_hash != self.data.hash():
            raise ValueError("wrong DataHash")
        if self.header.evidence_hash != self.evidence.hash():
            raise ValueError("wrong EvidenceHash")

    def make_part_set(self, part_size: int = BLOCK_PART_SIZE) -> "PartSet":
        return PartSet.from_data(self.to_bytes(), part_size)

    def block_id(self, part_size: int = BLOCK_PART_SIZE) -> BlockID:
        ps = self.make_part_set(part_size)
        return BlockID(self.hash(), ps.header())

    def to_proto(self) -> Writer:
        w = Writer()
        w.message(1, self.header.to_proto())
        if self.data.txs:
            dw = Writer()
            for tx in self.data.txs:
                dw.bytes(1, tx, skip_empty=False)
            w.message(2, dw)
        ev_w = self.evidence.to_proto()
        if ev_w is not None:
            w.message(3, ev_w)
        if self.last_commit is not None:
            w.message(4, self.last_commit.to_proto())
        return w

    def to_bytes(self) -> bytes:
        return self.to_proto().finish()

    @classmethod
    def from_bytes(cls, data: bytes) -> "Block":
        from .evidence import EvidenceData

        r = Reader(data)
        header = None
        d = Data()
        ev = EvidenceData()
        lc = None
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                header = Header.from_bytes(r.bytes())
            elif f == 2:
                rr = Reader(r.bytes())
                while not rr.at_end():
                    ff, wwt = rr.field()
                    if ff == 1:
                        d.txs.append(rr.bytes())
                    else:
                        rr.skip(wwt)
            elif f == 3:
                ev = EvidenceData.from_bytes(r.bytes())
            elif f == 4:
                lc = Commit.from_bytes(r.bytes())
            else:
                r.skip(wt)
        if header is None:
            raise ValueError("block missing header")
        return cls(header, d, ev, lc)


# --- Part sets (reference: types/part_set.go) --------------------------------


@dataclass
class Part:
    index: int
    bytes_: bytes
    proof: merkle.Proof

    def validate_basic(self) -> None:
        if self.index < 0:
            raise ValueError("negative part index")
        if self.proof.index != self.index:
            raise ValueError("part proof index mismatch")

    def to_proto(self) -> "Writer":
        w = Writer()
        w.varint(1, self.index, skip_zero=False)
        w.bytes(2, self.bytes_, skip_empty=False)
        pw = Writer()
        pw.varint(1, self.proof.total)
        pw.varint(2, self.proof.index, skip_zero=False)
        pw.bytes(3, self.proof.leaf_hash)
        for a in self.proof.aunts:
            pw.bytes(4, a, skip_empty=False)
        w.message(3, pw)
        return w

    def to_bytes(self) -> bytes:
        return self.to_proto().finish()

    @classmethod
    def from_bytes(cls, data: bytes) -> "Part":
        r = Reader(data)
        index, bytes_ = 0, b""
        proof = merkle.Proof(0, 0, b"", [])
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                index = r.varint()
            elif f == 2:
                bytes_ = r.bytes()
            elif f == 3:
                rr = Reader(r.bytes())
                total = pidx = 0
                lh: bytes = b""
                aunts: list[bytes] = []
                while not rr.at_end():
                    ff, wwt = rr.field()
                    if ff == 1:
                        total = rr.varint()
                    elif ff == 2:
                        pidx = rr.varint()
                    elif ff == 3:
                        lh = rr.bytes()
                    elif ff == 4:
                        aunts.append(rr.bytes())
                    else:
                        rr.skip(wwt)
                proof = merkle.Proof(total, pidx, lh, aunts)
            else:
                r.skip(wt)
        return cls(index, bytes_, proof)


class PartSet:
    """A block's wire bytes split into merkle-proven parts."""

    def __init__(self, total: int, hash_: bytes):
        from ..libs.bits import BitArray

        self.total = total
        self.hash = hash_
        self.parts: list[Part | None] = [None] * total
        self.parts_bitarray = BitArray(total)
        self.count = 0
        self.byte_size = 0

    @classmethod
    def from_data(cls, data: bytes, part_size: int = BLOCK_PART_SIZE) -> "PartSet":
        chunks = [data[i : i + part_size] for i in range(0, len(data), part_size)]
        if not chunks:
            chunks = [b""]
        root, proofs = merkle.proofs_from_byte_slices(chunks)
        ps = cls(len(chunks), root)
        for i, (chunk, proof) in enumerate(zip(chunks, proofs)):
            ps.parts[i] = Part(i, chunk, proof)
            ps.parts_bitarray.set(i, True)
        ps.count = len(chunks)
        ps.byte_size = len(data)
        return ps

    def header(self) -> PartSetHeader:
        return PartSetHeader(self.total, self.hash)

    def has_header(self, h: PartSetHeader) -> bool:
        return self.total == h.total and self.hash == h.hash

    def add_part(self, part: Part) -> bool:
        """Returns True if added; raises on invalid proof."""
        if part.index >= self.total:
            raise ValueError("part index out of range")
        if self.parts[part.index] is not None:
            return False
        part.validate_basic()
        if not part.proof.verify(self.hash, part.bytes_):
            raise ValueError("invalid part proof")
        self.parts[part.index] = part
        self.parts_bitarray.set(part.index, True)
        self.count += 1
        self.byte_size += len(part.bytes_)
        return True

    def get_part(self, i: int) -> Part | None:
        return self.parts[i]

    def is_complete(self) -> bool:
        return self.count == self.total

    def assemble(self) -> bytes:
        assert self.is_complete()
        return b"".join(p.bytes_ for p in self.parts)  # type: ignore[union-attr]
