"""Canonical sign-bytes (reference: types/canonical.go).

The bytes a validator signs for votes and proposals. Deterministic
protobuf wire encoding, length-delimited (varint length prefix), field
numbers and types mirroring the reference's canonical.proto:

  CanonicalVote     { type=1 varint; height=2 sfixed64; round=3 sfixed64;
                      block_id=4; timestamp=5; chain_id=6 }
  CanonicalProposal { type=1; height=2 sfixed64; round=3 sfixed64;
                      pol_round=4 varint; block_id=5; timestamp=6;
                      chain_id=7 }
  CanonicalBlockID  { hash=1; part_set_header=2 }
  CanonicalPartSetHeader { total=1 varint; hash=2 }
  Timestamp         { seconds=1 varint; nanos=2 varint }

Zero-valued scalars are skipped (proto3 canonical form); a nil BlockID
encodes as an absent field.
"""

from __future__ import annotations

from ..encoding.proto import Writer, encode_varint


def timestamp_writer(time_ns: int) -> Writer | None:
    if time_ns == 0:
        return None
    w = Writer()
    w.varint(1, time_ns // 1_000_000_000)
    w.varint(2, time_ns % 1_000_000_000)
    return w


def canonical_block_id_writer(block_id) -> Writer | None:
    """block_id: types.block.BlockID or None. CanonicalizeBlockID
    returns nil for a ZERO block id (field omitted — nil votes), where
    zero is the reference's IsZero: empty hash AND zero
    part_set_header — NOT is_nil()'s hash-only check (an empty-hash
    BlockID with a real part-set header still canonicalizes, keeping
    sign bytes byte-identical with the reference). A present
    CanonicalBlockID always carries its part_set_header: the field is
    gogoproto nullable=false (canonical.proto:12), so the reference
    emits it even when empty."""
    if block_id is None or block_id.is_zero():
        return None
    w = Writer()
    w.bytes(1, block_id.hash)
    pw = Writer()
    psh = block_id.part_set_header
    if psh is not None:
        pw.varint(1, psh.total)
        pw.bytes(2, psh.hash)
    w.message(2, pw)
    return w


def vote_sign_bytes(chain_id: str, vote_type: int, height: int, round_: int,
                    block_id, time_ns: int) -> bytes:
    w = Writer()
    w.varint(1, vote_type)
    w.sfixed64(2, height)
    w.sfixed64(3, round_)
    w.message(4, canonical_block_id_writer(block_id))
    w.message(5, timestamp_writer(time_ns))
    w.string(6, chain_id)
    body = w.finish()
    return encode_varint(len(body)) + body


def vote_sign_parts(chain_id: str, vote_type: int, height: int,
                    round_: int, block_id) -> tuple[bytes, bytes]:
    """The timestamp-independent halves of vote sign bytes.

    For ANY time_ns:
        vote_sign_bytes(...) ==
            encode_varint(len(pre) + len(tsf) + len(suf)) + pre + tsf + suf
    with tsf = ts_field_bytes(time_ns). Built with the exact same
    Writer calls as vote_sign_bytes, so the invariant holds by
    construction (tests enforce it across edge cases). Within one
    commit every signature shares (pre, suf) — only the timestamp
    field and the outer length prefix differ per lane — which is what
    lets commit verification ship a template plus per-lane timestamp
    patches to the device instead of full per-lane sign bytes."""
    w = Writer()
    w.varint(1, vote_type)
    w.sfixed64(2, height)
    w.sfixed64(3, round_)
    w.message(4, canonical_block_id_writer(block_id))
    pre = w.finish()
    w = Writer()
    w.string(6, chain_id)
    return pre, w.finish()


def ts_field_bytes(time_ns: int) -> bytes:
    """Wire bytes of canonical-vote field 5 (the Timestamp message);
    empty when time_ns == 0 (absent field, proto3 canonical form)."""
    w = Writer()
    w.message(5, timestamp_writer(time_ns))
    return w.finish()


def strip_canonical_timestamp(sign_bytes: bytes, ts_field: int) -> bytes:
    """Re-emit a length-prefixed canonical blob with the timestamp field
    removed — used to decide whether two sign-byte blobs differ only by
    timestamp (reference: privval checkVotesOnlyDifferByTimestamp,
    file.go:413). Wire-level copy; no semantic re-encoding."""
    from ..encoding.proto import Reader, decode_varint

    body_len, pos = decode_varint(sign_bytes, 0)
    body = sign_bytes[pos:pos + body_len]
    if len(body) != body_len:
        raise ValueError("truncated canonical sign bytes")
    r = Reader(body)
    out = bytearray()
    while not r.at_end():
        start = r._pos
        f, wt = r.field()
        r.skip(wt)
        if f != ts_field:
            out += body[start:r._pos]
    return encode_varint(len(out)) + bytes(out)


def extract_canonical_timestamp(sign_bytes: bytes, ts_field: int) -> int:
    """Timestamp (ns) carried inside a canonical sign-bytes blob; 0 if
    the field is absent."""
    from ..encoding.proto import Reader, decode_varint

    body_len, pos = decode_varint(sign_bytes, 0)
    r = Reader(sign_bytes[pos:pos + body_len])
    while not r.at_end():
        f, wt = r.field()
        if f == ts_field and wt == 2:
            tr = Reader(r.bytes())
            secs = nanos = 0
            while not tr.at_end():
                tf, twt = tr.field()
                if tf == 1:
                    secs = tr.varint()
                elif tf == 2:
                    nanos = tr.varint()
                else:
                    tr.skip(twt)
            return secs * 1_000_000_000 + nanos
        r.skip(wt)
    return 0


def proposal_sign_bytes(chain_id: str, height: int, round_: int,
                        pol_round: int, block_id, time_ns: int) -> bytes:
    w = Writer()
    w.varint(1, 32)  # SignedMsgType PROPOSAL
    w.sfixed64(2, height)
    w.sfixed64(3, round_)
    # pol_round is -1 when absent; encodes as int64 two's complement.
    w.varint(4, pol_round)
    w.message(5, canonical_block_id_writer(block_id))
    w.message(6, timestamp_writer(time_ns))
    w.string(7, chain_id)
    body = w.finish()
    return encode_varint(len(body)) + body
