"""Block proposals (reference: types/proposal.go)."""

from __future__ import annotations

from dataclasses import dataclass

from ..encoding.proto import Reader, Writer
from . import canonical
from .block import BlockID, block_id_writer, read_block_id, read_timestamp


@dataclass
class Proposal:
    height: int
    round: int
    pol_round: int  # -1 if no proof-of-lock
    block_id: BlockID
    timestamp: int = 0
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical.proposal_sign_bytes(
            chain_id, self.height, self.round, self.pol_round,
            self.block_id, self.timestamp,
        )

    def validate_basic(self) -> None:
        from .block import MAX_SIGNATURE_SIZE

        if self.height <= 0:
            raise ValueError("proposal height must be positive")
        if self.round < 0:
            raise ValueError("negative round")
        if self.pol_round < -1 or self.pol_round >= self.round:
            raise ValueError("bad POL round")
        self.block_id.validate_basic()
        if not self.block_id.is_complete():
            raise ValueError("proposal BlockID must be complete")
        if not self.signature:
            raise ValueError("missing signature")
        if len(self.signature) > MAX_SIGNATURE_SIZE:
            raise ValueError("signature too big")

    def to_proto(self) -> Writer:
        w = Writer()
        w.varint(1, 32)  # type PROPOSAL
        w.varint(2, self.height)
        w.varint(3, self.round)
        # pol_round encoded +1 so -1 is the (skipped) zero value
        w.varint(4, self.pol_round + 1)
        w.message(5, block_id_writer(self.block_id))
        w.message(6, canonical.timestamp_writer(self.timestamp))
        w.bytes(7, self.signature)
        return w

    def to_bytes(self) -> bytes:
        return self.to_proto().finish()

    @classmethod
    def from_bytes(cls, data: bytes) -> "Proposal":
        r = Reader(data)
        kw = dict(height=0, round=0, pol_round=-1, block_id=None,
                  timestamp=0, signature=b"")
        while not r.at_end():
            f, wt = r.field()
            if f == 2:
                kw["height"] = r.varint()
            elif f == 3:
                kw["round"] = r.varint()
            elif f == 4:
                kw["pol_round"] = r.varint() - 1
            elif f == 5:
                kw["block_id"] = read_block_id(r.bytes())
            elif f == 6:
                kw["timestamp"] = read_timestamp(r.bytes())
            elif f == 7:
                kw["signature"] = r.bytes()
            else:
                r.skip(wt)
        if kw["block_id"] is None:
            raise ValueError("proposal missing block_id")
        return cls(**kw)
