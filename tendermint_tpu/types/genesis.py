"""Genesis document (reference: types/genesis.go)."""

from __future__ import annotations

import json
import time as _time
from dataclasses import dataclass, field

from .. import crypto
from ..crypto import tmhash
from .params import ConsensusParams
from .validator import Validator

MAX_CHAIN_ID_LEN = 50

# Reference tmjson key-type tags (crypto/encoding + amino-era names)
_REF_KEY_TYPES = {
    "tendermint/PubKeyEd25519": "ed25519",
    "tendermint/PubKeySecp256k1": "secp256k1",
    "tendermint/PubKeySr25519": "sr25519",
}


def _pub_key_from_json(pk: dict) -> "crypto.PubKey":
    """{'type','value'} with either repo conventions (short type name,
    hex value) or reference tmjson (amino-style tag, base64 value)."""
    tname = _REF_KEY_TYPES.get(pk["type"], pk["type"])
    raw = pk["value"]
    try:
        return crypto.pubkey_from_type_and_bytes(tname, bytes.fromhex(raw))
    except ValueError:
        import base64

        return crypto.pubkey_from_type_and_bytes(
            tname, base64.b64decode(raw))


@dataclass
class GenesisValidator:
    pub_key: crypto.PubKey
    power: int
    name: str = ""

    @property
    def address(self) -> bytes:
        return self.pub_key.address()


@dataclass
class GenesisDoc:
    chain_id: str
    genesis_time: int = 0  # ns
    initial_height: int = 1
    consensus_params: ConsensusParams = field(default_factory=ConsensusParams)
    validators: list[GenesisValidator] = field(default_factory=list)
    app_hash: bytes = b""
    app_state: dict | list | str | None = None

    def validate_and_complete(self) -> None:
        if not self.chain_id or len(self.chain_id) > MAX_CHAIN_ID_LEN:
            raise ValueError("bad chain id")
        if self.initial_height < 0:
            raise ValueError("negative initial height")
        if self.initial_height == 0:
            self.initial_height = 1
        self.consensus_params.validate_basic()
        for v in self.validators:
            if v.power < 0:
                raise ValueError("negative validator power")
        if self.genesis_time == 0:
            self.genesis_time = _time.time_ns()

    def validator_set(self):
        from .validator_set import ValidatorSet

        return ValidatorSet(
            [Validator.new(v.pub_key, v.power) for v in self.validators]
        )

    def hash(self) -> bytes:
        return tmhash.sum256(self.to_json().encode())

    def to_json(self) -> str:
        return json.dumps(
            {
                "chain_id": self.chain_id,
                "genesis_time": self.genesis_time,
                "initial_height": self.initial_height,
                "consensus_params": self.consensus_params.to_json(),
                "validators": [
                    {
                        "pub_key": {
                            "type": v.pub_key.type_name,
                            "value": v.pub_key.bytes().hex(),
                        },
                        "power": v.power,
                        "name": v.name,
                    }
                    for v in self.validators
                ],
                "app_hash": self.app_hash.hex(),
                "app_state": self.app_state,
            },
            sort_keys=True,
        )

    @classmethod
    def from_json(cls, s: str) -> "GenesisDoc":
        """Accepts this repo's JSON AND the reference's tmjson format
        (types/genesis.go: RFC3339 genesis_time, string-encoded
        int64s, 'tendermint/PubKeyEd25519'-style key types with base64
        values) — a reference operator's genesis.json loads unchanged."""
        d = json.loads(s)
        gt = d.get("genesis_time", 0)
        if isinstance(gt, str):
            from ..libs.timeenc import rfc3339_to_ns

            gt = rfc3339_to_ns(gt)
        doc = cls(
            chain_id=d["chain_id"],
            genesis_time=gt,
            initial_height=int(d.get("initial_height") or 1),
            consensus_params=ConsensusParams.from_json(
                d.get("consensus_params")
            ),
            validators=[
                GenesisValidator(
                    pub_key=_pub_key_from_json(gv["pub_key"]),
                    power=int(gv["power"]),
                    name=gv.get("name") or "",
                )
                for gv in d.get("validators") or []
            ],
            app_hash=bytes.fromhex(d.get("app_hash", "")),
            app_state=d.get("app_state"),
        )
        doc.validate_and_complete()
        return doc

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    @classmethod
    def load(cls, path: str) -> "GenesisDoc":
        with open(path) as f:
            return cls.from_json(f.read())
