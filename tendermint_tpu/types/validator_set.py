"""ValidatorSet: proposer rotation + batched commit verification
(reference: types/validator_set.go).

The verify_commit* family is the framework's north-star surface: where
the reference loops `PubKey.VerifySignature` per signature
(validator_set.go:683-705,720-762,776-824), every variant here collects
its exact verification set first and executes it as ONE device batch
with per-lane verdicts. Large all-ed25519 sets additionally route
through crypto/tpu/expanded.py: per-validator comb tables cached on
device across heights (the valset persists block to block), which
removes pubkey decompression and all scalar-mul doublings from the
per-commit critical path."""

from __future__ import annotations

from ..crypto import merkle
from ..crypto.batch import BatchVerifier
from .block import BlockID
from .validator import Validator

MAX_TOTAL_VOTING_POWER = (1 << 62) // 8
PRIORITY_WINDOW_SIZE_FACTOR = 2
# Lanes at/above this go through the expanded per-validator comb
# tables (crypto/tpu/expanded.py MIN_EXPAND); below it the general
# batch kernel / host path wins because the table build + HBM
# residency don't amortize.
_EXPAND_MIN = 128


class VerificationError(Exception):
    pass


class CommitVerifyPlan:
    """One commit-check decomposed into its signature lanes BEFORE any
    cryptography runs: the selection loops of verify_commit_light /
    verify_commit_light_trusting (power tally, address matching, the
    insufficient-power rejections) produce a plan, and the signature
    work is a separate step. The split lets the light serving plane
    (light/serving.py) coalesce the lanes of MANY independent plans —
    concurrent client requests, both checks of one skipping step —
    into a single wide device launch, while the classic verify_commit*
    methods just plan + execute inline."""

    __slots__ = ("valset", "lanes", "slots", "sigs", "msgs")

    def __init__(self, valset: "ValidatorSet", lanes: list[int],
                 slots: list[int], sigs: list[bytes], msgs):
        self.valset = valset
        self.lanes = lanes    # indices into valset.validators (tables)
        self.slots = slots    # commit signature slots (error reports)
        self.sigs = sigs
        self.msgs = msgs      # list[bytes] | StructuredSignBytes

    def __len__(self) -> int:
        return len(self.lanes)

    def triples(self) -> list[tuple]:
        """(pub_key, sign_bytes, signature) per lane, msgs
        materialized — the form a cross-plan batch consumes (different
        plans may come from different validator sets, so the shared
        launch uses the general per-lane-key kernel, not this set's
        expanded tables)."""
        from .sign_batch import StructuredSignBytes

        msgs = self.msgs.materialize() \
            if isinstance(self.msgs, StructuredSignBytes) else self.msgs
        return [(self.valset.validators[i].pub_key, m, s)
                for i, m, s in zip(self.lanes, msgs, self.sigs)]

    def raise_invalid(self, verdicts) -> None:
        """Map per-lane verdicts back to commit slots; raise the same
        VerificationError the inline verify_commit* paths produce."""
        bad = [self.slots[i] for i in range(len(self.slots))
               if not verdicts[i]]
        if bad:
            raise VerificationError(
                f"invalid signature(s) at index(es) {bad}")

    def execute(self) -> None:
        """Verify this plan alone (the classic inline path): one
        batch through the owning set's expanded tables / BatchVerifier."""
        ok, verdicts = self.valset._batch_verify_lanes(
            self.lanes, self.msgs, self.sigs)
        if not ok:
            self.raise_invalid(verdicts)


class ValidatorSet:
    def __init__(self, validators: list[Validator]):
        self._total: int | None = None
        self._addr_cache: dict = {}
        self._addr_cache_src: list | None = None
        if validators:
            vals = [v.copy() for v in validators]
            vals.sort(key=lambda v: (-v.voting_power, v.address))
            self.validators = vals
            self.proposer: Validator | None = None
            self._increment_proposer_priority(1)
        else:
            self.validators = []
            self.proposer = None

    # -- queries --

    def __len__(self) -> int:
        return len(self.validators)

    def total_voting_power(self) -> int:
        if self._total is None:
            self._total = sum(v.voting_power for v in self.validators)
            if self._total > MAX_TOTAL_VOTING_POWER:
                raise ValueError("total voting power exceeds cap")
        return self._total

    def _addr_index(self) -> dict:
        """address -> index map, rebuilt when the validators list is
        replaced or grows (callers outside this class assign/append to
        .validators directly, so validity is keyed on the list object
        + its length rather than on construction sites). Turns the
        per-conflicting-vote / per-evidence-item lookups — and
        update_with_change_set's has_address loop — from O(n) scans
        into O(1) at the 10k-validator design point (the reference
        keeps sorted order + binary search, validator_set.go:646)."""
        vals = self.validators
        if self._addr_cache_src is not vals or \
                len(self._addr_cache) != len(vals):
            self._addr_cache = {v.address: i for i, v in enumerate(vals)}
            self._addr_cache_src = vals
        return self._addr_cache

    def get_by_address(self, addr: bytes) -> tuple[int, Validator | None]:
        i = self._addr_index().get(addr, -1)
        return (i, self.validators[i]) if i >= 0 else (-1, None)

    def get_by_index(self, i: int) -> Validator | None:
        if 0 <= i < len(self.validators):
            return self.validators[i]
        return None

    def has_address(self, addr: bytes) -> bool:
        return self.get_by_address(addr)[0] >= 0

    def hash(self) -> bytes:
        return merkle.hash_from_byte_slices(
            [v.bytes_for_hash() for v in self.validators]
        )

    def copy(self) -> "ValidatorSet":
        vs = ValidatorSet([])
        vs.validators = [v.copy() for v in self.validators]
        if self.proposer is not None:
            i, _ = self.get_by_address(self.proposer.address)
            vs.proposer = vs.validators[i] if i >= 0 else self.proposer.copy()
        vs._total = self._total
        return vs

    def validate_basic(self) -> None:
        if not self.validators:
            raise ValueError("empty validator set")
        for v in self.validators:
            v.validate_basic()
        if self.proposer is None:
            raise ValueError("no proposer")

    # -- proposer rotation (reference: validator_set.go:110-230) --

    def increment_proposer_priority(self, times: int) -> None:
        if times <= 0:
            raise ValueError("times must be positive")
        self._increment_proposer_priority(times)

    def _increment_proposer_priority(self, times: int) -> None:
        diff_max = PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power()
        self._rescale_priorities(diff_max)
        self._shift_by_avg_proposer_priority()
        prop = None
        for _ in range(times):
            prop = self._single_increment()
        self.proposer = prop

    def _single_increment(self) -> Validator:
        for v in self.validators:
            v.proposer_priority += v.voting_power
        mostest = self.validators[0]
        for v in self.validators[1:]:
            mostest = mostest.compare_proposer_priority(v)
        mostest.proposer_priority -= self.total_voting_power()
        return mostest

    def _rescale_priorities(self, diff_max: int) -> None:
        if diff_max <= 0 or not self.validators:
            return
        prios = [v.proposer_priority for v in self.validators]
        diff = max(prios) - min(prios)
        if diff > diff_max:
            ratio = (diff + diff_max - 1) // diff_max
            for v in self.validators:
                # truncated (toward-zero) division, matching Go int64 /
                q = abs(v.proposer_priority) // ratio
                v.proposer_priority = q if v.proposer_priority >= 0 else -q

    def _shift_by_avg_proposer_priority(self) -> None:
        if not self.validators:
            return
        total = sum(v.proposer_priority for v in self.validators)
        n = len(self.validators)
        avg = total // n if total >= 0 else -((-total) // n)  # trunc toward 0
        for v in self.validators:
            v.proposer_priority -= avg

    def get_proposer(self) -> Validator:
        assert self.validators
        if self.proposer is None:
            self.proposer = self._find_proposer()
        return self.proposer

    def _find_proposer(self) -> Validator:
        mostest = self.validators[0]
        for v in self.validators[1:]:
            mostest = mostest.compare_proposer_priority(v)
        return mostest

    # -- validator updates (reference: validator_set.go:516-646) --

    def update_with_change_set(self, changes: list[Validator]) -> None:
        """Apply ABCI validator updates: power 0 removes, new adds,
        other powers update. New validators start at priority
        -1.125 * new total power (reference: computeNewPriorities)."""
        if not changes:
            return
        seen = set()
        for c in changes:
            if c.address in seen:
                raise ValueError("duplicate address in change set")
            seen.add(c.address)
            if c.voting_power < 0:
                raise ValueError("negative power update")

        removals = {c.address for c in changes if c.voting_power == 0}
        updates = {c.address: c for c in changes if c.voting_power > 0}

        for addr in removals:
            if not self.has_address(addr):
                raise ValueError("removing unknown validator")
        kept = [v for v in self.validators if v.address not in removals]

        new_total = sum(
            updates.get(v.address, v).voting_power for v in kept
        ) + sum(c.voting_power for c in updates.values() if not self.has_address(c.address))
        if new_total == 0:
            raise ValueError("validator set would be empty")
        if new_total > MAX_TOTAL_VOTING_POWER:
            raise ValueError("total voting power would exceed cap")

        out: list[Validator] = []
        for v in kept:
            if v.address in updates:
                nv = updates.pop(v.address).copy()
                nv.proposer_priority = v.proposer_priority
                out.append(nv)
            else:
                out.append(v)
        for c in updates.values():
            nv = c.copy()
            nv.proposer_priority = -(new_total + (new_total >> 3))
            out.append(nv)

        out.sort(key=lambda v: (-v.voting_power, v.address))
        self.validators = out
        self._total = None
        self._shift_by_avg_proposer_priority()

    # -- commit verification (batched; the hot path) --

    def _use_expanded(self, lanes: list[int]) -> bool:
        """Will _batch_verify_lanes take the expanded device path?"""
        from ..crypto import batch as _batch
        from ..crypto.tpu import verify as tv

        # Above _MAX_BATCH a single launch is off the table (the
        # BatchVerifier fallback self-splits); e.g. a full fast-sync
        # window at 10k validators. The valset-size cap is
        # backend-dependent (expanded.max_keys: HBM budget on chips,
        # one build chunk on the CPU backend where tables buy nothing).
        if not (_EXPAND_MIN <= len(lanes) <= tv._MAX_BATCH
                and not _batch.host_forced()
                and _batch.device_available("ed25519")):
            return False
        try:
            from ..crypto.tpu import expanded

            cap = expanded.max_keys()
        except Exception:
            # max_keys inits the JAX backend; a broken device runtime
            # must degrade to the host path (with the usual breaker
            # cooldown), not crash commit verification.
            _batch.mark_device_failed("ed25519")
            _batch.logger.exception("backend probe failed; host path")
            return False
        return (len(self.validators) <= cap
                and all(self.validators[i].pub_key.type_name == "ed25519"
                        for i in lanes))

    def warm_device_tables(self):
        """Kick a background build of this set's expanded device
        tables (crypto/tpu/expanded.py warm_async) if commit verifies
        for it would use them. Called when a validator-set change is
        adopted so the first commit under the new set doesn't pay the
        table build inline. Returns the thread or None."""
        if not self._use_expanded(list(range(len(self.validators)))):
            return None
        from ..crypto.tpu import expanded

        return expanded.warm_async(
            [v.pub_key.bytes() for v in self.validators])

    def structured_or_bytes(self, lanes: list[int], build, materialize):
        """THE structured-vs-full-bytes policy, one copy for every
        call site (commit verify, fast-sync windows, vote scheduler):
        build() -> a types.sign_batch.StructuredSignBytes when the
        expanded device path will consume it; ValueError from build
        (hostile timestamps, too many template groups, oversized sign
        bytes) means the input doesn't fit the vectorized layout —
        fall back to materialize()'s full bytes SILENTLY, because
        that's an input property, not a bug."""
        if self._use_expanded(lanes):
            try:
                return build()
            except ValueError:
                pass
        return materialize()

    def _commit_msgs(self, chain_id: str, commit, slots: list[int],
                     lanes: list[int]):
        """Sign bytes for the given commit slots: structured when the
        device path will consume it, materialized otherwise."""
        if not slots:
            return []
        from .sign_batch import CommitSignBatch

        return self.structured_or_bytes(
            lanes,
            lambda: CommitSignBatch(chain_id, commit, slots),
            lambda: [commit.vote_sign_bytes(chain_id, s) for s in slots],
        )

    def _batch_verify_lanes(self, lanes: list[int], msgs,
                            sigs: list[bytes]):
        """One device batch over (self.validators[lanes[i]], msgs[i],
        sigs[i]). Large all-ed25519 sets go through the expanded
        per-validator comb tables (cached on device across heights —
        see crypto/tpu/expanded.py); everything else through the
        general BatchVerifier.

        msgs is either a list of sign-byte blobs or a
        types.sign_batch.StructuredSignBytes (single-commit batch or a
        fast-sync window's merged batch): the structured form lets the
        expanded path assemble the bytes ON DEVICE (template +
        per-lane timestamp patch) instead of shipping ~190 B of
        redundant sign bytes per lane; every fallback materializes the
        identical full bytes."""
        from ..crypto import batch as _batch
        from .sign_batch import StructuredSignBytes

        structured = isinstance(msgs, StructuredSignBytes)
        # structured implies _use_expanded held when the batch was
        # built (_commit_msgs) — don't repeat the O(n) key-type scan.
        if structured or self._use_expanded(lanes):
            from ..crypto.tpu import expanded
            from ..libs import failpoints

            try:
                failpoints.hit("device.verify")
                exp = expanded.get_expanded(
                    [v.pub_key.bytes() for v in self.validators])
                if structured:
                    try:
                        verdicts = exp.verify_structured(
                            lanes, msgs, sigs)
                    except ValueError:
                        # structural limit (oversized templates /
                        # sign bytes), NOT a device failure: same
                        # device, full-bytes form. Logged loudly —
                        # if this is the lane-0 reassembly self-check
                        # firing, the structured path has a template
                        # bug that must surface, not hide behind a
                        # working fallback.
                        _batch.logger.exception(
                            "structured commit verify rejected the "
                            "batch (%d lanes); using full-bytes form",
                            len(lanes))
                        verdicts = exp.verify(
                            lanes, msgs.materialize(), sigs)
                else:
                    verdicts = exp.verify(lanes, msgs, sigs)
                return bool(verdicts.all()), verdicts
            except Exception:
                # dead device mid-table-build or mid-launch: degrade
                # to the BatchVerifier (which itself degrades device
                # -> host) instead of failing the commit verify
                _batch.mark_device_failed("ed25519")
                _batch.logger.exception(
                    "expanded-valset verify failed (%d lanes); "
                    "degrading", len(lanes))
        if structured:
            msgs = msgs.materialize()
        bv = BatchVerifier()
        for i, m, s in zip(lanes, msgs, sigs):
            bv.add(self.validators[i].pub_key, m, s)
        return bv.verify()

    def verify_commit(self, chain_id: str, block_id: BlockID, height: int,
                      commit) -> None:
        """Verify ALL non-absent signatures; tally for-block power must
        exceed 2/3 (reference: validator_set.go:662)."""
        self._check_commit_basics(block_id, height, commit)
        lanes: list[int] = []
        sigs: list[bytes] = []
        tallied = 0
        for idx, cs in enumerate(commit.signatures):
            if cs.is_absent():
                continue
            val = self.validators[idx]
            if cs.validator_address and cs.validator_address != val.address:
                raise VerificationError(
                    f"wrong validator address in slot {idx}"
                )
            lanes.append(idx)
            sigs.append(cs.signature)
            if cs.for_block():
                tallied += val.voting_power
        msgs = self._commit_msgs(chain_id, commit, lanes, lanes)
        ok, verdicts = self._batch_verify_lanes(lanes, msgs, sigs)
        if not ok:
            bad = [lanes[i] for i in range(len(lanes)) if not verdicts[i]]
            raise VerificationError(f"invalid signature(s) at index(es) {bad}")
        if 3 * tallied <= 2 * self.total_voting_power():
            raise VerificationError(
                f"insufficient voting power: {tallied} of {self.total_voting_power()}"
            )

    def plan_commit_light(self, chain_id: str, block_id: BlockID,
                          height: int, commit) -> CommitVerifyPlan:
        """Selection half of verify_commit_light: basics + the
        cheapest 2/3 of for-block power, NO signature work. Raises
        VerificationError before planning any cryptography when the
        power cannot reach the threshold."""
        self._check_commit_basics(block_id, height, commit)
        lanes: list[int] = []
        sigs: list[bytes] = []
        tallied = 0
        need = 2 * self.total_voting_power()
        for idx, cs in enumerate(commit.signatures):
            if not cs.for_block():
                continue
            val = self.validators[idx]
            lanes.append(idx)
            sigs.append(cs.signature)
            tallied += val.voting_power
            if 3 * tallied > need:
                break
        if 3 * tallied <= need:
            raise VerificationError(
                f"insufficient voting power: {tallied} of {self.total_voting_power()}"
            )
        msgs = self._commit_msgs(chain_id, commit, lanes, lanes)
        return CommitVerifyPlan(self, lanes, lanes, sigs, msgs)

    def verify_commit_light(self, chain_id: str, block_id: BlockID,
                            height: int, commit) -> None:
        """Verify only the for-block signatures needed to pass 2/3
        (reference: validator_set.go:720) — as one batch."""
        self.plan_commit_light(chain_id, block_id, height,
                               commit).execute()

    def plan_commit_trusting(self, chain_id: str, commit,
                             trust_num: int,
                             trust_den: int) -> CommitVerifyPlan:
        """Selection half of verify_commit_light_trusting: address
        matching + the trust-level power tally, NO signature work.
        Raises VerificationError (insufficient trusted power / double
        vote) before planning any cryptography."""
        if trust_den <= 0 or trust_num <= 0 or trust_num > trust_den:
            raise ValueError("invalid trust level")
        lanes: list[int] = []  # OUR validator indices (for the tables)
        slots: list[int] = []  # commit slots (for sign bytes/errors)
        sigs: list[bytes] = []
        tallied = 0
        need = self.total_voting_power() * trust_num
        seen: set[int] = set()
        for idx, cs in enumerate(commit.signatures):
            if not cs.for_block():
                continue
            vi, val = self.get_by_address(cs.validator_address)
            if vi < 0:
                continue
            if vi in seen:
                raise VerificationError("double vote from same validator")
            seen.add(vi)
            lanes.append(vi)
            slots.append(idx)
            sigs.append(cs.signature)
            tallied += val.voting_power
            if tallied * trust_den > need:
                break
        if tallied * trust_den <= need:
            raise VerificationError(
                f"insufficient trusted power: {tallied}"
            )
        msgs = self._commit_msgs(chain_id, commit, slots, lanes)
        return CommitVerifyPlan(self, lanes, slots, sigs, msgs)

    def verify_commit_light_trusting(self, chain_id: str, commit,
                                     trust_num: int, trust_den: int) -> None:
        """Trust-fraction variant for light-client skipping verification
        (reference: validator_set.go:776). Validators are matched by
        ADDRESS (the commit came from a possibly newer set)."""
        self.plan_commit_trusting(chain_id, commit, trust_num,
                                  trust_den).execute()

    def _check_commit_basics(self, block_id: BlockID, height: int, commit) -> None:
        if commit is None:
            raise VerificationError("nil commit")
        if len(self.validators) != len(commit.signatures):
            raise VerificationError(
                f"commit has {len(commit.signatures)} sigs, valset has "
                f"{len(self.validators)}"
            )
        if height != commit.height:
            raise VerificationError(f"commit height {commit.height} != {height}")
        if commit.block_id != block_id:
            raise VerificationError("commit is for a different block")

    def __repr__(self) -> str:
        return f"ValidatorSet(n={len(self.validators)}, power={self.total_voting_power()})"
