"""Signed-transaction envelope codec for the mempool admission plane.

The paper's thesis is that signature verification belongs on the
device in large batches; the consensus commit path already does that,
but CheckTx still round-trips the app per tx. This envelope is the
wire contract that lets the mempool pre-verify tx signatures in
batched device launches BEFORE any ABCI round trip
(mempool/admission.py): a tx that starts with the 4-byte MAGIC is

    MAGIC || proto{1: pub_key (32B ed25519),
                   2: signature (64B over sign_bytes(payload)),
                   3: payload}

and anything else is an UNSIGNED tx, passed through untouched (the
app still sees exactly the bytes the client sent — enveloped txs
reach CheckTx/DeliverTx as the FULL envelope, so the envelope bytes
are the tx identity everywhere: hashes, dedup cache, gossip, blocks).

Bytes that start with MAGIC but do not decode to the three fields are
MALFORMED, not unsigned — otherwise garbage prefixed with the magic
would bypass `mempool.admission = "strict"`.

The signature domain is separated from every consensus signing
context by the SIGN_DOMAIN prefix, so a tx-envelope signature can
never be replayed as (or collide with) a vote/proposal signature and
vice versa.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..encoding.proto import Reader, Writer

# Chosen to be invalid UTF-8 and an impossible protobuf tag start, so
# no plausible text or proto-encoded app payload begins with it by
# accident. An app whose raw (unsigned) txs can legitimately start
# with these bytes must wrap them in envelopes.
MAGIC = b"\xf5\x54\x58\x01"  # 0xF5 'T' 'X' v1

SIGN_DOMAIN = b"tendermint-tpu/tx-envelope/v1\x00"

PUBKEY_SIZE = 32
SIGNATURE_SIZE = 64


class MalformedEnvelopeError(ValueError):
    """MAGIC present but the envelope fields do not decode/size-check."""


@dataclass(frozen=True)
class TxEnvelope:
    pub_key: bytes     # 32-byte ed25519 public key
    signature: bytes   # 64-byte signature over sign_bytes(payload)
    payload: bytes     # the application-level tx bytes


def sign_bytes(payload: bytes) -> bytes:
    """The message actually signed/verified for `payload`."""
    return SIGN_DOMAIN + payload


def encode(pub_key: bytes, signature: bytes, payload: bytes) -> bytes:
    if len(pub_key) != PUBKEY_SIZE:
        raise ValueError(f"pub_key must be {PUBKEY_SIZE} bytes")
    if len(signature) != SIGNATURE_SIZE:
        raise ValueError(f"signature must be {SIGNATURE_SIZE} bytes")
    w = Writer()
    w.bytes(1, pub_key, skip_empty=False)
    w.bytes(2, signature, skip_empty=False)
    w.bytes(3, payload, skip_empty=False)
    return MAGIC + w.finish()


def sign_tx(priv_key, payload: bytes) -> bytes:
    """Wrap `payload` in an envelope signed by `priv_key` (an
    Ed25519PrivKey) — the client-side half of the admission plane."""
    return encode(priv_key.pub_key().bytes(),
                  priv_key.sign(sign_bytes(payload)), payload)


def is_enveloped(tx: bytes) -> bool:
    return tx.startswith(MAGIC)


def parse(tx: bytes) -> TxEnvelope | None:
    """Decode a tx: None for unsigned (no MAGIC), a TxEnvelope for a
    well-formed envelope. Raises MalformedEnvelopeError when the MAGIC
    is present but the body does not decode — malformed is a REJECT
    shape, never a pass-through."""
    if not tx.startswith(MAGIC):
        return None
    pub = sig = payload = None
    try:
        r = Reader(tx[len(MAGIC):])
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                pub = r.bytes()
            elif f == 2:
                sig = r.bytes()
            elif f == 3:
                payload = r.bytes()
            else:
                r.skip(wt)
    except Exception as e:
        raise MalformedEnvelopeError(f"undecodable envelope: {e}") from e
    if pub is None or sig is None or payload is None:
        raise MalformedEnvelopeError("envelope missing pub/sig/payload")
    if len(pub) != PUBKEY_SIZE:
        raise MalformedEnvelopeError(
            f"envelope pub_key {len(pub)}B != {PUBKEY_SIZE}B")
    if len(sig) != SIGNATURE_SIZE:
        raise MalformedEnvelopeError(
            f"envelope signature {len(sig)}B != {SIGNATURE_SIZE}B")
    return TxEnvelope(pub_key=pub, signature=sig, payload=payload)
