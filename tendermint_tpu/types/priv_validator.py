"""PrivValidator interface (reference: types/priv_validator.go).

The signing abstraction consensus uses: FilePV (privval/) persists
last-sign state for double-sign protection; MockPV is the in-memory
test implementation.
"""

from __future__ import annotations

from .. import crypto
from ..crypto import ed25519


class PrivValidator:
    def get_pub_key(self) -> crypto.PubKey:
        raise NotImplementedError

    def sign_vote(self, chain_id: str, vote) -> None:
        """Sets vote.signature in place (raises on refusal)."""
        raise NotImplementedError

    def sign_proposal(self, chain_id: str, proposal) -> None:
        raise NotImplementedError


class MockPV(PrivValidator):
    """In-memory signer for tests; no double-sign protection."""

    def __init__(self, priv_key: crypto.PrivKey | None = None,
                 break_proposal_sigs: bool = False,
                 break_vote_sigs: bool = False):
        self.priv_key = priv_key or ed25519.Ed25519PrivKey.generate()
        self.break_proposal_sigs = break_proposal_sigs
        self.break_vote_sigs = break_vote_sigs

    def get_pub_key(self) -> crypto.PubKey:
        return self.priv_key.pub_key()

    def sign_vote(self, chain_id: str, vote) -> None:
        if self.break_vote_sigs:
            chain_id = "incorrect-chain-id"
        vote.signature = self.priv_key.sign(vote.sign_bytes(chain_id))

    def sign_proposal(self, chain_id: str, proposal) -> None:
        if self.break_proposal_sigs:
            chain_id = "incorrect-chain-id"
        proposal.signature = self.priv_key.sign(proposal.sign_bytes(chain_id))
