"""Validators (reference: types/validator.go)."""

from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto import PubKey
from ..encoding.proto import Reader, Writer


@dataclass
class Validator:
    address: bytes
    pub_key: PubKey
    voting_power: int
    proposer_priority: int = 0

    @classmethod
    def new(cls, pub_key: PubKey, power: int) -> "Validator":
        return cls(pub_key.address(), pub_key, power, 0)

    def validate_basic(self) -> None:
        if self.pub_key is None:
            raise ValueError("validator missing pubkey")
        if self.voting_power < 0:
            raise ValueError("negative voting power")
        if len(self.address) != 20:
            raise ValueError("bad address size")

    def compare_proposer_priority(self, other: "Validator") -> "Validator":
        """Higher priority wins; ties break to the lower address
        (reference: types/validator.go CompareProposerPriority)."""
        if self.proposer_priority > other.proposer_priority:
            return self
        if self.proposer_priority < other.proposer_priority:
            return other
        if self.address < other.address:
            return self
        if self.address > other.address:
            return other
        raise ValueError("duplicate validator address")

    def bytes_for_hash(self) -> bytes:
        """Deterministic encoding hashed into ValidatorsHash
        (reference: types/validator.go Validator.Bytes)."""
        w = Writer()
        pkw = Writer()
        pkw.string(1, self.pub_key.type_name)
        pkw.bytes(2, self.pub_key.bytes())
        w.message(1, pkw)
        w.varint(2, self.voting_power)
        return w.finish()

    def copy(self) -> "Validator":
        return Validator(
            self.address, self.pub_key, self.voting_power, self.proposer_priority
        )

    def to_proto(self) -> Writer:
        w = Writer()
        w.bytes(1, self.address)
        pkw = Writer()
        pkw.string(1, self.pub_key.type_name)
        pkw.bytes(2, self.pub_key.bytes())
        w.message(2, pkw)
        w.varint(3, self.voting_power)
        # two's-complement for possibly-negative priority
        w.varint(4, self.proposer_priority)
        return w

    @classmethod
    def from_bytes(cls, data: bytes) -> "Validator":
        from .. import crypto

        r = Reader(data)
        addr = b""
        pk = None
        power = 0
        prio = 0
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                addr = r.bytes()
            elif f == 2:
                rr = Reader(r.bytes())
                tname, kb = "", b""
                while not rr.at_end():
                    ff, wwt = rr.field()
                    if ff == 1:
                        tname = rr.string()
                    elif ff == 2:
                        kb = rr.bytes()
                    else:
                        rr.skip(wwt)
                pk = crypto.pubkey_from_type_and_bytes(tname, kb)
            elif f == 3:
                power = r.varint()
            elif f == 4:
                prio = r.varint()
            else:
                r.skip(wt)
        if pk is None:
            raise ValueError("validator missing pubkey")
        return cls(addr or pk.address(), pk, power, prio)
