"""Validators (reference: types/validator.go)."""

from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto import PubKey, pubkey_from_type_and_bytes
from ..encoding.proto import Reader, Writer

# crypto.PublicKey oneof field numbers (reference:
# proto/tendermint/crypto/keys.proto — ed25519=1, secp256k1=2).
# sr25519=3 is a repo extension: the reference's codec.go rejects
# sr25519 keys in proto entirely; field 3 follows the upstream
# tendermint v0.35 assignment so a future reference can interop.
_PK_ONEOF = {"ed25519": 1, "secp256k1": 2, "sr25519": 3}
_PK_ONEOF_REV = {v: k for k, v in _PK_ONEOF.items()}


def pubkey_proto_writer(pk: PubKey) -> Writer:
    w = Writer()
    w.bytes(_PK_ONEOF[pk.type_name], pk.bytes(), skip_empty=False)
    return w


def pubkey_from_proto_bytes(data: bytes) -> PubKey:
    r = Reader(data)
    while not r.at_end():
        f, wt = r.field()
        if f in _PK_ONEOF_REV:
            return pubkey_from_type_and_bytes(_PK_ONEOF_REV[f], r.bytes())
        r.skip(wt)
    raise ValueError("PublicKey proto has no known oneof field")


@dataclass
class Validator:
    address: bytes
    pub_key: PubKey
    voting_power: int
    proposer_priority: int = 0

    @classmethod
    def new(cls, pub_key: PubKey, power: int) -> "Validator":
        return cls(pub_key.address(), pub_key, power, 0)

    def validate_basic(self) -> None:
        if self.pub_key is None:
            raise ValueError("validator missing pubkey")
        if self.voting_power < 0:
            raise ValueError("negative voting power")
        if len(self.address) != 20:
            raise ValueError("bad address size")

    def compare_proposer_priority(self, other: "Validator") -> "Validator":
        """Higher priority wins; ties break to the lower address
        (reference: types/validator.go CompareProposerPriority)."""
        if self.proposer_priority > other.proposer_priority:
            return self
        if self.proposer_priority < other.proposer_priority:
            return other
        if self.address < other.address:
            return self
        if self.address > other.address:
            return other
        raise ValueError("duplicate validator address")

    def bytes_for_hash(self) -> bytes:
        """Deterministic encoding hashed into ValidatorsHash
        (reference: types/validator.go Validator.Bytes =
        SimpleValidator{PublicKey pub_key = 1, int64 voting_power = 2}
        with the crypto.PublicKey oneof of keys.proto). Cross-validated
        against the reference's TLA+ MBT corpus, which carries real
        validators_hash values (light/mbt_ref.py)."""
        w = Writer()
        w.message(1, pubkey_proto_writer(self.pub_key))
        w.varint(2, self.voting_power)
        return w.finish()

    def copy(self) -> "Validator":
        return Validator(
            self.address, self.pub_key, self.voting_power, self.proposer_priority
        )

    def to_proto(self) -> Writer:
        """reference: proto/tendermint/types/validator.proto Validator
        {address=1, PublicKey pub_key=2, voting_power=3,
        proposer_priority=4}."""
        w = Writer()
        w.bytes(1, self.address)
        w.message(2, pubkey_proto_writer(self.pub_key))
        w.varint(3, self.voting_power)
        # two's-complement for possibly-negative priority
        w.varint(4, self.proposer_priority)
        return w

    @classmethod
    def from_bytes(cls, data: bytes) -> "Validator":
        r = Reader(data)
        addr = b""
        pk = None
        power = 0
        prio = 0
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                addr = r.bytes()
            elif f == 2:
                pk = pubkey_from_proto_bytes(r.bytes())
            elif f == 3:
                power = r.varint()
            elif f == 4:
                prio = r.varint()
            else:
                r.skip(wt)
        if pk is None:
            raise ValueError("validator missing pubkey")
        return cls(addr or pk.address(), pk, power, prio)
