"""BlockMeta — header + sizing info stored per height (reference:
types/block_meta.go)."""

from __future__ import annotations

from dataclasses import dataclass

from ..encoding.proto import Reader, Writer
from .block import Block, BlockID, Header, block_id_writer, read_block_id


@dataclass
class BlockMeta:
    block_id: BlockID
    block_size: int
    header: Header
    num_txs: int

    @classmethod
    def from_block(cls, block: Block, block_id: BlockID | None = None) -> "BlockMeta":
        data = block.to_bytes()
        bid = block_id or block.block_id()
        return cls(bid, len(data), block.header, len(block.data.txs))

    def to_bytes(self) -> bytes:
        w = Writer()
        w.message(1, block_id_writer(self.block_id))
        w.varint(2, self.block_size)
        w.message(3, self.header.to_proto())
        w.varint(4, self.num_txs)
        return w.finish()

    @classmethod
    def from_bytes(cls, data: bytes) -> "BlockMeta":
        r = Reader(data)
        bid = BlockID(b"", None)
        size = num_txs = 0
        header = None
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                bid = read_block_id(r.bytes())
            elif f == 2:
                size = r.varint()
            elif f == 3:
                header = Header.from_bytes(r.bytes())
            elif f == 4:
                num_txs = r.varint()
            else:
                r.skip(wt)
        assert header is not None, "block meta missing header"
        return cls(bid, size, header, num_txs)
