"""Votes (reference: types/vote.go).

A Vote is a signed prevote or precommit for a BlockID (or nil). The
sign-bytes include the chain ID and the canonical encoding of
(type, height, round, block_id, timestamp).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from ..encoding.proto import Reader, Writer
from . import canonical


class VoteType(enum.IntEnum):
    PREVOTE = 1
    PRECOMMIT = 2

    @classmethod
    def is_valid(cls, v: int) -> bool:
        return v in (cls.PREVOTE, cls.PRECOMMIT)


MAX_VOTES_COUNT = 10000  # DoS bound, reference types/vote_set.go:14-18


@dataclass
class Vote:
    type: VoteType
    height: int
    round: int
    block_id: "BlockID | None"  # None == nil vote
    timestamp: int  # ns since epoch
    validator_address: bytes
    validator_index: int
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical.vote_sign_bytes(
            chain_id, int(self.type), self.height, self.round,
            self.block_id, self.timestamp,
        )

    def verify(self, chain_id: str, pub_key) -> bool:
        """Synchronous single-sig verify (host path). Batch paths go
        through crypto.batch.BatchVerifier with the same sign bytes."""
        if pub_key.address() != self.validator_address:
            return False
        return pub_key.verify_signature(self.sign_bytes(chain_id), self.signature)

    def is_nil(self) -> bool:
        return self.block_id is None or self.block_id.is_nil()

    def validate_basic(self) -> None:
        from .block import MAX_SIGNATURE_SIZE

        if not VoteType.is_valid(int(self.type)):
            raise ValueError("invalid vote type")
        if self.height <= 0:
            raise ValueError("vote height must be positive")
        if self.round < 0:
            raise ValueError("negative round")
        if self.block_id is not None:
            self.block_id.validate_basic()
        if len(self.validator_address) != 20:
            raise ValueError("bad validator address size")
        if self.validator_index < 0:
            raise ValueError("negative validator index")
        if not self.signature:
            raise ValueError("missing signature")
        if len(self.signature) > MAX_SIGNATURE_SIZE:
            raise ValueError("signature too big")

    # -- wire --

    def to_proto(self) -> Writer:
        from .block import block_id_writer

        w = Writer()
        w.varint(1, int(self.type))
        w.varint(2, self.height)
        w.varint(3, self.round)
        w.message(4, block_id_writer(self.block_id))
        w.message(5, canonical.timestamp_writer(self.timestamp))
        w.bytes(6, self.validator_address)
        w.varint(7, self.validator_index)
        w.bytes(8, self.signature)
        return w

    def to_bytes(self) -> bytes:
        return self.to_proto().finish()

    @classmethod
    def from_bytes(cls, data: bytes) -> "Vote":
        from .block import BlockID, read_block_id, read_timestamp

        r = Reader(data)
        kw = dict(
            type=VoteType.PREVOTE, height=0, round=0, block_id=None,
            timestamp=0, validator_address=b"", validator_index=0,
            signature=b"",
        )
        while not r.at_end():
            f, wt = r.field()
            if f == 1:
                kw["type"] = VoteType(r.varint())
            elif f == 2:
                kw["height"] = r.varint()
            elif f == 3:
                kw["round"] = r.varint()
            elif f == 4:
                kw["block_id"] = read_block_id(r.bytes())
            elif f == 5:
                kw["timestamp"] = read_timestamp(r.bytes())
            elif f == 6:
                kw["validator_address"] = r.bytes()
            elif f == 7:
                kw["validator_index"] = r.varint()
            elif f == 8:
                kw["signature"] = r.bytes()
            else:
                r.skip(wt)
        return cls(**kw)
