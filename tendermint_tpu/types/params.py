"""Consensus parameters (reference: types/params.go)."""

from __future__ import annotations

from dataclasses import dataclass, field, asdict

from ..crypto import tmhash

MAX_BLOCK_SIZE_BYTES = 104857600  # 100 MB


@dataclass
class BlockParams:
    max_bytes: int = 22020096  # 21 MB
    max_gas: int = -1
    time_iota_ms: int = 1000


@dataclass
class EvidenceParams:
    max_age_num_blocks: int = 100000
    max_age_duration_ns: int = 48 * 3600 * 1_000_000_000
    max_bytes: int = 1048576


@dataclass
class ValidatorParams:
    pub_key_types: list[str] = field(default_factory=lambda: ["ed25519"])


@dataclass
class VersionParams:
    app_version: int = 0


@dataclass
class ConsensusParams:
    block: BlockParams = field(default_factory=BlockParams)
    evidence: EvidenceParams = field(default_factory=EvidenceParams)
    validator: ValidatorParams = field(default_factory=ValidatorParams)
    version: VersionParams = field(default_factory=VersionParams)

    def validate_basic(self) -> None:
        if not 0 < self.block.max_bytes <= MAX_BLOCK_SIZE_BYTES:
            raise ValueError("block.max_bytes out of range")
        if self.block.max_gas < -1:
            raise ValueError("block.max_gas < -1")
        if self.evidence.max_age_num_blocks <= 0:
            raise ValueError("evidence.max_age_num_blocks must be positive")
        if self.evidence.max_bytes > self.block.max_bytes:
            raise ValueError("evidence.max_bytes > block.max_bytes")
        if not self.validator.pub_key_types:
            raise ValueError("no validator pubkey types")

    def hash(self) -> bytes:
        """Deterministic hash stored in Header.consensus_hash."""
        from ..encoding.proto import Writer

        w = Writer()
        w.varint(1, self.block.max_bytes)
        w.varint(2, self.block.max_gas + 1)  # shift so -1 encodes as 0
        w.varint(3, self.evidence.max_age_num_blocks)
        w.varint(4, self.evidence.max_age_duration_ns)
        w.varint(5, self.evidence.max_bytes)
        for t in self.validator.pub_key_types:
            w.string(6, t)
        w.varint(7, self.version.app_version)
        return tmhash.sum256(w.finish())

    def update(self, updates: dict | None) -> "ConsensusParams":
        """Apply ABCI EndBlock param updates (partial dict form)."""
        import copy

        out = copy.deepcopy(self)
        if not updates:
            return out
        for section, vals in updates.items():
            target = getattr(out, section, None)
            if target is None:
                continue
            for k, v in vals.items():
                if hasattr(target, k):
                    setattr(target, k, v)
        out.validate_basic()
        return out

    def to_json(self) -> dict:
        return asdict(self)

    @classmethod
    def from_json(cls, d: dict | None) -> "ConsensusParams":
        """Accepts both this repo's JSON and the reference's tmjson
        (string-encoded int64s, `max_age_duration`, null params —
        types/genesis.go ConsensusParams)."""
        import dataclasses

        d = d or {}

        def sec(name, klass, renames=()):
            raw = dict(d.get(name) or {})
            fields = {f.name for f in dataclasses.fields(klass)}
            out = {}
            for k, v in raw.items():
                k = dict(renames).get(k, k)
                if k not in fields:
                    # loud, not silent: a typo'd knob running with its
                    # default would be a config the operator didn't ask
                    # for (every reference tmjson key maps via renames)
                    raise ValueError(
                        f"unknown consensus_params.{name} key {k!r}")
                if isinstance(v, str) and v.lstrip("-").isdigit():
                    v = int(v)
                out[k] = v
            return klass(**out)

        return cls(
            block=sec("block", BlockParams),
            evidence=sec("evidence", EvidenceParams,
                         (("max_age_duration", "max_age_duration_ns"),)),
            validator=sec("validator", ValidatorParams),
            version=sec("version", VersionParams),
        )
