"""Typed events + EventBus (reference: types/events.go, event_bus.go).

The EventBus wraps libs.pubsub with the canonical event attribute
keys (tm.event, tx.height, tx.hash, ...) consumed by RPC subscribe
and the tx indexer.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..libs.pubsub import PubSub, Query

EVENT_NEW_BLOCK = "NewBlock"
EVENT_NEW_BLOCK_HEADER = "NewBlockHeader"
EVENT_TX = "Tx"
EVENT_NEW_ROUND = "NewRound"
EVENT_NEW_ROUND_STEP = "NewRoundStep"
EVENT_COMPLETE_PROPOSAL = "CompleteProposal"
EVENT_POLKA = "Polka"
EVENT_LOCK = "Lock"
EVENT_RELOCK = "Relock"
EVENT_UNLOCK = "Unlock"
EVENT_VALID_BLOCK = "ValidBlock"
EVENT_TIMEOUT_PROPOSE = "TimeoutPropose"
EVENT_TIMEOUT_WAIT = "TimeoutWait"
EVENT_VOTE = "Vote"
EVENT_VALIDATOR_SET_UPDATES = "ValidatorSetUpdates"
EVENT_NEW_EVIDENCE = "NewEvidence"

TYPE_KEY = "tm.event"
TX_HASH_KEY = "tx.hash"
TX_HEIGHT_KEY = "tx.height"


def query_for_event(event: str) -> Query:
    return Query.parse(f"{TYPE_KEY} = '{event}'")


QUERY_NEW_BLOCK = query_for_event(EVENT_NEW_BLOCK)
QUERY_TX = query_for_event(EVENT_TX)


@dataclass
class EventDataNewBlock:
    block: object
    result_begin_block: dict = field(default_factory=dict)
    result_end_block: dict = field(default_factory=dict)


@dataclass
class EventDataNewBlockHeader:
    header: object
    num_txs: int = 0


@dataclass
class EventDataTx:
    height: int
    tx: bytes
    index: int
    result: dict = field(default_factory=dict)


@dataclass
class EventDataRoundState:
    height: int
    round: int
    step: str


@dataclass
class EventDataVote:
    vote: object


@dataclass
class EventDataNewEvidence:
    evidence: object
    height: int


@dataclass
class EventDataValidatorSetUpdates:
    validator_updates: list


class EventBus:
    """Typed publish API over a PubSub (reference: types/event_bus.go)."""

    def __init__(self):
        self.pubsub = PubSub()

    def subscribe(self, subscriber: str, query: Query):
        return self.pubsub.subscribe(subscriber, query)

    def unsubscribe(self, subscriber: str, query: Query) -> None:
        self.pubsub.unsubscribe(subscriber, query)

    def unsubscribe_all(self, subscriber: str) -> None:
        self.pubsub.unsubscribe_all(subscriber)

    def _publish(self, event_type: str, data, extra: dict[str, list[str]] | None = None):
        attrs = {TYPE_KEY: [event_type]}
        if extra:
            for k, v in extra.items():
                attrs.setdefault(k, []).extend(v)
        self.pubsub.publish(data, attrs)

    def publish_new_block(self, data: EventDataNewBlock, events: list | None = None):
        self._publish(EVENT_NEW_BLOCK, data, _abci_attrs(events))

    def publish_new_block_header(self, data: EventDataNewBlockHeader):
        self._publish(EVENT_NEW_BLOCK_HEADER, data)

    def publish_tx(self, data: EventDataTx, events: list | None = None):
        from .tx import tx_hash

        attrs = _abci_attrs(events) or {}
        attrs[TX_HASH_KEY] = [tx_hash(data.tx).hex().upper()]
        attrs[TX_HEIGHT_KEY] = [str(data.height)]
        self._publish(EVENT_TX, data, attrs)

    def publish_vote(self, data: EventDataVote):
        self._publish(EVENT_VOTE, data)

    def publish_new_round_step(self, data: EventDataRoundState):
        self._publish(EVENT_NEW_ROUND_STEP, data)

    def publish_new_round(self, data: EventDataRoundState):
        self._publish(EVENT_NEW_ROUND, data)

    def publish_complete_proposal(self, data: EventDataRoundState):
        self._publish(EVENT_COMPLETE_PROPOSAL, data)

    def publish_polka(self, data: EventDataRoundState):
        self._publish(EVENT_POLKA, data)

    def publish_lock(self, data: EventDataRoundState):
        self._publish(EVENT_LOCK, data)

    def publish_relock(self, data: EventDataRoundState):
        self._publish(EVENT_RELOCK, data)

    def publish_unlock(self, data: EventDataRoundState):
        self._publish(EVENT_UNLOCK, data)

    def publish_valid_block(self, data: EventDataRoundState):
        self._publish(EVENT_VALID_BLOCK, data)

    def publish_timeout_propose(self, data: EventDataRoundState):
        self._publish(EVENT_TIMEOUT_PROPOSE, data)

    def publish_timeout_wait(self, data: EventDataRoundState):
        self._publish(EVENT_TIMEOUT_WAIT, data)

    def publish_new_evidence(self, data: EventDataNewEvidence):
        self._publish(EVENT_NEW_EVIDENCE, data)

    def publish_validator_set_updates(self, data: EventDataValidatorSetUpdates):
        self._publish(EVENT_VALIDATOR_SET_UPDATES, data)


def _abci_attrs(events: list | None) -> dict[str, list[str]] | None:
    """Flatten ABCI events ([{type, attributes:[{key,value}]}]) into
    'type.key' -> [values] attributes for query matching."""
    if not events:
        return None
    out: dict[str, list[str]] = {}
    for ev in events:
        etype = ev.get("type", "")
        for attr in ev.get("attributes", []):
            k = attr.get("key", "")
            if isinstance(k, bytes):
                k = k.decode()
            v = attr.get("value", "")
            if isinstance(v, bytes):
                v = v.decode()
            if etype and k:
                out.setdefault(f"{etype}.{k}", []).append(v)
    return out
