"""Node configuration (reference: config/config.go:55-68).

One Config of per-module sections with ValidateBasic on each; TOML
load/save mirrors the reference's config file workflow. Timeout
defaults match config/config.go:846-875 (propose 3000ms +500/round,
prevote/precommit 1000ms +500/round, commit 1000ms)."""

from __future__ import annotations

import os
from dataclasses import dataclass, field


@dataclass
class BaseConfig:
    chain_id: str = ""
    moniker: str = "node"
    home: str = "."
    fast_sync: bool = True
    db_dir: str = "data"
    # sqlite (ordered, disk-resident, range deletes — the tm-db
    # analogue) | filedb (log-structured, memory-resident) | memdb
    db_backend: str = "sqlite"
    # sqlite durability (PRAGMA synchronous): FULL fsyncs every
    # committed batch — the contract the crash-recovery sweep proves.
    # NORMAL/OFF trade the tail of the log for write speed; only safe
    # for replayable non-validator workloads (libs/db.py SqliteDB).
    db_synchronous: str = "FULL"
    genesis_file: str = "config/genesis.json"
    priv_validator_key_file: str = "config/priv_validator_key.json"
    priv_validator_state_file: str = "data/priv_validator_state.json"
    # When set (e.g. "tcp://127.0.0.1:26659"), the node LISTENS here
    # for a remote signer to dial in and uses it instead of the file
    # key (reference: config.go PrivValidatorListenAddr, wired at
    # node.go:663). Run the sidecar: `tendermint-tpu signer
    # --connect <this addr>`.
    priv_validator_laddr: str = ""
    # Pin of the remote signer's LINK identity: hex address of the
    # signer sidecar's node key (printed by `tendermint-tpu signer` at
    # startup). Without it, whoever dials priv_validator_laddr first
    # wins the pinned slot and the real signer is then rejected — a
    # liveness attack if the laddr is reachable beyond loopback. Set
    # this whenever priv_validator_laddr is not loopback/firewalled.
    priv_validator_signer_id: str = ""
    node_key_file: str = "config/node_key.json"
    abci: str = "builtin"  # builtin | socket | grpc
    proxy_app: str = "kvstore"
    # gate inbound conns/peers through ABCI /p2p/filter/... queries
    # (reference config.BaseConfig.FilterPeers, node.go:432-466)
    filter_peers: bool = False
    # builtin kvstore: take a state-sync snapshot every N heights
    # (0 = only advertise the live head; reference e2e app
    # snapshot_interval)
    snapshot_interval: int = 0

    def resolve(self, path: str) -> str:
        return path if os.path.isabs(path) else os.path.join(self.home, path)

    def validate_basic(self) -> None:
        if self.db_backend not in ("sqlite", "filedb", "memdb"):
            raise ValueError(f"unknown db_backend {self.db_backend!r}")
        if self.db_synchronous.upper() not in ("OFF", "NORMAL", "FULL"):
            raise ValueError(
                f"db_synchronous must be OFF|NORMAL|FULL, "
                f"not {self.db_synchronous!r}")


@dataclass
class RPCConfig:
    laddr: str = "tcp://127.0.0.1:26657"
    grpc_laddr: str = ""  # gRPC broadcast API (reference rpc/grpc)
    unsafe: bool = False  # expose unsafe_* / dial_* routes
    max_open_connections: int = 900
    max_subscription_clients: int = 100
    max_subscriptions_per_client: int = 5
    timeout_broadcast_tx_commit_ms: int = 10000
    max_body_bytes: int = 1000000
    pprof_laddr: str = ""
    # Overload limiter (rpc/jsonrpc.py): at most this many requests in
    # flight at once (0 = unlimited), and a token-bucket request rate
    # with ~1 s of burst (0 = unlimited). Excess requests get a
    # 429-style JSON-RPC error instead of queueing unboundedly.
    max_concurrent_requests: int = 256
    rate_limit_rps: float = 0.0

    def validate_basic(self) -> None:
        if self.timeout_broadcast_tx_commit_ms < 0:
            raise ValueError("negative broadcast timeout")
        if self.max_concurrent_requests < 0:
            raise ValueError("negative max_concurrent_requests")
        if self.rate_limit_rps < 0:
            raise ValueError("negative rate_limit_rps")


@dataclass
class P2PConfig:
    laddr: str = "tcp://0.0.0.0:26656"
    external_address: str = ""
    seeds: str = ""
    persistent_peers: str = ""
    max_num_inbound_peers: int = 40
    max_num_outbound_peers: int = 10
    flush_throttle_ms: int = 100
    max_packet_msg_payload_size: int = 1024
    send_rate: int = 5120000
    recv_rate: int = 5120000
    pex: bool = True
    seed_mode: bool = False
    allow_duplicate_ip: bool = False
    handshake_timeout_s: int = 20
    dial_timeout_s: int = 3
    # PEX ensure-peers cadence (reference: PEXReactor
    # ensurePeersPeriod, 30s). Short-lived test nets lower it so
    # seed-bootstrap discovery converges within the run.
    pex_ensure_period_s: float = 30.0
    # Slow-peer escalation (p2p/switch.py + libs/overload.py
    # SlowPeerTracker): a peer whose unsent backlog
    # (pending_send_bytes) sits at/above the high-water mark for
    # consecutive scan intervals escalates skip-gossip -> demote ->
    # disconnect (non-persistent only). 0 high-water disables.
    slow_peer_pending_bytes: int = 1 << 20
    slow_peer_check_interval_s: float = 2.0
    slow_peer_skip_strikes: int = 2
    slow_peer_demote_strikes: int = 4
    slow_peer_disconnect_strikes: int = 8

    def validate_basic(self) -> None:
        if self.max_num_inbound_peers < 0 or self.max_num_outbound_peers < 0:
            raise ValueError("negative peer limits")
        if self.flush_throttle_ms < 0:
            raise ValueError("negative flush throttle")
        if self.pex_ensure_period_s <= 0:
            raise ValueError("pex_ensure_period_s must be positive")
        if self.slow_peer_pending_bytes < 0:
            raise ValueError("negative slow_peer_pending_bytes")
        if self.slow_peer_check_interval_s <= 0:
            raise ValueError("slow_peer_check_interval_s must be positive")
        if not (0 < self.slow_peer_skip_strikes
                <= self.slow_peer_demote_strikes
                <= self.slow_peer_disconnect_strikes):
            raise ValueError(
                "slow_peer strikes must satisfy 0 < skip <= demote "
                "<= disconnect")


@dataclass
class MempoolConfig:
    recheck: bool = True
    broadcast: bool = True
    wal_dir: str = ""
    size: int = 5000
    max_txs_bytes: int = 1073741824
    cache_size: int = 10000
    keep_invalid_txs_in_cache: bool = False
    max_tx_bytes: int = 1048576
    # CheckTx admission control: reject with MempoolBusyError when
    # this many CheckTx requests are already in flight on the ABCI
    # mempool connection (0 = unlimited) — a saturated app window must
    # shed new admissions, not queue them unboundedly.
    checktx_max_inflight: int = 1024
    # Device-offloaded signature pre-verification in front of CheckTx
    # (mempool/admission.py): txs carrying a types/tx_envelope.py
    # signature envelope are coalesced into batched ed25519 verify
    # launches and only signature-valid txs pay the ABCI round trip.
    #   off        — no envelope processing at all
    #   permissive — enveloped txs are pre-verified; unsigned txs pass
    #                through to CheckTx untouched (default)
    #   strict     — unsigned txs are shed too (signed-only chains)
    admission: str = "permissive"
    # micro-batch collector: flush a verify batch at this many txs ...
    admission_batch: int = 256
    # ... or this many ms after the first tx arrives, whichever first
    admission_flush_ms: float = 2.0
    # pre-verify backlog bound (pending + in-verify txs); the newest
    # arrival is shed with a 429-style error when full
    admission_queue: int = 2048

    def validate_basic(self) -> None:
        if self.size < 0 or self.cache_size < 0 or self.max_tx_bytes < 0:
            raise ValueError("negative mempool limits")
        if self.checktx_max_inflight < 0:
            raise ValueError("negative checktx_max_inflight")
        if self.admission not in ("off", "permissive", "strict"):
            raise ValueError(
                f"mempool.admission must be off|permissive|strict, "
                f"not {self.admission!r}")
        if self.admission_batch < 1 or self.admission_queue < 1:
            raise ValueError(
                "admission_batch and admission_queue must be positive")
        if self.admission_flush_ms < 0:
            raise ValueError("negative admission_flush_ms")


@dataclass
class LightConfig:
    """Light-client serving plane (light/serving.py; this framework's
    addition — the reference light proxy verifies per request with no
    cross-request sharing). Knobs for the shared verification plane a
    LightProxy / ServingPool runs requests through."""

    # verified-header LRU entries (trusting-period-aware; a second
    # client hitting a cached height costs a dict lookup, not a
    # device launch)
    cache_size: int = 4096
    # coalesced verify launches flush at this many signature lanes ...
    batch_max: int = 1024
    # ... or this many ms after the first pending check, whichever
    # comes first (the admission-collector window shape)
    flush_ms: float = 2.0
    # pending-verify backlog bound (parked + in-verify commit checks);
    # the newest REQUEST is shed with a 429-style error when full.
    # Floor of 2: one non-adjacent verification parks TWO concurrent
    # commit checks, so pending_max=1 would deterministically shed
    # every skipping verify on an otherwise idle plane
    pending_max: int = 1024
    # ServingPool proxy workers sharing one plane
    workers: int = 2

    def validate_basic(self) -> None:
        for name in ("cache_size", "batch_max", "workers"):
            if getattr(self, name) < 1:
                raise ValueError(f"light.{name} must be positive")
        if self.pending_max < 2:
            raise ValueError(
                "light.pending_max must be >= 2 (a non-adjacent "
                "verification parks two concurrent commit checks)")
        if self.flush_ms < 0:
            raise ValueError("negative light.flush_ms")


@dataclass
class StateSyncConfig:
    enable: bool = False
    rpc_servers: list[str] = field(default_factory=list)
    trust_height: int = 0
    trust_hash: str = ""
    trust_period_s: int = 168 * 3600
    discovery_time_s: int = 15
    chunk_request_timeout_s: int = 10
    chunk_fetchers: int = 4

    def validate_basic(self) -> None:
        if self.enable and self.trust_height <= 0:
            raise ValueError("statesync requires trust_height")


@dataclass
class FastSyncConfig:
    version: str = "v0"
    # Verify-ahead window pipelining (blockchain/verify_ahead.py
    # WindowPipeline): window W+1's commit-signature batch verifies in
    # an executor thread while window W's blocks execute. Verdicts and
    # persistence order are identical either way — disable only to
    # take executor-thread contention off a constrained host.
    verify_ahead: bool = True

    def validate_basic(self) -> None:
        if self.version not in ("v0", "v2"):
            raise ValueError(f"unknown fastsync version {self.version}")


@dataclass
class ConsensusConfig:
    wal_file: str = "data/cs.wal/wal"
    # reference config/config.go:846-875
    timeout_propose_ms: int = 3000
    timeout_propose_delta_ms: int = 500
    timeout_prevote_ms: int = 1000
    timeout_prevote_delta_ms: int = 500
    timeout_precommit_ms: int = 1000
    timeout_precommit_delta_ms: int = 500
    timeout_commit_ms: int = 1000
    skip_timeout_commit: bool = False
    create_empty_blocks: bool = True
    create_empty_blocks_interval_ms: int = 0
    double_sign_check_height: int = 0
    peer_gossip_sleep_ms: int = 100
    peer_query_maj23_sleep_ms: int = 2000
    # Micro-batch vote verification (this framework's TPU hot path —
    # no reference equivalent): incoming votes accumulate for up to
    # vote_batch_window_ms (or until vote_batch_max) and are verified
    # as one device batch off the event loop; 0 disables batching and
    # verifies each vote synchronously like the reference.
    vote_batch_window_ms: float = 2.0
    vote_batch_max: int = 1024
    # Overload bounds (libs/overload.py): the serialized receive
    # funnel is split by class — state/vote/proposal messages get a
    # blocking (backpressure) queue, block parts / catchup data get a
    # shed-when-full queue — and the vote-scheduler buffer is capped
    # (excess votes are shed and re-gossiped via votebits
    # reconciliation once pressure clears).
    peer_funnel_votes_size: int = 1024
    peer_funnel_data_size: int = 512
    vote_buf_max: int = 4096

    def propose_timeout(self, round_: int) -> float:
        return (self.timeout_propose_ms
                + self.timeout_propose_delta_ms * round_) / 1000

    def prevote_timeout(self, round_: int) -> float:
        return (self.timeout_prevote_ms
                + self.timeout_prevote_delta_ms * round_) / 1000

    def precommit_timeout(self, round_: int) -> float:
        return (self.timeout_precommit_ms
                + self.timeout_precommit_delta_ms * round_) / 1000

    def commit_timeout(self) -> float:
        return self.timeout_commit_ms / 1000

    def validate_basic(self) -> None:
        for name in ("timeout_propose_ms", "timeout_propose_delta_ms",
                     "timeout_prevote_ms", "timeout_prevote_delta_ms",
                     "timeout_precommit_ms", "timeout_precommit_delta_ms",
                     "timeout_commit_ms", "create_empty_blocks_interval_ms",
                     "double_sign_check_height"):
            if getattr(self, name) < 0:
                raise ValueError(f"negative {name}")
        for name in ("peer_funnel_votes_size", "peer_funnel_data_size",
                     "vote_buf_max"):
            if getattr(self, name) <= 0:
                raise ValueError(f"{name} must be positive")


@dataclass
class SpeculationConfig:
    """Verify-ahead pipeline (consensus/speculation.py +
    crypto/tpu/resident.py; this framework's addition): commit
    verification launched speculatively as precommits arrive, served
    at commit time from a byte-exact template match — misses fall back
    to the ordinary breaker-aware verify path, so these knobs tune
    performance, never correctness."""

    enabled: bool = True
    # ResidentArena capacity in signature lanes (sentinel included).
    # ~230 B/lane resident, so the default (12,288 = a 10,240-lane
    # commit + headroom) costs ~2.8 MB of device memory — noise next
    # to the expanded comb tables' 3.3 GB on a 16 GB chip. Valsets
    # beyond the capacity speculate on the host path.
    arena_lanes: int = 12288
    # speculation entries kept beyond the current height (fast-sync /
    # catch-up lookahead); entries below height-1 retire on commit
    max_heights_ahead: int = 2
    # micro-batch window: patches accumulate this long after the first
    # pending arrival before a speculative launch (vote-scheduler
    # cadence; 0 launches every drain immediately)
    flush_ms: float = 2.0

    def validate_basic(self) -> None:
        if self.arena_lanes < 2:
            raise ValueError(
                "speculation.arena_lanes must be >= 2 (one sentinel "
                "lane + at least one real lane)")
        if self.max_heights_ahead < 1:
            raise ValueError(
                "speculation.max_heights_ahead must be positive")
        if self.flush_ms < 0:
            raise ValueError("negative speculation.flush_ms")


@dataclass
class MeshConfig:
    """Multi-chip verify fabric (crypto/tpu/{verify,expanded,
    resident}.py; this framework's addition): how the ('dp',) device
    mesh is used by the production verify paths. Pure performance
    knobs — verdicts are identical on any mesh shape."""

    # Key-range sharding crossover for the expanded comb tables:
    # valsets <= this many keys REPLICATE their tables on every chip
    # (every gather chip-local, zero routing overhead); bigger sets
    # row-shard by key range with lane->home-device routing, cutting
    # per-chip HBM by the mesh size and lifting the valset cap to
    # mesh_size x the single-chip budget. 0 = auto (the single-chip
    # table budget — replicate while it fits, shard beyond). Values
    # past the single-chip budget are effectively capped by it: a
    # valset that cannot replicate within one chip shards regardless.
    expanded_shard_crossover_keys: int = 0
    # Split the speculation plane's ResidentArena into per-device
    # shards when a mesh exists: steady-state splices upload only each
    # chip's ~1/N of the ~105 B/lane deltas, and each shard carries
    # its own known-answer sentinel (per-device breaker attribution).
    arena_shards: bool = True

    def validate_basic(self) -> None:
        if self.expanded_shard_crossover_keys < 0:
            raise ValueError(
                "negative mesh.expanded_shard_crossover_keys")


@dataclass
class CryptoConfig:
    """Verify-backend intent + launch-ledger sizing (crypto/tpu/
    {watchdog,ledger}.py; this framework's addition).

    `backend` is the operator's PROMISE, not a dispatch switch: the
    verify paths keep their own breaker-aware device/host ladder
    regardless. With "tpu" the silicon watchdog degrades the /status
    device check whenever the launch ledger shows launches landing on
    CPU, raising, going silent past the window, or drifting >3x past
    the recorded silicon exec baseline — the wedged-relay shape that
    let BENCH_r04/r05 run on TFRT_CPU_0 unnoticed. "auto" (default)
    and "cpu" report the effective backend but never degrade on it."""

    backend: str = "auto"
    # effective-backend classification window: how long without a
    # successful device launch before the watchdog calls the plane
    # idle/degraded
    watchdog_window_s: float = 60.0
    # bounded launch-ledger ring (records, process-global; ~1 KB each)
    ledger_capacity: int = 512

    def validate_basic(self) -> None:
        if self.backend not in ("auto", "tpu", "cpu"):
            raise ValueError(
                f"unknown crypto.backend {self.backend!r} "
                "(want auto|tpu|cpu)")
        if self.watchdog_window_s <= 0:
            raise ValueError("crypto.watchdog_window_s must be positive")
        if self.ledger_capacity < 16:
            raise ValueError("crypto.ledger_capacity must be >= 16")


def fast_consensus_config() -> ConsensusConfig:
    """Short timeouts for in-process tests (reference: the 10ms
    timeout-commit test config, config/config.go:867-875)."""
    return ConsensusConfig(
        timeout_propose_ms=400, timeout_propose_delta_ms=100,
        timeout_prevote_ms=200, timeout_prevote_delta_ms=100,
        timeout_precommit_ms=200, timeout_precommit_delta_ms=100,
        timeout_commit_ms=20, skip_timeout_commit=True,
    )


@dataclass
class InstrumentationConfig:
    prometheus: bool = False
    prometheus_listen_addr: str = ":26660"
    namespace: str = "tendermint"


@dataclass
class ChaosConfig:
    """Chaos engineering (this framework's addition; no reference
    equivalent). `failpoints` is a libs/failpoints.py spec string —
    e.g. "wal.fsync=delay:50;every=10,device.verify=error;prob=0.01"
    — armed at node build time. Config is the STRICT surface: a
    malformed spec fails validate_basic instead of being skipped
    (unlike the TM_TPU_FAILPOINTS env var, which logs and ignores)."""

    failpoints: str = ""

    def validate_basic(self) -> None:
        if self.failpoints:
            from .libs.failpoints import validate_spec

            # the SAME checks install_spec/arm() enforce (dry run):
            # anything that would raise at node build must raise here
            try:
                validate_spec(self.failpoints)
            except ValueError as e:
                raise ValueError(f"[chaos] failpoints: {e}") from None


@dataclass
class TxIndexConfig:
    """reference: config/config.go:976 TxIndexConfig — which indexer
    backs /tx_search and /block_search: "kv" (default) or "null"
    (indexing disabled; the search RPCs then error)."""

    indexer: str = "kv"

    def validate_basic(self) -> None:
        if self.indexer not in ("kv", "null"):
            raise ValueError(f"unknown tx_index.indexer {self.indexer!r}")


@dataclass
class Config:
    base: BaseConfig = field(default_factory=BaseConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    p2p: P2PConfig = field(default_factory=P2PConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    light: LightConfig = field(default_factory=LightConfig)
    statesync: StateSyncConfig = field(default_factory=StateSyncConfig)
    fastsync: FastSyncConfig = field(default_factory=FastSyncConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    speculation: SpeculationConfig = field(
        default_factory=SpeculationConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    crypto: CryptoConfig = field(default_factory=CryptoConfig)
    tx_index: TxIndexConfig = field(default_factory=TxIndexConfig)
    instrumentation: InstrumentationConfig = field(
        default_factory=InstrumentationConfig
    )
    chaos: ChaosConfig = field(default_factory=ChaosConfig)

    def validate_basic(self) -> None:
        self.base.validate_basic()
        self.rpc.validate_basic()
        self.p2p.validate_basic()
        self.mempool.validate_basic()
        self.light.validate_basic()
        self.statesync.validate_basic()
        self.fastsync.validate_basic()
        self.consensus.validate_basic()
        self.speculation.validate_basic()
        self.mesh.validate_basic()
        self.crypto.validate_basic()
        self.tx_index.validate_basic()
        self.chaos.validate_basic()

    # -- file round trip (flat TOML-ish key=value per [section]) --

    def save(self, path: str) -> None:
        import dataclasses

        lines = []
        for section_name in ("base", "rpc", "p2p", "mempool", "light",
                             "statesync", "fastsync", "consensus",
                             "speculation", "mesh", "crypto",
                             "tx_index", "instrumentation", "chaos"):
            section = getattr(self, section_name)
            lines.append(f"[{section_name}]")
            for f in dataclasses.fields(section):
                v = getattr(section, f.name)
                if isinstance(v, bool):
                    sv = "true" if v else "false"
                elif isinstance(v, list):
                    sv = '"' + ",".join(v) + '"'
                elif isinstance(v, str):
                    sv = f'"{v}"'
                else:
                    sv = str(v)
                lines.append(f"{f.name} = {sv}")
            lines.append("")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as fh:
            fh.write("\n".join(lines))

    @classmethod
    def load(cls, path: str) -> "Config":
        import dataclasses

        cfg = cls()
        section = None
        with open(path) as fh:
            for raw in fh:
                line = raw.strip()
                if not line or line.startswith("#"):
                    continue
                if line.startswith("[") and line.endswith("]"):
                    section = getattr(cfg, line[1:-1], None)
                    continue
                if section is None or "=" not in line:
                    continue
                key, _, val = line.partition("=")
                key, val = key.strip(), val.strip()
                fld = next(
                    (f for f in dataclasses.fields(section) if f.name == key),
                    None,
                )
                if fld is None:
                    continue
                if fld.type in ("bool", bool):
                    setattr(section, key, val == "true")
                elif fld.type in ("int", int):
                    setattr(section, key, int(val))
                elif fld.type in ("float", float):
                    setattr(section, key, float(val))
                elif fld.type.startswith("list") if isinstance(fld.type, str) else False:
                    s = val.strip('"')
                    setattr(section, key, [x for x in s.split(",") if x])
                else:
                    setattr(section, key, val.strip('"'))
        return cfg
