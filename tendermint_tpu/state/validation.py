"""Stateful block validation (reference: state/validation.go:14).

The LastCommit check is a full batched VerifyCommit — on the hot path
this is the single biggest signature workload in block processing, and
it runs as ONE BatchVerifier call (TPU-wide) instead of the
reference's sequential loop."""

from __future__ import annotations

from ..types.block import Block
from ..types.validator_set import VerificationError
from . import State, median_time


class BlockValidationError(Exception):
    pass


def validate_block(state: State, block: Block, evidence_pool=None,
                   speculation=None) -> None:
    block.validate_basic()
    h = block.header

    from . import BLOCK_PROTOCOL_VERSION

    if h.version_block != BLOCK_PROTOCOL_VERSION:
        raise BlockValidationError(
            f"block protocol version {h.version_block} != {BLOCK_PROTOCOL_VERSION}"
        )
    if h.version_app != state.app_version:
        raise BlockValidationError(
            f"app version {h.version_app} != {state.app_version}"
        )
    if h.chain_id != state.chain_id:
        raise BlockValidationError(
            f"chain id {h.chain_id!r} != {state.chain_id!r}"
        )
    if state.last_block_height == 0:
        if h.height != state.initial_height:
            raise BlockValidationError(
                f"expected initial height {state.initial_height}, got {h.height}"
            )
    elif h.height != state.last_block_height + 1:
        raise BlockValidationError(
            f"expected height {state.last_block_height + 1}, got {h.height}"
        )
    if h.last_block_id != state.last_block_id:
        raise BlockValidationError("wrong LastBlockID")

    # hashes against current state
    if h.app_hash != state.app_hash:
        raise BlockValidationError("wrong AppHash")
    if h.consensus_hash != state.consensus_params.hash():
        raise BlockValidationError("wrong ConsensusHash")
    if h.validators_hash != state.validators.hash():
        raise BlockValidationError("wrong ValidatorsHash")
    if h.next_validators_hash != state.next_validators.hash():
        raise BlockValidationError("wrong NextValidatorsHash")
    if h.last_results_hash != state.last_results_hash:
        raise BlockValidationError("wrong LastResultsHash")

    # LastCommit: genesis block carries an empty one; later blocks carry
    # +2/3 of the previous validator set — ALL sigs verified, batched.
    if h.height == state.initial_height:
        if block.last_commit is not None and block.last_commit.signatures:
            raise BlockValidationError("initial block can't have LastCommit sigs")
    else:
        if block.last_commit is None:
            raise BlockValidationError("nil LastCommit")
        if len(block.last_commit.signatures) != len(state.last_validators):
            raise BlockValidationError(
                f"LastCommit has {len(block.last_commit.signatures)} sigs, "
                f"need {len(state.last_validators)}"
            )
        from ..libs.metrics import state_metrics

        try:
            with state_metrics().commit_verify_seconds.time():
                # Verify-ahead serve point (consensus/speculation.py):
                # a speculation hit answers from the launch that ran
                # while the precommits were still arriving — zero
                # verification launches here; misses (and commits the
                # plane never saw) take the ordinary batched path.
                served = False
                if speculation is not None:
                    served = speculation.serve_commit(
                        state.last_validators, state.chain_id,
                        state.last_block_id, h.height - 1,
                        block.last_commit)
                if not served:
                    state.last_validators.verify_commit(
                        state.chain_id, state.last_block_id,
                        h.height - 1, block.last_commit,
                    )
        except VerificationError as e:
            raise BlockValidationError(f"invalid LastCommit: {e}") from e

    # time: initial block matches genesis; later blocks carry the
    # weighted median of LastCommit timestamps (BFT time) and must be
    # strictly after the previous block
    if h.height == state.initial_height:
        if h.time != state.last_block_time:
            raise BlockValidationError("genesis block time mismatch")
    else:
        if h.time <= state.last_block_time:
            raise BlockValidationError("block time not after last block")
        expected = median_time(block.last_commit, state.last_validators)
        if h.time != expected:
            raise BlockValidationError(
                f"block time {h.time} != median commit time {expected}"
            )

    # evidence size + validity
    max_ev = state.consensus_params.evidence.max_bytes
    ev_bytes = sum(len(e.to_bytes()) for e in block.evidence.evidence)
    if ev_bytes > max_ev:
        raise BlockValidationError("evidence exceeds max bytes")
    if evidence_pool is not None and block.evidence.evidence:
        evidence_pool.check_evidence(block.evidence.evidence)

    if not state.validators.has_address(h.proposer_address):
        raise BlockValidationError("proposer not in validator set")
