"""State store (reference: state/store.go:74-81).

Persists: the current State, validator sets (sparse — a full set every
`VALSET_CHECKPOINT` heights, else a pointer to the last stored height,
reference state/store.go:458 lastStoredHeightFor), consensus params
(same sparse scheme), and per-height ABCI responses for replay."""

from __future__ import annotations

import json
import struct

from ..libs.db import DB
from ..types.block import BlockID, PartSetHeader
from ..types.params import ConsensusParams
from ..types.validator import Validator
from ..types.validator_set import ValidatorSet
from ..abci import types as abci_types
from . import State

VALSET_CHECKPOINT = 100000  # reference: valSetCheckpointInterval

_STATE_KEY = b"stateKey"


def _h(height: int) -> bytes:
    return struct.pack(">Q", height)


def _valset_to_json(vs: ValidatorSet) -> dict:
    return {
        "validators": [
            {
                "pub_key_type": v.pub_key.type_name,
                "pub_key": v.pub_key.bytes().hex(),
                "power": v.voting_power,
                "priority": v.proposer_priority,
            }
            for v in vs.validators
        ],
        "proposer": vs.proposer.address.hex() if vs.proposer else None,
    }


def _valset_from_json(d: dict) -> ValidatorSet:
    from .. import crypto

    vs = ValidatorSet([])
    for vd in d["validators"]:
        pk = crypto.pubkey_from_type_and_bytes(
            vd["pub_key_type"], bytes.fromhex(vd["pub_key"])
        )
        val = Validator.new(pk, vd["power"])
        val.proposer_priority = vd["priority"]
        vs.validators.append(val)
    if d.get("proposer"):
        i, v = vs.get_by_address(bytes.fromhex(d["proposer"]))
        vs.proposer = v
    return vs


class Store:
    def __init__(self, db: DB):
        self.db = db

    # -- state --

    def save(self, state: State) -> None:
        ops = self._save_ops(state)
        self.db.write_batch(ops)

    def _save_ops(self, state: State) -> list[tuple[bytes, bytes | None]]:
        next_height = state.last_block_height + 1
        if next_height == 1:
            next_height = state.initial_height
            ops = self._valset_ops(next_height, state.validators)
        else:
            ops = []
        ops += self._valset_ops(next_height + 1, state.next_validators)
        ops += self._params_ops(next_height, state.consensus_params,
                                state.last_height_consensus_params_changed)
        ops.append((_STATE_KEY, self._state_bytes(state)))
        return ops

    def _state_bytes(self, state: State) -> bytes:
        bid = state.last_block_id
        return json.dumps({
            "chain_id": state.chain_id,
            "initial_height": state.initial_height,
            "last_block_height": state.last_block_height,
            "last_block_id": {
                "hash": bid.hash.hex(),
                "psh_total": bid.part_set_header.total if bid.part_set_header else 0,
                "psh_hash": bid.part_set_header.hash.hex() if bid.part_set_header else "",
            },
            "last_block_time": state.last_block_time,
            "validators": _valset_to_json(state.validators),
            "next_validators": _valset_to_json(state.next_validators),
            "last_validators": _valset_to_json(state.last_validators),
            "last_height_validators_changed": state.last_height_validators_changed,
            "consensus_params": state.consensus_params.to_json(),
            "last_height_consensus_params_changed":
                state.last_height_consensus_params_changed,
            "last_results_hash": state.last_results_hash.hex(),
            "app_hash": state.app_hash.hex(),
            "app_version": state.app_version,
        }).encode()

    def load(self) -> State | None:
        raw = self.db.get(_STATE_KEY)
        if raw is None:
            return None
        d = json.loads(raw)
        bd = d["last_block_id"]
        psh = (
            PartSetHeader(bd["psh_total"], bytes.fromhex(bd["psh_hash"]))
            if bd["psh_total"] else None
        )
        return State(
            chain_id=d["chain_id"],
            initial_height=d["initial_height"],
            last_block_height=d["last_block_height"],
            last_block_id=BlockID(bytes.fromhex(bd["hash"]), psh),
            last_block_time=d["last_block_time"],
            next_validators=_valset_from_json(d["next_validators"]),
            validators=_valset_from_json(d["validators"]),
            last_validators=_valset_from_json(d["last_validators"]),
            last_height_validators_changed=d["last_height_validators_changed"],
            consensus_params=ConsensusParams.from_json(d["consensus_params"]),
            last_height_consensus_params_changed=
                d["last_height_consensus_params_changed"],
            last_results_hash=bytes.fromhex(d["last_results_hash"]),
            app_hash=bytes.fromhex(d["app_hash"]),
            app_version=d.get("app_version", 0),
        )

    def bootstrap(self, state: State) -> None:
        """Seed the store from an out-of-band trusted state (statesync;
        reference state/store.go:188). ONE batch: these rows used to go
        out as four separate write_batch calls plus a set, so a crash
        mid-bootstrap could leave a height with a validator set but no
        state row (or vice versa) — a skew no startup reconciler can
        tell apart from corruption. All-or-nothing now."""
        height = state.last_block_height + 1
        if height == 1:
            height = state.initial_height
        ops: list[tuple[bytes, bytes | None]] = []
        if height > 1 and len(state.last_validators):
            ops += self._valset_ops(height - 1, state.last_validators)
        ops += self._valset_ops(height, state.validators)
        ops += self._valset_ops(height + 1, state.next_validators)
        ops += self._params_ops(height, state.consensus_params,
                                state.last_height_consensus_params_changed)
        ops.append((_STATE_KEY, self._state_bytes(state)))
        self.db.write_batch(ops)

    # -- validator sets (sparse) --

    def _valset_ops(self, height: int, vs: ValidatorSet):
        # checkpoint heights and every height store the full set; other
        # heights COULD store a pointer — we store full sets but prune
        # keeps checkpoints, mirroring the reference's recoverability.
        return [(b"validatorsKey:" + _h(height),
                 json.dumps(_valset_to_json(vs)).encode())]

    def save_validator_set(self, height: int, vs: ValidatorSet) -> None:
        self.db.write_batch(self._valset_ops(height, vs))

    def load_validators(self, height: int) -> ValidatorSet | None:
        raw = self.db.get(b"validatorsKey:" + _h(height))
        if raw is None:
            return None
        return _valset_from_json(json.loads(raw))

    # -- consensus params (sparse via last-changed pointer) --

    def _params_ops(self, height: int, params: ConsensusParams,
                    last_changed: int):
        return [(b"consensusParamsKey:" + _h(height),
                 json.dumps({
                     "params": params.to_json(),
                     "last_changed": last_changed,
                 }).encode())]

    def load_consensus_params(self, height: int) -> ConsensusParams | None:
        raw = self.db.get(b"consensusParamsKey:" + _h(height))
        if raw is None:
            return None
        return ConsensusParams.from_json(json.loads(raw)["params"])

    # -- ABCI responses (for replay + RPC block_results) --

    def save_abci_responses(self, height: int, responses: dict) -> None:
        """responses: {"deliver_txs": [ResponseDeliverTx], "begin_block":
        ResponseBeginBlock, "end_block": ResponseEndBlock}."""
        self.db.set(
            b"abciResponsesKey:" + _h(height),
            json.dumps({
                "deliver_txs": [
                    abci_types.encode_msg(r).decode()
                    for r in responses.get("deliver_txs", [])
                ],
                "begin_block": abci_types.encode_msg(
                    responses["begin_block"]
                ).decode() if responses.get("begin_block") else None,
                "end_block": abci_types.encode_msg(
                    responses["end_block"]
                ).decode() if responses.get("end_block") else None,
            }).encode(),
        )

    def load_abci_responses(self, height: int) -> dict | None:
        raw = self.db.get(b"abciResponsesKey:" + _h(height))
        if raw is None:
            return None
        d = json.loads(raw)
        return {
            "deliver_txs": [
                abci_types.decode_msg(s.encode()) for s in d["deliver_txs"]
            ],
            "begin_block": abci_types.decode_msg(d["begin_block"].encode())
                if d["begin_block"] else None,
            "end_block": abci_types.decode_msg(d["end_block"].encode())
                if d["end_block"] else None,
        }

    # -- pruning (reference state/store.go:223) --

    def prune_states(self, from_height: int, to_height: int) -> None:
        if from_height <= 0 or to_height <= from_height:
            return
        ops: list[tuple[bytes, bytes | None]] = []
        for height in range(from_height, to_height):
            if height % VALSET_CHECKPOINT != 0:
                ops.append((b"validatorsKey:" + _h(height), None))
            ops.append((b"consensusParamsKey:" + _h(height), None))
            ops.append((b"abciResponsesKey:" + _h(height), None))
        self.db.write_batch(ops)
