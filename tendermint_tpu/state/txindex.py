"""Transaction indexer (reference: state/txindex/).

IndexerService subscribes to the EventBus Tx stream and writes each
TxResult into a kv index: primary record by tx hash, secondary keys
for height and for every ABCI event attribute (`type.key=value`), so
`tx_search` can answer the same query language the pubsub uses."""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass

from ..crypto import tmhash
from ..libs.pubsub import Query
from ..types.events import EventDataTx, query_for_event

logger = logging.getLogger("txindex")

_PRIMARY = b"tx/"
_BY_HEIGHT = b"txh/"
_BY_EVENT = b"txe/"


@dataclass
class TxResult:
    height: int
    index: int
    tx: bytes
    result: dict

    def hash(self) -> bytes:
        return tmhash.sum256(self.tx)


class TxIndexer:
    """kv indexer (reference: state/txindex/kv/kv.go)."""

    def __init__(self, db):
        self.db = db

    def index(self, tr: TxResult) -> None:
        h = tr.hash()
        payload = json.dumps({
            "height": tr.height, "index": tr.index,
            "tx": tr.tx.hex(), "result": tr.result,
        }).encode()
        ops = [(_PRIMARY + h, payload),
               (_BY_HEIGHT + _u64(tr.height) + _u32(tr.index) + h, b"")]
        for ev in tr.result.get("events", []):
            etype = ev.get("type", "")
            for attr in ev.get("attributes", []):
                k, v = attr.get("key", ""), attr.get("value", "")
                if not etype or not k:
                    continue
                composite = f"{etype}.{k}={v}".encode()
                ops.append((_BY_EVENT + composite + b"/" +
                            _u64(tr.height) + _u32(tr.index) + h, b""))
        self.db.write_batch(ops)

    def get(self, tx_hash: bytes) -> TxResult | None:
        raw = self.db.get(_PRIMARY + tx_hash)
        if raw is None:
            return None
        d = json.loads(raw)
        return TxResult(d["height"], d["index"],
                        bytes.fromhex(d["tx"]), d["result"])

    def search(self, query: Query) -> list[TxResult]:
        """Equality conditions narrow via the secondary indexes and are
        intersected; every other operator (ranges, CONTAINS, EXISTS) is
        applied as a post-filter. A query with no equality condition
        scans the primary records (reference kv.go Search)."""
        candidate_sets: list[set[bytes]] = []
        for cond in query.conditions:
            if cond.op != "=":
                continue
            if cond.key == "tx.height":
                h = _height_literal(cond.value)
                hashes = set() if h is None or h < 0 else {
                    k[-32:] for k, _ in self.db.iterate_prefix(
                        _BY_HEIGHT + _u64(h))
                }
            else:
                # Exact-composite match: the remainder after the
                # composite must be exactly "/" + u64 + u32 + hash —
                # a stored value that merely EXTENDS the queried one
                # past a "/" (paths, denoms) leaves a longer
                # remainder and is rejected.
                prefix = _BY_EVENT + \
                    f"{cond.key}={_fmt_value(cond.value)}".encode()
                rem = 1 + 8 + 4 + 32
                hashes = {
                    k[-32:] for k, _ in self.db.iterate_prefix(prefix)
                    if len(k) == len(prefix) + rem and
                    k[len(prefix):len(prefix) + 1] == b"/"
                }
            candidate_sets.append(hashes)
        if candidate_sets:
            hits = set.intersection(*candidate_sets)
        else:
            hits = {k[len(_PRIMARY):]
                    for k, _ in self.db.iterate_prefix(_PRIMARY)}
        out = [self.get(h) for h in sorted(hits)]
        results = [t for t in out if t is not None]
        for cond in query.conditions:
            if cond.op == "=":
                continue
            results = [
                t for t in results
                if cond.matches({cond.key: vals} if
                                (vals := _attr_values(t, cond)) else {})
            ]
        results.sort(key=lambda t: (t.height, t.index))
        return results


def _attr_values(tr: TxResult, cond) -> list[str]:
    if cond.key == "tx.height":
        return [str(tr.height)]
    if cond.key == "tx.hash":
        return [tr.hash().hex().upper()]
    out = []
    for ev in tr.result.get("events", []):
        for attr in ev.get("attributes", []):
            if f"{ev.get('type')}.{attr.get('key')}" == cond.key:
                out.append(attr.get("value", ""))
    return out


def _height_literal(v) -> int | None:
    """Exact-integer height from a query literal; None when the
    literal can't match any height (non-numeric string, fractional
    float) — int() truncation would turn `height = 3.5` into a wrong
    match at 3, and int('abc') would escape as an internal error."""
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return int(f) if f.is_integer() else None


def _fmt_value(v) -> str:
    """Render a query literal the way event attributes are stored:
    Query.parse turns unquoted numbers into floats, but ABCI event
    attribute values are strings — `amount = 100` must produce the
    composite `amount=100`, not `amount=100.0`."""
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


def _u64(v: int) -> bytes:
    return v.to_bytes(8, "big")


def _u32(v: int) -> bytes:
    return v.to_bytes(4, "big")


_BLK_PRIMARY = b"blk/"
_BLK_EVENT = b"blke/"


class BlockIndexer:
    """Indexes BeginBlock/EndBlock events per height so block_search
    can answer event queries (later-v0.34.x state/indexer/block/kv —
    the pinned reference predates the route; semantics match the
    released version: `block.height` is implicit, every event
    attribute is searchable as `type.key=value`)."""

    def __init__(self, db):
        self.db = db

    def index(self, height: int, result_begin_block: dict,
              result_end_block: dict) -> None:
        ops = [(_BLK_PRIMARY + _u64(height), b"")]
        for res in (result_begin_block, result_end_block):
            for ev in (res or {}).get("events", []):
                etype = ev.get("type", "")
                for attr in ev.get("attributes", []):
                    k, v = attr.get("key", ""), attr.get("value", "")
                    if not etype or not k:
                        continue
                    composite = f"{etype}.{k}={v}".encode()
                    ops.append((_BLK_EVENT + composite + b"/" +
                                _u64(height), b""))
        self.db.write_batch(ops)

    def has(self, height: int) -> bool:
        return self.db.get(_BLK_PRIMARY + _u64(height)) is not None

    def search(self, query: Query) -> list[int]:
        """Heights matching the query, ascending. Equality conditions
        narrow via the index; other operators post-filter (which for
        block queries can only reference block.height or indexed
        attributes of candidate heights)."""
        candidate_sets: list[set[int]] = []
        for cond in query.conditions:
            if cond.op != "=":
                continue
            if cond.key == "block.height":
                h = _height_literal(cond.value)
                candidate_sets.append(
                    {h} if h is not None and h >= 0 and self.has(h)
                    else set())
            else:
                # exact-composite match (see TxIndexer.search): the
                # remainder must be exactly "/" + u64(height)
                prefix = _BLK_EVENT + \
                    f"{cond.key}={_fmt_value(cond.value)}".encode()
                candidate_sets.append({
                    int.from_bytes(k[-8:], "big")
                    for k, _ in self.db.iterate_prefix(prefix)
                    if len(k) == len(prefix) + 9 and
                    k[len(prefix):len(prefix) + 1] == b"/"
                })
        if candidate_sets:
            hits = set.intersection(*candidate_sets)
        else:
            hits = {int.from_bytes(k[len(_BLK_PRIMARY):], "big")
                    for k, _ in self.db.iterate_prefix(_BLK_PRIMARY)}
        heights = sorted(hits)
        for cond in query.conditions:
            if cond.op == "=":
                continue
            if cond.key == "block.height":
                heights = [h for h in heights
                           if cond.matches({"block.height": [str(h)]})]
            else:
                # One prefix scan bucketed by height (not a rescan per
                # candidate — that is O(heights x index entries)).
                # Empty value list -> empty attrs (not {key: []}), so
                # EXISTS on a never-emitted event matches nothing
                # (same guard as TxIndexer.search above).
                by_height = self._attr_values_by_height(cond.key)
                heights = [
                    h for h in heights
                    if cond.matches({cond.key: vals} if
                                    (vals := by_height.get(h)) else {})
                ]
        return heights

    def _attr_values_by_height(self, key: str) -> dict[int, list[str]]:
        prefix = _BLK_EVENT + key.encode() + b"="
        out: dict[int, list[str]] = {}
        for k, _ in self.db.iterate_prefix(prefix):
            # layout: prefix + value + "/" + u64(height)
            if len(k) < len(prefix) + 9 or k[-9:-8] != b"/":
                continue
            h = int.from_bytes(k[-8:], "big")
            out.setdefault(h, []).append(
                k[len(prefix):-9].decode("utf-8", "replace"))
        return out


class IndexerService:
    """Bridges EventBus → TxIndexer
    (reference: state/txindex/indexer_service.go)."""

    SUBSCRIBER = "tx-indexer"

    def __init__(self, indexer: TxIndexer, event_bus,
                 block_indexer: BlockIndexer | None = None):
        self.indexer = indexer
        self.block_indexer = block_indexer
        self.event_bus = event_bus

    def start(self) -> None:
        import asyncio

        self._sub = self.event_bus.subscribe(self.SUBSCRIBER,
                                             query_for_event("Tx"))
        self._blk_sub = self.event_bus.subscribe(
            self.SUBSCRIBER, query_for_event("NewBlock")) \
            if self.block_indexer is not None else None
        loop = asyncio.get_running_loop()
        self._task = loop.create_task(self._run(), name="tx-indexer")
        self._blk_task = loop.create_task(
            self._run_blocks(), name="block-indexer") \
            if self._blk_sub is not None else None

    def stop(self) -> None:
        self.event_bus.unsubscribe_all(self.SUBSCRIBER)
        for t in (getattr(self, "_task", None),
                  getattr(self, "_blk_task", None)):
            if t is not None:
                t.cancel()

    async def _run(self) -> None:
        import asyncio

        while True:
            try:
                msg = await self._sub.next()
            except asyncio.CancelledError:
                return
            data = msg.data
            if isinstance(data, EventDataTx):
                try:
                    self.indexer.index(TxResult(data.height, data.index,
                                                data.tx, data.result))
                except Exception:
                    logger.exception("failed to index tx at height %d",
                                     data.height)

    async def _run_blocks(self) -> None:
        import asyncio

        from ..types.events import EventDataNewBlock

        while True:
            try:
                msg = await self._blk_sub.next()
            except asyncio.CancelledError:
                return
            data = msg.data
            if isinstance(data, EventDataNewBlock):
                try:
                    self.block_indexer.index(
                        data.block.header.height,
                        data.result_begin_block, data.result_end_block)
                except Exception:
                    logger.exception("failed to index block events")
