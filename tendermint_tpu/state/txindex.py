"""Transaction indexer (reference: state/txindex/).

IndexerService subscribes to the EventBus Tx stream and writes each
TxResult into a kv index: primary record by tx hash, secondary keys
for height and for every ABCI event attribute (`type.key=value`), so
`tx_search` can answer the same query language the pubsub uses."""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass

from ..crypto import tmhash
from ..libs.pubsub import Query
from ..types.events import EventDataTx, query_for_event

logger = logging.getLogger("txindex")

_PRIMARY = b"tx/"
_BY_HEIGHT = b"txh/"
_BY_EVENT = b"txe/"


@dataclass
class TxResult:
    height: int
    index: int
    tx: bytes
    result: dict

    def hash(self) -> bytes:
        return tmhash.sum256(self.tx)


class TxIndexer:
    """kv indexer (reference: state/txindex/kv/kv.go)."""

    def __init__(self, db):
        self.db = db

    def index(self, tr: TxResult) -> None:
        h = tr.hash()
        payload = json.dumps({
            "height": tr.height, "index": tr.index,
            "tx": tr.tx.hex(), "result": tr.result,
        }).encode()
        ops = [(_PRIMARY + h, payload),
               (_BY_HEIGHT + _u64(tr.height) + _u32(tr.index) + h, b"")]
        for ev in tr.result.get("events", []):
            etype = ev.get("type", "")
            for attr in ev.get("attributes", []):
                k, v = attr.get("key", ""), attr.get("value", "")
                if not etype or not k:
                    continue
                composite = f"{etype}.{k}={v}".encode()
                ops.append((_BY_EVENT + composite + b"/" +
                            _u64(tr.height) + _u32(tr.index) + h, b""))
        self.db.write_batch(ops)

    def get(self, tx_hash: bytes) -> TxResult | None:
        raw = self.db.get(_PRIMARY + tx_hash)
        if raw is None:
            return None
        d = json.loads(raw)
        return TxResult(d["height"], d["index"],
                        bytes.fromhex(d["tx"]), d["result"])

    def search(self, query: Query) -> list[TxResult]:
        """Equality conditions narrow via the secondary indexes and are
        intersected; every other operator (ranges, CONTAINS, EXISTS) is
        applied as a post-filter. A query with no equality condition
        scans the primary records (reference kv.go Search)."""
        candidate_sets: list[set[bytes]] = []
        for cond in query.conditions:
            if cond.op != "=":
                continue
            if cond.key == "tx.height":
                hashes = {
                    k[-32:] for k, _ in self.db.iterate_prefix(
                        _BY_HEIGHT + _u64(int(cond.value)))
                }
            else:
                composite = f"{cond.key}={cond.value}".encode()
                hashes = {
                    k[-32:] for k, _ in self.db.iterate_prefix(
                        _BY_EVENT + composite + b"/")
                }
            candidate_sets.append(hashes)
        if candidate_sets:
            hits = set.intersection(*candidate_sets)
        else:
            hits = {k[len(_PRIMARY):]
                    for k, _ in self.db.iterate_prefix(_PRIMARY)}
        out = [self.get(h) for h in sorted(hits)]
        results = [t for t in out if t is not None]
        for cond in query.conditions:
            if cond.op == "=":
                continue
            results = [
                t for t in results
                if cond.matches({cond.key: vals} if
                                (vals := _attr_values(t, cond)) else {})
            ]
        results.sort(key=lambda t: (t.height, t.index))
        return results


def _attr_values(tr: TxResult, cond) -> list[str]:
    if cond.key == "tx.height":
        return [str(tr.height)]
    if cond.key == "tx.hash":
        return [tr.hash().hex().upper()]
    out = []
    for ev in tr.result.get("events", []):
        for attr in ev.get("attributes", []):
            if f"{ev.get('type')}.{attr.get('key')}" == cond.key:
                out.append(attr.get("value", ""))
    return out


def _u64(v: int) -> bytes:
    return v.to_bytes(8, "big")


def _u32(v: int) -> bytes:
    return v.to_bytes(4, "big")


class IndexerService:
    """Bridges EventBus → TxIndexer
    (reference: state/txindex/indexer_service.go)."""

    SUBSCRIBER = "tx-indexer"

    def __init__(self, indexer: TxIndexer, event_bus):
        self.indexer = indexer
        self.event_bus = event_bus

    def start(self) -> None:
        import asyncio

        self._sub = self.event_bus.subscribe(self.SUBSCRIBER,
                                             query_for_event("Tx"))
        self._task = asyncio.get_running_loop().create_task(
            self._run(), name="tx-indexer")

    def stop(self) -> None:
        self.event_bus.unsubscribe_all(self.SUBSCRIBER)
        if getattr(self, "_task", None) is not None:
            self._task.cancel()

    async def _run(self) -> None:
        import asyncio

        while True:
            try:
                msg = await self._sub.next()
            except asyncio.CancelledError:
                return
            data = msg.data
            if isinstance(data, EventDataTx):
                try:
                    self.indexer.index(TxResult(data.height, data.index,
                                                data.tx, data.result))
                except Exception:
                    logger.exception("failed to index tx at height %d",
                                     data.height)
